#!/bin/sh
# CLI end-to-end on agaricus (reference demo/binary_classification/runexp.sh)
set -e
cd "$(dirname "$0")"
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
export PYTHONPATH="$(cd ../.. && pwd)${PYTHONPATH:+:$PYTHONPATH}"
python -m xgboost_tpu mushroom.conf model_out=./0002.model
python -m xgboost_tpu mushroom.conf task=pred model_in=./0002.model name_pred=pred.txt
python -m xgboost_tpu mushroom.conf task=dump model_in=./0002.model name_dump=dump.raw.txt
head -3 dump.raw.txt
rm -f 0002.model pred.txt dump.raw.txt
echo "runexp ok"

"""Kaggle Otto demo (reference demo/kaggle-otto/otto_train_pred.R).

The reference demo is R-only (9-class product classification,
multi:softprob + 3-fold CV + probability-matrix submission); the same
flow here through the Python API on a deterministic stand-in with the
competition's shape (93 count features, 9 classes).  The R counterpart
for this framework lives in ``R-package/demo/``.
"""
import numpy as np

import xgboost_tpu as xgb

rng = np.random.RandomState(9)
n, n_feat, n_class = 6000, 93, 9
centers = rng.poisson(1.0, size=(n_class, n_feat))
y = rng.randint(0, n_class, size=n)
X = rng.poisson(centers[y] + 0.5).astype(np.float32)

cut = int(n * 0.8)
dtrain = xgb.DMatrix(X[:cut], label=y[:cut])
dtest = xgb.DMatrix(X[cut:])

param = {"objective": "multi:softprob", "eval_metric": "mlogloss",
         "num_class": n_class, "max_depth": 6, "eta": 0.3}

# cross-validate first (the R demo's xgb.cv step)
print("running cross validation")
xgb.cv(param, dtrain, num_boost_round=5, nfold=3)

# train and write a submission-style probability matrix
bst = xgb.train(param, dtrain, 5, verbose_eval=False)
pred = np.asarray(bst.predict(dtest))
assert pred.shape == (n - cut, n_class)
with open("otto.submission.csv", "w") as fo:
    fo.write("id," + ",".join("Class_%d" % (c + 1)
                              for c in range(n_class)) + "\n")
    for i, row in enumerate(pred):
        fo.write("%d," % (i + 1)
                 + ",".join("%.2f" % p for p in row) + "\n")
print("otto demo ok: wrote otto.submission.csv "
      "(mlogloss-trained softprob matrix)")

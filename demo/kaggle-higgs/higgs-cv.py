"""Cross-validation on the higgs-like data (reference demo/kaggle-higgs/
higgs-cv.py): 5-fold CV with auc + ams@0.15."""
from higgs_data import synth_higgs

import xgboost_tpu as xgb

data, label, weight = synth_higgs(n=20000, seed=44)
dtrain = xgb.DMatrix(data, label=label, missing=-999.0, weight=weight)

param = {"objective": "binary:logitraw", "eta": 0.1, "max_depth": 6,
         "eval_metric": "auc"}
res = xgb.cv(param, dtrain, num_boost_round=10, nfold=5,
             metrics=("auc", "ams@0.15"), seed=0, verbose_eval=False)
for line in res:
    print(line)

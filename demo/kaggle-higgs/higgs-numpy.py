"""Kaggle Higgs demo (reference demo/kaggle-higgs/higgs-numpy.py).

The competition CSV is not bundled; a deterministic higgs-like stand-in
with the same shape (30 features, -999.0 missing sentinel, per-event
weights, ~1:2 signal/background imbalance) exercises the identical
pipeline: weighted DMatrix with ``missing=-999.0``, binary:logitraw,
scale_pos_weight from the weight ratio, auc + ams@0.15 watch metrics.
"""
from higgs_data import synth_higgs

import xgboost_tpu as xgb

test_size = 550000

data, label, weight = synth_higgs()
# rescale weight to make it same as the (hypothetical) test set
weight = weight * float(test_size) / len(label)

sum_wpos = weight[label == 1.0].sum()
sum_wneg = weight[label == 0.0].sum()
print("weight statistics: wpos=%g, wneg=%g, ratio=%g"
      % (sum_wpos, sum_wneg, sum_wneg / sum_wpos))

xgmat = xgb.DMatrix(data, label=label, missing=-999.0, weight=weight)

param = {
    "objective": "binary:logitraw",        # rank by raw margin
    "scale_pos_weight": sum_wneg / sum_wpos,
    "eta": 0.1,
    "max_depth": 6,
    "eval_metric": "auc",
}
# watch both auc and the approximate median significance at 15% threshold
plst = list(param.items()) + [("eval_metric", "ams@0.15")]

watchlist = [(xgmat, "train")]
num_round = 20  # the reference runs 120; 20 keeps the demo quick
print("loading data end, start to boost trees")
bst = xgb.train(plst, xgmat, num_round, evals=watchlist, verbose_eval=5)
bst.save_model("higgs.model")
print("finish training")

"""Speed test (reference demo/kaggle-higgs/speedtest.py: xgboost vs
sklearn GradientBoostingClassifier at matched settings — the source of
the README's "~20x faster" claim).

Compares xgboost_tpu (current JAX backend: TPU if attached, else CPU)
against sklearn's GradientBoostingClassifier on the higgs-like stand-in
at the reference's settings (depth 6, eta 0.1, 10 rounds).  Skips the
sklearn half gracefully if sklearn is unavailable.
"""
import time

import numpy as np

from higgs_data import synth_higgs

import xgboost_tpu as xgb

data, label, weight = synth_higgs(n=100000, seed=45)
test_size = 550000
weight = weight * float(test_size) / len(label)
sum_wpos = weight[label == 1.0].sum()
sum_wneg = weight[label == 0.0].sum()

num_round = 10
param = {"objective": "binary:logitraw",
         "scale_pos_weight": sum_wneg / sum_wpos,
         "eta": 0.1, "max_depth": 6, "eval_metric": "auc"}

xgmat = xgb.DMatrix(data, label=label, missing=-999.0, weight=weight)
# warm-up round compiles the kernels; the timed run measures steady state
xgb.train(param, xgmat, 1, verbose_eval=False)
tstart = time.time()
bst = xgb.train(param, xgmat, num_round,
                evals=[(xgmat, "train")], verbose_eval=False)
import jax  # noqa: E402 (after the timed section setup)
print("xgboost_tpu (%s): %g s for %d rounds"
      % (jax.default_backend(), time.time() - tstart, num_round))

try:
    from sklearn.ensemble import GradientBoostingClassifier
except ImportError:
    print("sklearn not installed; skipping the comparison half")
else:
    data0 = np.where(data == -999.0, 0.0, data)  # sklearn has no missing
    tstart = time.time()
    gbm = GradientBoostingClassifier(n_estimators=num_round,
                                     max_depth=6, verbose=2)
    gbm.fit(data0, label)
    print("sklearn.GradientBoostingClassifier: %g s for %d rounds"
          % (time.time() - tstart, num_round))

"""Shared higgs-like synthetic stand-in for the unbundled competition
CSV: 30 features, -999.0 missing sentinel, per-event weights,
imbalanced signal/background — same shape as the reference demo's data
pipeline expects."""
import numpy as np


def synth_higgs(n=50000, f=30, seed=42):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    margin = X[:, 0] + 0.8 * X[:, 1] * X[:, 2] - 0.5 * X[:, 3] ** 2 + 1.0
    y = (margin + rng.randn(n) > 0.8).astype(np.float32)
    # detector-style missingness: -999.0 sentinel on a feature block
    mask = rng.rand(n, f) < 0.1
    X[mask] = -999.0
    w = rng.gamma(2.0, 1.0, size=n).astype(np.float32)
    return X, y, w

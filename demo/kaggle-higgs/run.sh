#!/bin/sh
# Higgs demo driver (reference demo/kaggle-higgs/run.sh: train then pred)
set -e
cd "$(dirname "$0")"
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
export PYTHONPATH="$(cd ../.. && pwd)${PYTHONPATH:+:$PYTHONPATH}"
python higgs-numpy.py
python higgs-pred.py
head -3 higgs.submission.csv
rm -f higgs.model higgs.submission.csv
echo "higgs demo ok"

"""Prediction + submission formatting (reference demo/kaggle-higgs/
higgs-pred.py): load the saved model, rank events by raw margin, label
the top 15% as signal, write a submission-style CSV."""
import numpy as np

from higgs_data import synth_higgs

import xgboost_tpu as xgb

# make top 15% as signal
threshold_ratio = 0.15

data, label, weight = synth_higgs(n=20000, seed=43)
xgmat = xgb.DMatrix(data, missing=-999.0)
bst = xgb.Booster(model_file="higgs.model")
ypred = np.asarray(bst.predict(xgmat, output_margin=True))

res = [(i, ypred[i]) for i in range(len(ypred))]
rorder = {}
for k, v in sorted(res, key=lambda x: -x[1]):
    rorder[k] = len(rorder) + 1

ntop = int(threshold_ratio * len(rorder))
with open("higgs.submission.csv", "w") as fo:
    fo.write("EventId,RankOrder,Class\n")
    nhit = 0
    for k, v in res:
        cls = "s" if rorder[k] <= ntop else "b"
        if cls == "s":
            nhit += 1
        fo.write("%s,%d,%s\n" % (k, len(rorder) + 1 - rorder[k], cls))
print("finished writing into prediction file (%d signal)" % nhit)

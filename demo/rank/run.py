"""Learning-to-rank demo (reference demo/rank/: LambdaMART on MQ2008):
rank:pairwise with group information and NDCG evaluation."""
import numpy as np

import xgboost_tpu as xgb

rng = np.random.RandomState(11)
w = rng.randn(46)


def make_groups(n_groups):
    rows, labels, sizes = [], [], []
    for _ in range(n_groups):
        g = rng.randint(8, 25)
        Xg = rng.randn(g, 46).astype(np.float32)
        score = Xg @ w + 1.5 * rng.randn(g)
        rel = np.zeros(g)
        order = np.argsort(-score)
        rel[order[: max(1, g // 6)]] = 2
        rel[order[max(1, g // 6): max(2, g // 3)]] = 1
        rows.append(Xg); labels.append(rel); sizes.append(g)
    return np.concatenate(rows), np.concatenate(labels), sizes


Xtr, ytr, gtr = make_groups(300)
Xte, yte, gte = make_groups(100)
dtrain = xgb.DMatrix(Xtr, label=ytr)
dtrain.set_group(gtr)
dtest = xgb.DMatrix(Xte, label=yte)
dtest.set_group(gte)
params = {"objective": "rank:pairwise", "eta": 0.1, "max_depth": 6,
          "eval_metric": "ndcg"}
bst = xgb.train(params, dtrain, 4,
                evals=[(dtrain, "train"), (dtest, "test")])
print("rank demo ok")

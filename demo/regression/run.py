"""Regression demo (reference demo/regression/): reg:linear on a
synthetic machine-performance-like dataset, CLI-config style params."""
import numpy as np

import xgboost_tpu as xgb

rng = np.random.RandomState(1)
X = rng.rand(2000, 12).astype(np.float32)
y = (3 * X[:, 0] - 2 * X[:, 1] * X[:, 2] + 0.5 * rng.randn(2000)).astype(
    np.float32)
dtrain = xgb.DMatrix(X[:1500], label=y[:1500])
dtest = xgb.DMatrix(X[1500:], label=y[1500:])
params = {"objective": "reg:linear", "eta": 0.3, "max_depth": 4,
          "eval_metric": "rmse"}
bst = xgb.train(params, dtrain, 30,
                evals=[(dtrain, "train"), (dtest, "test")],
                verbose_eval=10)
print("regression demo ok")

#!/bin/sh
# YearPredictionMSD experiment (reference demo/yearpredMSD/runexp.sh):
# make libsvm data, train via the CLI config.
set -e
cd "$(dirname "$0")"
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
export PYTHONPATH="$(cd ../.. && pwd)${PYTHONPATH:+:$PYTHONPATH}"
if [ ! -f yearpredMSD.libsvm.train ]; then
    echo "making synthetic yearpredMSD data (UCI download unavailable offline)"
    python make_data.py
fi
python -m xgboost_tpu yearpredMSD.conf model_out=NONE
rm -f yearpredMSD.libsvm.train yearpredMSD.libsvm.test
echo "yearpredMSD demo ok"

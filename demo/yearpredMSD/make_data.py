"""Generate a YearPredictionMSD-like libsvm train/test pair (the UCI
download of the reference's runexp.sh is unavailable offline): 90 audio
timbre features, year labels 1922-2011 correlated with the features."""
import numpy as np

rng = np.random.RandomState(11)
n, f = 8000, 90
X = rng.randn(n, f).astype(np.float32)
year = np.clip(
    1998 + 6 * X[:, 0] - 4 * X[:, 1] + 2 * X[:, 2] * X[:, 3]
    + 3 * rng.randn(n), 1922, 2011).round()


def write(path, Xs, ys):
    with open(path, "w") as fo:
        for row, label in zip(Xs, ys):
            feats = " ".join("%d:%.4f" % (j, v) for j, v in enumerate(row))
            fo.write("%d %s\n" % (label, feats))


cut = int(n * 0.9)  # the reference splits head/tail of one file
write("yearpredMSD.libsvm.train", X[:cut], year[:cut])
write("yearpredMSD.libsvm.test", X[cut:], year[cut:])
print("wrote yearpredMSD.libsvm.{train,test}")

"""Multiclass softmax demo (reference demo/multiclass_classification/
train.py: dermatology, 6 classes): both multi:softmax (class ids) and
multi:softprob (probability matrix)."""
import numpy as np

import xgboost_tpu as xgb

rng = np.random.RandomState(7)
n, n_class = 2000, 6
centers = rng.randint(0, 4, size=(n_class, 34))
y = rng.randint(0, n_class, size=n)
X = np.clip(centers[y] + rng.randint(-1, 2, size=(n, 34)), 0, 3).astype(
    np.float32)
cut = int(n * 0.7)
dtrain = xgb.DMatrix(X[:cut], label=y[:cut])
dtest = xgb.DMatrix(X[cut:], label=y[cut:])
params = {"objective": "multi:softmax", "num_class": n_class,
          "max_depth": 6, "eta": 0.1}
bst = xgb.train(params, dtrain, 5,
                evals=[(dtrain, "train"), (dtest, "test")])
pred = np.asarray(bst.predict(dtest))
print("softmax test merror:", float(np.mean(pred != y[cut:])))

params["objective"] = "multi:softprob"
bst2 = xgb.train(params, dtrain, 5, verbose_eval=False)
prob = np.asarray(bst2.predict(dtest))
print("softprob shape:", prob.shape,
      "merror:", float(np.mean(prob.argmax(axis=1) != y[cut:])))
print("multiclass demo ok")

"""Per-tree leaf index prediction (reference predict_leaf_indices.py)."""
import os

import xgboost_tpu as xgb

DATA = os.environ.get("XGBTPU_DEMO_DATA", "/root/reference/demo/data")
dtrain = xgb.DMatrix(f"{DATA}/agaricus.txt.train")
dtest = xgb.DMatrix(f"{DATA}/agaricus.txt.test", num_col=dtrain.num_col)
bst = xgb.train({"max_depth": 2, "eta": 1,
                 "objective": "binary:logistic"}, dtrain, 3)
leaves = bst.predict(dtest, pred_leaf=True)
print("leaf index shape:", leaves.shape)
print(leaves[:5])
print("predict_leaf_indices ok")

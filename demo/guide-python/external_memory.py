"""Out-of-core training from a paged matrix (reference external_memory.py:
the #cachefile convention)."""
import os
import tempfile

import xgboost_tpu as xgb
from xgboost_tpu.external import ExtMemDMatrix

DATA = os.environ.get("XGBTPU_DEMO_DATA", "/root/reference/demo/data")
with tempfile.TemporaryDirectory() as d:
    dtrain = ExtMemDMatrix(f"{DATA}/agaricus.txt.train",
                           cache=f"{d}/dtrain.cache")
    param = {"max_depth": 2, "eta": 1, "objective": "binary:logistic"}
    bst = xgb.train(param, dtrain, 2, evals=[(dtrain, "train")])
print("external_memory ok")

"""Predict with only the first N trees (reference predict_first_ntree.py)."""
import os

import numpy as np

import xgboost_tpu as xgb

DATA = os.environ.get("XGBTPU_DEMO_DATA", "/root/reference/demo/data")
dtrain = xgb.DMatrix(f"{DATA}/agaricus.txt.train")
dtest = xgb.DMatrix(f"{DATA}/agaricus.txt.test", num_col=dtrain.num_col)
param = {"max_depth": 2, "eta": 1, "objective": "binary:logistic"}
bst = xgb.train(param, dtrain, 3, evals=[(dtest, "eval")])
label = dtest.get_label()
p1 = bst.predict(dtest, ntree_limit=1)
pall = bst.predict(dtest)
print("error of ntree=1:", float(np.mean((np.asarray(p1) > 0.5) != label)))
print("error of all trees:",
      float(np.mean((np.asarray(pall) > 0.5) != label)))
print("predict_first_ntree ok")

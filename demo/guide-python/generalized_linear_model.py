"""GBLinear booster (reference generalized_linear_model.py)."""
import os

import xgboost_tpu as xgb

DATA = os.environ.get("XGBTPU_DEMO_DATA", "/root/reference/demo/data")
dtrain = xgb.DMatrix(f"{DATA}/agaricus.txt.train")
dtest = xgb.DMatrix(f"{DATA}/agaricus.txt.test", num_col=dtrain.num_col)
param = {"booster": "gblinear", "objective": "binary:logistic",
         "alpha": 0.0001, "lambda": 1}
bst = xgb.train(param, dtrain, 4, evals=[(dtest, "eval"), (dtrain, "train")])
print("generalized_linear_model ok")

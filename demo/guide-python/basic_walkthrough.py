"""Basic walkthrough (reference demo/guide-python/basic_walkthrough.py):
DMatrix from libsvm file / numpy / scipy, train with a watchlist,
predict, save/load models and binary DMatrix caches."""
import os
import tempfile

import numpy as np

import xgboost_tpu as xgb

DATA = os.environ.get("XGBTPU_DEMO_DATA",
                      "/root/reference/demo/data")

dtrain = xgb.DMatrix(f"{DATA}/agaricus.txt.train")
dtest = xgb.DMatrix(f"{DATA}/agaricus.txt.test", num_col=dtrain.num_col)

param = {"max_depth": 2, "eta": 1, "objective": "binary:logistic"}
watchlist = [(dtest, "eval"), (dtrain, "train")]
bst = xgb.train(param, dtrain, num_boost_round=2, evals=watchlist)

preds = bst.predict(dtest)
labels = dtest.get_label()
err = sum(1 for i in range(len(preds))
          if int(preds[i] > 0.5) != labels[i]) / float(len(preds))
print(f"error={err:.6f}")

with tempfile.TemporaryDirectory() as d:
    # model save/load
    bst.save_model(f"{d}/0001.model")
    bst2 = xgb.Booster(model_file=f"{d}/0001.model")
    assert np.allclose(np.asarray(bst2.predict(dtest)), np.asarray(preds))
    # text dump with feature map
    bst.dump_model(f"{d}/dump.raw.txt")
    # binary DMatrix cache
    dtest.save_binary(f"{d}/dtest.buffer")
    dtest2 = xgb.DMatrix(f"{d}/dtest.buffer")
    assert np.allclose(np.asarray(bst.predict(dtest2)), np.asarray(preds))

# numpy interface
rng = np.random.RandomState(1994)
data = rng.randn(100, 10).astype(np.float32)
label = rng.randint(2, size=100).astype(np.float32)
dtrain_np = xgb.DMatrix(data, label=label)
xgb.train(param, dtrain_np, 2)
print("basic_walkthrough ok")

"""sklearn-style estimator API (reference sklearn_examples.py)."""
import numpy as np

from xgboost_tpu.sklearn import XGBClassifier, XGBRegressor

rng = np.random.RandomState(1994)
X = rng.rand(200, 10).astype(np.float32)
y = (X[:, 0] + X[:, 1] > 1.0).astype(int)
clf = XGBClassifier(n_estimators=4, max_depth=3).fit(X, y)
print("classifier acc:", float((clf.predict(X) == y).mean()))

yr = X[:, 0] * 2 + rng.randn(200) * 0.1
reg = XGBRegressor(n_estimators=4, max_depth=3).fit(X, yr)
print("regressor mse:", float(((reg.predict(X) - yr) ** 2).mean()))
print("sklearn_examples ok")

"""k-fold cross validation (reference cross_validation.py)."""
import os

import xgboost_tpu as xgb

DATA = os.environ.get("XGBTPU_DEMO_DATA", "/root/reference/demo/data")
dtrain = xgb.DMatrix(f"{DATA}/agaricus.txt.train")
param = {"max_depth": 2, "eta": 1, "objective": "binary:logistic"}
for line in xgb.cv(param, dtrain, num_boost_round=3, nfold=5,
                   metrics=["error"], seed=0):
    print(line)
print("cross_validation ok")

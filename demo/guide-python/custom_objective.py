"""Custom objective + custom metric (reference custom_objective.py):
user-supplied grad/hess through Booster.boost and feval."""
import os

import numpy as np

import xgboost_tpu as xgb

DATA = os.environ.get("XGBTPU_DEMO_DATA", "/root/reference/demo/data")
dtrain = xgb.DMatrix(f"{DATA}/agaricus.txt.train")
dtest = xgb.DMatrix(f"{DATA}/agaricus.txt.test", num_col=dtrain.num_col)
param = {"max_depth": 2, "eta": 1}


def logregobj(preds, dtrain):
    labels = dtrain.get_label()
    preds = 1.0 / (1.0 + np.exp(-preds))
    grad = preds - labels
    hess = preds * (1.0 - preds)
    return grad, hess


def evalerror(preds, dtrain):
    labels = dtrain.get_label()
    return "error", float(np.mean((preds > 0.0) != labels))


bst = xgb.train(param, dtrain, 2, evals=[(dtest, "eval"), (dtrain, "train")],
                obj=logregobj, feval=evalerror)
print("custom_objective ok")

#!/bin/sh
# run every walkthrough (reference demo/guide-python/runall.sh)
set -e
cd "$(dirname "$0")"
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
export PYTHONPATH="$(cd ../.. && pwd)${PYTHONPATH:+:$PYTHONPATH}"
for f in basic_walkthrough custom_objective boost_from_prediction \
         cross_validation predict_first_ntree predict_leaf_indices \
         generalized_linear_model external_memory sklearn_examples; do
  echo "== $f =="
  python "$f.py"
done

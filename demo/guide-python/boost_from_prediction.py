"""Boost from an existing prediction via base_margin (reference
boost_from_prediction.py)."""
import os

import xgboost_tpu as xgb

DATA = os.environ.get("XGBTPU_DEMO_DATA", "/root/reference/demo/data")
dtrain = xgb.DMatrix(f"{DATA}/agaricus.txt.train")
dtest = xgb.DMatrix(f"{DATA}/agaricus.txt.test", num_col=dtrain.num_col)
param = {"max_depth": 2, "eta": 1, "objective": "binary:logistic"}
watchlist = [(dtest, "eval"), (dtrain, "train")]

bst = xgb.train(param, dtrain, 1, evals=watchlist)
# margin (not transformed probability) seeds the continued model
ptrain = bst.predict(dtrain, output_margin=True)
ptest = bst.predict(dtest, output_margin=True)
dtrain.set_base_margin(ptrain)
dtest.set_base_margin(ptest)
print("this is result of running from initial prediction")
bst2 = xgb.train(param, dtrain, 1, evals=watchlist)
print("boost_from_prediction ok")

"""Scikit-learn estimator API.

Mirrors the reference's sklearn surface (``wrapper/xgboost.py:748-846``:
``XGBModel`` / ``XGBClassifier`` / ``XGBRegressor``) with the richer
hyperparameter set the rest of this framework exposes.  sklearn itself
is optional — the estimators degrade to plain objects (with a built-in
label encoder) when it is absent, like the reference's
``SKLEARN_INSTALLED`` guard.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

try:
    from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
    from sklearn.preprocessing import LabelEncoder
    SKLEARN_INSTALLED = True
except ImportError:  # degrade gracefully (reference XGBModelBase = object)
    SKLEARN_INSTALLED = False
    BaseEstimator = object

    class ClassifierMixin:  # type: ignore[no-redef]
        pass

    class RegressorMixin:  # type: ignore[no-redef]
        pass

    class LabelEncoder:  # type: ignore[no-redef]
        def fit(self, y):
            self.classes_ = np.unique(y)
            return self

        def transform(self, y):
            idx = np.searchsorted(self.classes_, y)
            idx_clip = np.clip(idx, 0, len(self.classes_) - 1)
            if np.any(self.classes_[idx_clip] != np.asarray(y)):
                raise ValueError("y contains previously unseen labels")
            return idx

        def inverse_transform(self, idx):
            return self.classes_[np.asarray(idx, dtype=np.int64)]

from xgboost_tpu.data import DMatrix
from xgboost_tpu.learner import Booster, train


class XGBModel(BaseEstimator):
    """Base estimator (reference XGBModel, wrapper/xgboost.py:748-795)."""

    def __init__(self, max_depth=3, learning_rate=0.1, n_estimators=100,
                 silent=True, objective="reg:linear", booster="gbtree",
                 gamma=0.0, min_child_weight=1.0, max_delta_step=0.0,
                 subsample=1.0, colsample_bytree=1.0, colsample_bylevel=1.0,
                 reg_alpha=0.0, reg_lambda=1.0, scale_pos_weight=1.0,
                 base_score=0.5, seed=0, max_bin=256, missing=np.nan):
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.silent = silent
        self.objective = objective
        self.booster = booster
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.max_delta_step = max_delta_step
        self.subsample = subsample
        self.colsample_bytree = colsample_bytree
        self.colsample_bylevel = colsample_bylevel
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.scale_pos_weight = scale_pos_weight
        self.base_score = base_score
        self.seed = seed
        self.max_bin = max_bin
        self.missing = missing
        self._Booster: Optional[Booster] = None

    # -- sklearn protocol ------------------------------------------------
    _PARAM_NAMES = ("max_depth", "learning_rate", "n_estimators", "silent",
                    "objective", "booster", "gamma", "min_child_weight",
                    "max_delta_step", "subsample", "colsample_bytree",
                    "colsample_bylevel", "reg_alpha", "reg_lambda",
                    "scale_pos_weight", "base_score", "seed", "max_bin",
                    "missing")

    def get_params(self, deep=True):
        return {k: getattr(self, k) for k in self._PARAM_NAMES}

    def set_params(self, **params):
        for k, v in params.items():
            if k not in self._PARAM_NAMES:
                raise ValueError(f"invalid parameter {k!r}")
            setattr(self, k, v)
        return self

    def get_xgb_params(self) -> dict:
        """Estimator params -> booster param dict (reference
        get_xgb_params, wrapper/xgboost.py:780-785)."""
        p = {k: getattr(self, k) for k in self._PARAM_NAMES
             if k not in ("learning_rate", "n_estimators", "silent",
                          "missing")}
        p["eta"] = self.learning_rate
        p["silent"] = 1 if self.silent else 0
        return p

    def get_booster(self) -> Booster:
        if self._Booster is None:
            raise ValueError("need to call fit beforehand")
        return self._Booster

    # -- fit/predict -----------------------------------------------------
    def _dmatrix(self, X, y=None, sample_weight=None) -> DMatrix:
        return DMatrix(X, label=y, weight=sample_weight,
                       missing=self.missing)

    def _predict_data(self, X):
        """Prediction input: Booster.predict auto-wraps plain arrays
        (the single wrapping implementation, shared with the serving
        engine); only a non-NaN missing marker or a sparse input still
        needs the explicit DMatrix wrap here."""
        if hasattr(X, "num_row"):  # already a DMatrix flavor
            return X
        if isinstance(X, np.ndarray) and (
                self.missing is None or np.isnan(self.missing)):
            return X
        return self._dmatrix(X)

    def _encode_labels(self, y):
        """Hook: (train labels, extra params, eval-label transform)."""
        return y, {}, lambda ey: ey

    def fit(self, X, y, sample_weight=None, eval_set=None,
            early_stopping_rounds=None, verbose=False):
        if early_stopping_rounds is not None and not eval_set:
            raise ValueError(
                "For early stopping you need at least one set in eval_set")
        # drop stale early-stopping state from a previous fit
        for attr in ("best_score_", "best_iteration_"):
            if hasattr(self, attr):
                delattr(self, attr)
        labels, extra_params, trans = self._encode_labels(y)
        params = {**self.get_xgb_params(), **extra_params}
        dtrain = self._dmatrix(X, labels, sample_weight)
        evals = [(self._dmatrix(ex, trans(ey)), f"validation_{i}")
                 for i, (ex, ey) in enumerate(eval_set or [])]
        self.evals_result_ = {}
        self._Booster = train(
            params, dtrain, self.n_estimators, evals=evals,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self.evals_result_, verbose_eval=verbose)
        if early_stopping_rounds is not None:
            self.best_score_ = self._Booster.best_score
            self.best_iteration_ = self._Booster.best_iteration
        return self

    def predict(self, X):
        return self.get_booster().predict(self._predict_data(X))

    def apply(self, X):
        """Leaf index per (row, tree) (Booster.predict pred_leaf)."""
        return self.get_booster().predict(self._predict_data(X),
                                          pred_leaf=True)

    @property
    def feature_importances_(self) -> np.ndarray:
        if self.booster == "gblinear":
            raise AttributeError(
                "feature_importances_ is not supported for booster=gblinear")
        booster = self.get_booster()
        fscore = booster.get_fscore()
        n = booster.num_feature
        out = np.zeros(n, dtype=np.float32)
        for name, count in fscore.items():
            out[int(name[1:])] = count
        total = out.sum()
        return out / total if total > 0 else out


class XGBRegressor(XGBModel, RegressorMixin):
    """(reference XGBRegressor, wrapper/xgboost.py:846)"""


class XGBClassifier(XGBModel, ClassifierMixin):
    """(reference XGBClassifier, wrapper/xgboost.py:798-843)"""

    def __init__(self, max_depth=3, learning_rate=0.1, n_estimators=100,
                 silent=True, objective="binary:logistic", **kwargs):
        super().__init__(max_depth=max_depth, learning_rate=learning_rate,
                         n_estimators=n_estimators, silent=silent,
                         objective=objective, **kwargs)

    def _encode_labels(self, y):
        self._le = LabelEncoder().fit(y)
        self.classes_ = self._le.classes_
        self.n_classes_ = len(self.classes_)
        extra = {}
        if self.n_classes_ > 2:
            # multiclass switch (reference wrapper/xgboost.py:803-808) —
            # applied per-fit, never mutating self.objective, so a later
            # binary fit or sklearn clone() is unaffected
            extra = {"objective": "multi:softprob",
                     "num_class": self.n_classes_}
        return self._le.transform(y), extra, self._le.transform

    def predict(self, X):
        probs = self.predict_proba(X)
        return self._le.inverse_transform(np.argmax(probs, axis=1))

    def predict_proba(self, X):
        raw = self.get_booster().predict(self._predict_data(X))
        if raw.ndim > 1:  # multi:softprob
            return raw
        return np.vstack([1.0 - raw, raw]).T

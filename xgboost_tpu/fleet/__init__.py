"""xgboost_tpu.fleet — replica pool + routing front door for serving.

The distributed-serving tier (SERVING.md fleet section; ROADMAP
"millions-of-users" item): where ``xgboost_tpu.serving`` is ONE
process, this package is the shared-nothing FLEET of them —

- :class:`Membership` / :class:`LeaseClient`
  (:mod:`~xgboost_tpu.fleet.membership`): replica registration with
  heartbeat leases and health checking — the serving-side analog of
  the reference's tracker/rendezvous tier (``tracker/rabit_tracker.py``
  assigns ranks, brokers membership, accepts ``recover`` from
  restarted workers; SURVEY.md L0);
- :class:`FleetRouter` (:mod:`~xgboost_tpu.fleet.router`): one HTTP
  front door speaking the replica API — least-loaded dispatch for
  ``/predict``, consistent-hash-on-entity-id dispatch for
  ``/predict_by_id`` (feature-store residency concentrates per
  replica), per-replica circuit breakers, retry-once on a different
  healthy replica, and a global in-flight budget with 503 load
  shedding;
- :class:`RolloutController` (:mod:`~xgboost_tpu.fleet.rollout`):
  staged canary model rollout driven by ModelRegistry content hashes,
  gated on the canaries' own ``/metrics``, with one-command instant
  fleet rollback.

Quickstart::

    python tools/launch_fleet.py --model m.bin --replicas 3

or by hand: ``python -m xgboost_tpu task=fleet_router fleet_port=8000``
plus N replicas started with ``task=serve
serve_router_url=http://127.0.0.1:8000``.
"""

from xgboost_tpu.fleet.membership import (HashRing, LeaseClient,
                                          Membership, Replica)
from xgboost_tpu.fleet.router import FleetRouter, run_router
from xgboost_tpu.fleet.rollout import (RolloutController,
                                       scrape_labeled_samples,
                                       scrape_samples)

__all__ = [
    "Membership",
    "Replica",
    "HashRing",
    "LeaseClient",
    "FleetRouter",
    "run_router",
    "RolloutController",
    "scrape_samples",
    "scrape_labeled_samples",
]

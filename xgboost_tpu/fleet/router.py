"""Fleet router: the HTTP front door over a pool of serving replicas.

The scale step past one `PredictServer` process (SERVING.md fleet
section): N shared-nothing replicas register with this router
(fleet/membership.py, the tracker analog) and clients talk to ONE
endpoint that speaks the same API the replicas do:

- ``POST /predict`` — **least-loaded** dispatch (fewest outstanding
  router requests) over in-rotation replicas; a failed dispatch
  (connect error / 5xx / replica draining) is retried ONCE on a
  different healthy replica — predictions are idempotent, so the retry
  is safe and a rolling restart or replica kill costs zero client
  failures.
- ``POST /predict_by_id`` / ``POST /featurestore/put`` /
  ``/featurestore/invalidate`` — **consistent-hash** dispatch on
  entity id (fleet/membership.py HashRing): an entity's feature row is
  ``put`` to, and served from, the same replica across requests, so
  device-resident feature-store residency CONCENTRATES per replica
  instead of diluting N ways.  Requests spanning owners are split and
  the responses merged in input order.
- **admission control** — a global in-flight budget
  (``fleet_inflight``); requests past it are shed with 503 before any
  replica work (``xgbtpu_fleet_shed_total``), the router-level
  reject-don't-buffer stance.
- **circuit breakers** — per replica, consecutive-failure trip with a
  half-open probe after cooldown (state machine in
  fleet/membership.py; ``xgbtpu_fleet_breaker_*``).
- **tracing** — the client's ``X-Request-Id`` (or a generated one)
  becomes the trace id of a ``router.request`` span AND is forwarded
  to the replica, whose ``serve.request`` span lands under the same
  trace: one id correlates client log, router timeline, and replica
  timeline.

Admin surface: ``/fleet/register|heartbeat|deregister`` (the replica
protocol), ``GET /fleet/members``, ``POST /fleet/rollout`` /
``/fleet/rollback`` (fleet/rollout.py), ``GET /healthz``,
``GET /metrics``.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from xgboost_tpu.obs import span, trace, trace_context
from xgboost_tpu.obs.metrics import fleet_metrics
from xgboost_tpu.obs.server import PROM_CONTENT_TYPE
from xgboost_tpu.fleet.membership import Membership, Replica
from xgboost_tpu.reliability.deadline import (DEADLINE_HEADER, Deadline,
                                              DeadlineExceeded,
                                              backoff_delay, jittered)


class ForwardError(RuntimeError):
    """A dispatch to one replica failed (connect/read error or a
    retryable status); carries the replica id for breaker accounting."""

    def __init__(self, replica_id: str, detail: str,
                 status: Optional[int] = None):
        super().__init__(f"replica {replica_id}: {detail}")
        self.replica_id = replica_id
        self.status = status


class _ConnPool:
    """Tiny keep-alive connection pool, keyed by replica base URL.
    Idle connections are reused (loopback TCP connect costs more than
    the forward itself at fleet request rates); errored connections are
    closed, never returned."""

    def __init__(self, timeout: float = 30.0, max_idle: int = 8):
        self.timeout = float(timeout)
        self.max_idle = int(max_idle)
        self._idle: Dict[str, List[http.client.HTTPConnection]] = {}
        self._lock = threading.Lock()

    def acquire(self, url: str) -> http.client.HTTPConnection:
        with self._lock:
            conns = self._idle.get(url)
            if conns:
                return conns.pop()
        p = urlparse(url)
        return http.client.HTTPConnection(p.hostname, p.port,
                                          timeout=self.timeout)

    def release(self, url: str, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            conns = self._idle.setdefault(url, [])
            if len(conns) < self.max_idle:
                conns.append(conn)
                return
        conn.close()

    def prune(self, live_urls) -> None:
        """Close idle connections to URLs no longer registered —
        replicas bind ephemeral ports, so every restart is a NEW url
        and the old one's sockets would otherwise accumulate forever
        (fd exhaustion under long replica churn)."""
        with self._lock:
            dead = [u for u in self._idle if u not in live_urls]
            conns = [c for u in dead for c in self._idle.pop(u)]
        for c in conns:
            c.close()

    def close(self) -> None:
        with self._lock:
            conns = [c for lst in self._idle.values() for c in lst]
            self._idle.clear()
        for c in conns:
            c.close()


# response headers worth passing through from a replica (hop-by-hop
# headers like Connection/Keep-Alive must NOT cross the proxy)
_PASS_HEADERS = ("Content-Type",)


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # same Nagle/delayed-ACK stall fix as the replica handler
    # (serving/http.py): without it every hop adds a flat ~40 ms
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):
        if not self.server.quiet:
            super().log_message(fmt, *args)

    # --------------------------------------------------------------- util
    def _send(self, code: int, body: bytes,
              content_type: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        rid = getattr(self, "_request_id", None)
        if rid is not None:
            self.send_header("X-Request-Id", rid)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj).encode())

    def _read_body(self) -> Optional[bytes]:
        """Drain the request body — THE shared keep-alive hygiene
        (serving/http.py read_request_body); None = an error response
        was already sent."""
        from xgboost_tpu.serving.http import read_request_body
        return read_request_body(self, self.server.router.max_body_bytes)

    # ---------------------------------------------------------------- GET
    def do_GET(self):
        self._request_id = None
        rt: FleetRouter = self.server.router
        url = urlparse(self.path)
        if url.path == "/healthz":
            self._send_json(200, rt.health())
            return
        if url.path == "/metrics":
            from xgboost_tpu.obs.metrics import registry
            self._send(200, registry().render().encode(),
                       PROM_CONTENT_TYPE)
            return
        if url.path == "/fleet/members":
            self._send_json(200, rt.membership.describe())
            return
        if url.path == "/fleet/rollout":
            self._send_json(200, rt.rollout_status())
            return
        if url.path == "/placer/status":
            self._send_json(200, rt.placer_status())
            return
        self._send_json(404, {"error": f"no route {url.path}"})

    # --------------------------------------------------------------- POST
    def do_POST(self):
        self._request_id = None
        rt: FleetRouter = self.server.router
        url = urlparse(self.path)
        body = self._read_body()
        if body is None:
            return
        if url.path == "/predict":
            self._proxy_predict(url, body)
            return
        if url.path in ("/predict_by_id", "/featurestore/put",
                        "/featurestore/invalidate"):
            self._proxy_by_id(url, body)
            return
        if url.path == "/fleet/register":
            self._fleet_register(body)
            return
        if url.path == "/fleet/heartbeat":
            self._fleet_heartbeat(body)
            return
        if url.path == "/fleet/deregister":
            self._fleet_deregister(body)
            return
        if url.path == "/fleet/rollout":
            self._fleet_rollout(body)
            return
        if url.path == "/fleet/rollback":
            self._fleet_rollback(body)
            return
        if url.path == "/placer/lease":
            self._placer_lease(body)
            return
        if url.path == "/placer/plan":
            self._placer_plan(body)
            return
        self._send_json(404, {"error": f"no route {url.path}"})

    # ----------------------------------------------------- replica protocol
    def _fleet_register(self, body: bytes) -> None:
        try:
            req = json.loads(body)
            rid, rurl = str(req["replica_id"]), str(req["url"])
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        grant = self.server.router.membership.register(
            rid, rurl, model_path=req.get("model_path"),
            model_hash=req.get("model_hash"), pid=req.get("pid"),
            models=req.get("models"), device=req.get("device"))
        self.server.router.save_state()
        self._send_json(200, grant)

    def _fleet_heartbeat(self, body: bytes) -> None:
        try:
            req = json.loads(body)
            rid = str(req["replica_id"])
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        known = self.server.router.membership.heartbeat(
            rid, model_hash=req.get("model_hash"),
            models=req.get("models"), device=req.get("device"))
        # 200 either way: "known": false tells the client to re-register
        # (the tracker recover path) without an error-path round trip
        self._send_json(200, {"known": known})

    def _fleet_deregister(self, body: bytes) -> None:
        try:
            req = json.loads(body)
            rid = str(req["replica_id"])
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        removed = self.server.router.membership.deregister(rid)
        self.server.router.save_state()
        self._send_json(200, {"removed": removed})

    # -------------------------------------------------------------- placer
    def _placer_lease(self, body: bytes) -> None:
        try:
            req = json.loads(body)
            placer_id = str(req["placer_id"])
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        self._send_json(200, self.server.router.placer_acquire(
            placer_id, lease_sec=req.get("lease_sec")))

    def _placer_plan(self, body: bytes) -> None:
        try:
            req = json.loads(body)
            placer_id = str(req["placer_id"])
            plan = dict(req["plan"])
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        code, resp = self.server.router.placer_record_plan(
            placer_id, plan)
        self._send_json(code, resp)

    # ------------------------------------------------------------- rollout
    def _fleet_rollout(self, body: bytes) -> None:
        try:
            req = json.loads(body) if body.strip() else {}
            model_path = req["model_path"]
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        code, report = self.server.router.run_rollout(model_path, req)
        self._send_json(code, report)

    def _fleet_rollback(self, body: bytes) -> None:
        try:
            req = json.loads(body) if body.strip() else {}
        except ValueError as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        code, report = self.server.router.run_rollback(
            model=str(req.get("model", "")))
        self._send_json(code, report)

    # ------------------------------------------------------------ proxying
    def _proxy_predict(self, url, body: bytes) -> None:
        rt: FleetRouter = self.server.router
        self._proxy(url, body,
                    lambda path_qs, hdrs, sp, dl, model: rt.dispatch(
                        "POST", path_qs, body, hdrs, sp, deadline=dl,
                        model=model))

    def _proxy_by_id(self, url, body: bytes) -> None:
        rt: FleetRouter = self.server.router
        self._proxy(url, body,
                    lambda path_qs, hdrs, sp, dl, model: rt.dispatch_by_id(
                        url.path, path_qs, body, hdrs, sp, deadline=dl,
                        model=model))

    def _proxy(self, url, body: bytes, dispatch_fn) -> None:
        """THE proxy shell shared by every forwarded route: admission
        (per-tenant quota shed -> 429/503, budget shed -> 503, expired
        deadline -> 504), the router.request span under the client's
        trace id, and the error mapping (NoReplica -> 503, ForwardError
        -> 502, spent deadline -> 504, bad by-id payload -> 400).

        ``?model=`` names the tenant: requests route only to replicas
        HOSTING that catalog model, and the per-tenant quota + the
        labeled ``xgbtpu_tenant_*`` metrics key on it — one tenant's
        overload sheds as ITS 429/503s while its neighbors' traffic
        flows untouched."""
        rid = self.headers.get("X-Request-Id") or trace.new_id()
        self._request_id = rid
        rt: FleetRouter = self.server.router
        model = (parse_qs(url.query).get("model", [""])[0]
                 if url.query else "")
        tenant = model or "default"
        # the request's end-to-end budget: the client's X-Deadline-Ms,
        # or the router's fleet_deadline_ms default when configured —
        # every downstream hop SPENDS from this one object
        dl = Deadline.from_headers(self.headers)
        if dl is None and rt.deadline_ms > 0:
            dl = Deadline(rt.deadline_ms)
        if dl is not None and dl.expired():
            # reject before any dispatch: nobody is waiting for this
            from xgboost_tpu.profiling import reliability_metrics
            reliability_metrics().deadline_rejected.inc()
            self._send_json(504, {"error": "deadline expired before "
                                           "dispatch",
                                  "deadline_exceeded": True})
            return
        from xgboost_tpu.obs.metrics import tenant_metrics
        tm = tenant_metrics()
        tm.requests.inc(tenant)
        if model and not rt.membership.hosting(model):
            # no replica advertises this model: 404 (a client error)
            # when the fleet is otherwise alive, 503 when it is empty
            # (nothing can answer ANY model — same as NoReplica)
            if rt.membership.ids():
                tm.shed.inc(tenant)
                self._send_json(404, {
                    "error": f"no replica hosts model {model!r}",
                    "models": sorted(rt.membership.models_hosted())})
                return
        if rt.quotas.enabled:
            why = rt.quotas.try_admit(tenant)
            if why is not None:
                # rate -> 429 (slow down), inflight -> 503 (shed now):
                # the tenant's OWN budget said no — no global slot, no
                # replica work, no neighbor touched
                tm.shed.inc(tenant)
                self.close_connection = True
                if why == "rate":
                    self._send_json(429, {
                        "error": f"tenant {tenant!r} over rate limit",
                        "shed": True, "model": tenant})
                else:
                    self._send_json(503, {
                        "error": f"tenant {tenant!r} over in-flight "
                                 "budget", "shed": True, "model": tenant})
                return
            tm.inflight.set(tenant, rt.quotas.inflight(tenant))
        try:
            if not rt.enter_request():
                fleet_metrics().shed.inc()
                self.close_connection = True
                self._send_json(503, {"error": "router overloaded "
                                               "(in-flight budget)",
                                      "shed": True})
                return
            t_req = time.perf_counter()
            try:
                with trace_context(rid):
                    with span("router.request", request_id=rid,
                              path=url.path, model=model or None) as sp:
                        status, headers, out = dispatch_fn(
                            _path_qs(url), self._fwd_headers(rid, dl), sp,
                            dl, model)
                tm.latency_ms.inc(
                    tenant, (time.perf_counter() - t_req) * 1e3)
                self._relay(status, headers, out)
            except NoReplica:
                self._send_json(503, {"error": "no replica available"})
            except DeadlineExceeded as e:
                from xgboost_tpu.profiling import reliability_metrics
                reliability_metrics().deadline_rejected.inc()
                self._send_json(504, {"error": str(e),
                                      "deadline_exceeded": True})
            except ForwardError as e:
                self._send_json(502, {"error": str(e)})
            except ValueError as e:
                self._send_json(400, {"error": f"bad request: {e}"})
            finally:
                rt.exit_request()
        finally:
            if rt.quotas.enabled:
                rt.quotas.release(tenant)
                tm.inflight.set(tenant, rt.quotas.inflight(tenant))

    def _fwd_headers(self, rid: str, dl=None) -> Dict[str, str]:
        h = {"X-Request-Id": rid}
        if dl is not None:
            # stamp the REMAINING budget (never the original): queue
            # time at this hop is charged to the request
            h[DEADLINE_HEADER] = dl.header_value()
        ctype = self.headers.get("Content-Type")
        if ctype:
            h["Content-Type"] = ctype
        return h

    def _relay(self, status: int, headers: Dict[str, str],
               body: bytes) -> None:
        self._send(status, body,
                   headers.get("Content-Type", "application/json"))


def _path_qs(url) -> str:
    return url.path + (f"?{url.query}" if url.query else "")


class NoReplica(RuntimeError):
    """No in-rotation replica could accept the dispatch."""


class FleetRouter:
    """Membership + dispatch + admission control behind one HTTP port.

    ``port=0`` binds ephemeral (tests); the bound port is on
    ``self.port``.  :meth:`start` runs on a background thread,
    :meth:`serve_forever` blocks (SIGTERM stops the health loop and
    closes the listener — replicas keep serving direct traffic)."""

    # statuses that justify trying a different replica: the replica
    # cannot take the request (503 draining/overloaded, 502) or faulted
    # while handling it (500) — predicts are idempotent, so retrying on
    # a sibling is safe; deterministic client errors (4xx) pass through
    RETRYABLE_STATUS = (500, 502, 503)

    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 lease_sec: float = 10.0, hc_sec: float = 2.0,
                 inflight_budget: int = 256,
                 breaker_failures: int = 3,
                 breaker_cooldown_sec: float = 5.0,
                 retry: bool = True,
                 forward_timeout: float = 30.0,
                 max_body_mb: float = 64.0,
                 deadline_ms: float = 0.0,
                 slow_eject_factor: float = 3.0,
                 slow_eject_cooldown_sec: float = 5.0,
                 rollout_defaults: Optional[dict] = None,
                 state_path: str = "",
                 tenant_inflight: int = 0,
                 tenant_rate: float = 0.0,
                 tenant_burst: float = 8.0,
                 quiet: bool = True):
        from xgboost_tpu.catalog import TenantQuotas
        self.membership = Membership(
            lease_sec=lease_sec, breaker_failures=breaker_failures,
            breaker_cooldown_sec=breaker_cooldown_sec,
            slow_eject_factor=slow_eject_factor,
            slow_eject_cooldown_sec=slow_eject_cooldown_sec)
        # per-tenant quotas (?model= names the tenant): in-flight cap
        # and token-bucket rate limit, both 0 = disabled
        self.quotas = TenantQuotas(inflight_limit=tenant_inflight,
                                   rate=tenant_rate, burst=tenant_burst)
        # membership snapshot for zero-downtime restart: written
        # (CRC-footered, atomic+fsync) on register/deregister and each
        # health pass, restored — with fresh leases — on startup
        self.state_path = str(state_path)
        self.hc_sec = float(hc_sec)
        self.inflight_budget = int(inflight_budget)
        # default end-to-end budget stamped on requests that carry no
        # X-Deadline-Ms of their own (0 = none)
        self.deadline_ms = float(deadline_ms)
        self.retry = bool(retry)
        self.max_body_bytes = int(max_body_mb * (1 << 20))
        self.rollout_defaults = dict(rollout_defaults or {})
        self.quiet = quiet
        self.t0 = time.perf_counter()
        self._pool = _ConnPool(timeout=forward_timeout)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._rollout_lock = threading.Lock()
        self._rollout_state: dict = {}   # model-file backups for rollback
        self._last_rollout: dict = {"status": "none"}
        # placer single-holder lease + last recorded target plan: one
        # placer drives placement at a time; a standby that polls
        # /placer/lease takes over only after the holder's lease decays
        self._placer_lock = threading.Lock()
        self._placer_holder: Optional[str] = None
        self._placer_deadline = 0.0      # monotonic
        self._placer_lease_sec = max(float(lease_sec), 1.0)
        self._placer_plan: dict = {}
        self._stop = threading.Event()
        self._hc_thread: Optional[threading.Thread] = None
        self._httpd = ThreadingHTTPServer((host, port), _RouterHandler)
        self._httpd.daemon_threads = True
        self._httpd.router = self
        self._httpd.quiet = quiet
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None
        self._shut = False
        self._restore_state()

    # ----------------------------------------------------- state snapshot
    def save_state(self) -> None:
        """Persist the membership table (atomic, fsync'd, CRC-footered
        like every other durable artifact).  Best-effort: a full disk
        must not fail a registration."""
        if not self.state_path:
            return
        from xgboost_tpu.reliability.integrity import (add_footer,
                                                       atomic_write)
        try:
            atomic_write(
                self.state_path,
                add_footer(json.dumps(self.membership.snapshot(),
                                      sort_keys=True).encode()))
        except OSError as e:
            from xgboost_tpu.obs.metrics import swallowed_error
            swallowed_error("fleet.router.save_state", e)

    def _restore_state(self) -> None:
        """Zero-downtime restart: re-register every snapshotted replica
        with a fresh lease, so a SIGKILL'd router comes back already
        routing.  A corrupt/absent snapshot starts empty — replicas
        re-register within a heartbeat period anyway (the recover
        path); restore just removes that window."""
        if not self.state_path or not os.path.exists(self.state_path):
            return
        try:
            from xgboost_tpu.reliability.integrity import \
                verify_model_bytes
            with open(self.state_path, "rb") as f:
                payload = verify_model_bytes(f.read(), self.state_path)
            n = self.membership.restore(json.loads(payload))
            from xgboost_tpu.obs import event
            event("fleet.router.restore", replicas=n,
                  state_path=self.state_path)
            if not self.quiet:
                print(f"[fleet] restored {n} replica(s) from "
                      f"{self.state_path}", file=sys.stderr)
        except Exception as e:
            from xgboost_tpu.obs.metrics import swallowed_error
            swallowed_error("fleet.router.restore_state", e)

    # -------------------------------------------------------- admission
    def enter_request(self) -> bool:
        with self._inflight_lock:
            if self._inflight >= self.inflight_budget:
                return False
            self._inflight += 1
            fleet_metrics().inflight.set(self._inflight)
            return True

    def exit_request(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1
            fleet_metrics().inflight.set(self._inflight)

    @property
    def inflight(self) -> int:
        return self._inflight

    # --------------------------------------------------------- forwarding
    def _forward(self, rep: Replica, method: str, path_qs: str,
                 body: bytes, headers: Dict[str, str],
                 timeout: Optional[float] = None,
                 deadline: Optional[Deadline] = None
                 ) -> Tuple[int, Dict[str, str], bytes]:
        """One HTTP hop to one replica over the keep-alive pool.
        Raises :class:`ForwardError` on transport failure or a
        retryable status; other statuses (2xx/4xx) return verbatim.
        ``timeout`` overrides the pool default for THIS hop (the
        deadline path bounds each attempt by the remaining budget).

        A hop that times out because the DEADLINE shrank its window —
        the budget is spent when the timeout fires — raises
        :class:`DeadlineExceeded` instead of ForwardError: the replica
        did not fail, the request ran out of money, and charging the
        breaker would let a few tight-budget clients 503 a healthy
        replica for everyone (callers release neutrally)."""
        conn = self._pool.acquire(rep.url)
        # always (re)set: a pooled socket remembers the previous hop's
        # deadline-shortened timeout otherwise.  Applies to both a
        # fresh connect (conn.timeout is read at connect()) and a
        # pooled socket already connected.
        t = self._pool.timeout if timeout is None else timeout
        conn.timeout = t
        if conn.sock is not None:
            conn.sock.settimeout(t)
        try:
            hdrs = dict(headers)
            hdrs["Content-Length"] = str(len(body))
            conn.request(method, path_qs, body=body, headers=hdrs)
            resp = conn.getresponse()
            out = resp.read()
            status = resp.status
            will_close = resp.will_close
            keep = {k: v for k in _PASS_HEADERS
                    if (v := resp.getheader(k)) is not None}
        except Exception as e:
            conn.close()
            # socket.timeout is TimeoutError since 3.10; a connect
            # REFUSED stays a ForwardError (the breaker should see a
            # dead replica even from tight-budget traffic)
            if (deadline is not None and deadline.expired()
                    and isinstance(e, TimeoutError)):
                raise DeadlineExceeded(
                    f"budget exhausted mid-hop to {rep.replica_id}"
                ) from e
            raise ForwardError(rep.replica_id,
                               f"{type(e).__name__}: {e}") from e
        if will_close:
            # the replica announced Connection: close (drain/shed 503s
            # do) — pooling this socket would hand the NEXT dispatch a
            # dead connection and charge the miss to a healthy replica
            conn.close()
        else:
            self._pool.release(rep.url, conn)
        if status in self.RETRYABLE_STATUS:
            raise ForwardError(rep.replica_id, f"status {status}",
                               status=status)
        return status, keep, out

    def _hop_timeout(self, deadline: Optional[Deadline]
                     ) -> Optional[float]:
        """Per-attempt forward timeout: the pool default, shrunk to the
        request's remaining budget when one exists — a hop must never
        outwait the caller."""
        if deadline is None:
            return None
        return max(0.01, min(self._pool.timeout, deadline.remaining()))

    def dispatch(self, method: str, path_qs: str, body: bytes,
                 headers: Dict[str, str], sp=None,
                 deadline: Optional[Deadline] = None,
                 model: str = ""
                 ) -> Tuple[int, Dict[str, str], bytes]:
        """Route one LEAST-LOADED request (`/predict`): forward, and —
        on failure — retry ONCE on a different replica (predictions are
        idempotent), after a jittered backoff, spending the REMAINING
        deadline budget rather than arming a fresh timeout.  Breaker +
        per-replica metrics are driven from the outcomes, and each
        successful hop's latency feeds the membership's per-replica
        EWMA (the latency-ejection signal).  Entity-id routes never
        come through here: they address their ring owner single-attempt
        (:meth:`_dispatch_owner` — a put retried on the ring successor
        while the owner is merely slow would store rows where no later
        predict looks, and a by-id predict retried there answers a
        wrong 404; entity traffic fails over only when MEMBERSHIP
        changes)."""
        fm = fleet_metrics()
        t0 = time.perf_counter()
        tried: List[str] = []
        attempts = 2 if self.retry else 1
        last_err: Optional[ForwardError] = None
        try:
            for attempt in range(attempts):
                if deadline is not None and deadline.expired():
                    # the budget died with the last attempt: a retry
                    # would burn a replica on an answer nobody reads
                    if sp is not None:
                        sp.set("status", 504)
                    raise DeadlineExceeded(
                        "deadline spent after "
                        f"{attempt} attempt(s)")
                if attempt:
                    # jittered backoff before the retry (a fleet that
                    # retries in lockstep re-overloads the survivor),
                    # bounded so it never eats the remaining budget
                    time.sleep(backoff_delay(attempt, deadline=deadline))
                rep = self.membership.acquire(exclude=tried, model=model)
                if rep is None:
                    break
                tried.append(rep.replica_id)
                if attempt:
                    # counted only when a second replica was actually
                    # acquired — a 1-replica fleet's failed dispatch is
                    # not a retry
                    fm.retries.inc()
                fm.requests.inc(rep.replica_id)
                hdrs_out = dict(headers)
                if deadline is not None:
                    # restamped per attempt: the retry hop sees what is
                    # actually left, not the first hop's budget
                    hdrs_out[DEADLINE_HEADER] = deadline.header_value()
                t_hop = time.perf_counter()
                try:
                    status, hdrs, out = self._forward(
                        rep, method, path_qs, body, hdrs_out,
                        timeout=self._hop_timeout(deadline),
                        deadline=deadline)
                except DeadlineExceeded:
                    # the BUDGET cut the hop short, not the replica:
                    # neutral release (no breaker/EWMA charge), 504 out
                    self.membership.release(rep, ok=None)
                    if sp is not None:
                        sp.set("status", 504)
                    raise
                except ForwardError as e:
                    self.membership.release(rep, ok=False)
                    fm.errors.inc(rep.replica_id)
                    last_err = e
                    continue
                self.membership.release(
                    rep, ok=True,
                    latency=time.perf_counter() - t_hop)
                if sp is not None:
                    sp.set("replica", rep.replica_id)
                    sp.set("status", status)
                    if attempt:
                        sp.set("retried", attempt)
                return status, hdrs, out
            if last_err is not None:
                if sp is not None:
                    sp.set("status", 502)
                raise last_err
            if sp is not None:
                sp.set("status", 503)
            raise NoReplica()
        finally:
            fm.latency.observe(time.perf_counter() - t0)

    # ----------------------------------------------- id-keyed dispatching
    def dispatch_by_id(self, path: str, path_qs: str, body: bytes,
                       headers: Dict[str, str], sp=None,
                       deadline: Optional[Deadline] = None,
                       model: str = ""
                       ) -> Tuple[int, Dict[str, str], bytes]:
        """Consistent-hash dispatch for the entity-id routes.  The
        common case — every id owned by one replica — forwards the body
        verbatim (responses stay byte-identical to a direct replica
        call); requests spanning owners split into per-replica
        sub-requests whose responses merge in input order.  The
        deadline budget (already stamped on ``headers`` by the proxy
        shell) bounds each owner hop; entity hops are single-attempt,
        so the only deadline decision here is not starting one that
        cannot finish."""
        if deadline is not None and deadline.expired():
            raise DeadlineExceeded("deadline spent before owner dispatch")
        try:
            req = json.loads(body) if body.strip() else {}
        except ValueError as e:
            raise ValueError(f"invalid JSON body: {e}") from None
        if path == "/featurestore/invalidate" and req.get("all"):
            return self._broadcast_invalidate(path_qs, body, headers, sp)
        ids = req.get("ids")
        if not isinstance(ids, list) or not ids:
            raise ValueError("'ids' must be a non-empty list")
        # per-(model, entity) ownership: each tenant's hot rows
        # concentrate independently, on replicas hosting that model
        groups = self.membership.route_ids(ids, model=model)
        if not groups:
            raise NoReplica()
        if len(groups) == 1:
            # single owner: pure passthrough (bit-identical response).
            # The OWNER is addressed directly (acquire_specific), never
            # its ring successor: a breaker-open owner fails fast as
            # 503 rather than silently parking entity rows where no
            # later predict will look — the same stance as the split
            # path below; the ring reroutes only on membership change
            (rid,) = groups
            return self._dispatch_owner(rid, path_qs, body, headers, sp,
                                        deadline=deadline)
        return self._split_merge(path, path_qs, req, groups, headers, sp,
                                 deadline=deadline)

    def _dispatch_owner(self, rid: str, path_qs: str, body: bytes,
                        headers: Dict[str, str], sp=None,
                        deadline: Optional[Deadline] = None
                        ) -> Tuple[int, Dict[str, str], bytes]:
        """One single-attempt hop to a NAMED replica (the resolved ring
        owner), with the same accounting dispatch() does."""
        fm = fleet_metrics()
        t0 = time.perf_counter()
        rep = self.membership.acquire_specific(rid)
        if rep is None:
            if sp is not None:
                sp.set("status", 503)
            raise NoReplica()
        fm.requests.inc(rid)
        try:
            t_hop = time.perf_counter()
            try:
                status, hdrs, out = self._forward(
                    rep, "POST", path_qs, body, headers,
                    timeout=self._hop_timeout(deadline),
                    deadline=deadline)
            except DeadlineExceeded:
                self.membership.release(rep, ok=None)
                if sp is not None:
                    sp.set("status", 504)
                raise
            except ForwardError:
                self.membership.release(rep, ok=False)
                fm.errors.inc(rid)
                if sp is not None:
                    sp.set("status", 502)
                raise
            self.membership.release(rep, ok=True,
                                    latency=time.perf_counter() - t_hop)
            if sp is not None:
                sp.set("replica", rid)
                sp.set("status", status)
            return status, hdrs, out
        finally:
            fm.latency.observe(time.perf_counter() - t0)

    def _sub_body(self, path: str, req: dict, positions: List[int]
                  ) -> bytes:
        sub = dict(req)
        sub["ids"] = [req["ids"][i] for i in positions]
        if path == "/featurestore/put":
            rows = req.get("rows")
            if not isinstance(rows, list) or len(rows) != len(req["ids"]):
                raise ValueError("'rows' must be a list matching 'ids'")
            sub["rows"] = [rows[i] for i in positions]
        return json.dumps(sub).encode()

    def _split_merge(self, path: str, path_qs: str, req: dict,
                     groups: Dict[str, List[int]],
                     headers: Dict[str, str], sp=None,
                     deadline: Optional[Deadline] = None
                     ) -> Tuple[int, Dict[str, str], bytes]:
        """Fan a multi-owner id request out and merge the JSON
        responses: predictions land back in input order; missing-id
        404s union across replicas; the first other error wins.  Same
        single-attempt stance as key-routed dispatch: a sub-request
        that fails surfaces as 502 rather than being retried on a
        non-owner (see :meth:`dispatch`) — the client retries after
        membership converges."""
        ids = req["ids"]
        fm = fleet_metrics()
        n = len(ids)
        merged_preds: List = [None] * n
        missing: List = []
        versions: Dict[str, int] = {}
        invalidated = 0
        for rid, positions in sorted(groups.items()):
            # built BEFORE acquiring: a malformed request (rows/ids
            # length mismatch) must raise while no outstanding count or
            # half-open probe slot is held
            sub = self._sub_body(path, req, positions)
            rep = self.membership.acquire_specific(rid)
            if rep is None:
                # the owner left rotation (or its breaker opened)
                # between routing and dispatch: fail fast with 503 —
                # same stance as the single-owner path; "missing" would
                # be a lie (the rows may well be resident there) and a
                # re-put it provoked would land on the wrong replica
                if sp is not None:
                    sp.set("status", 503)
                raise NoReplica()
            fm.requests.inc(rid)
            t_hop = time.perf_counter()
            try:
                status, _, out = self._forward(
                    rep, "POST", path_qs, sub, headers,
                    timeout=self._hop_timeout(deadline),
                    deadline=deadline)
            except DeadlineExceeded:
                self.membership.release(rep, ok=None)
                raise
            except ForwardError:
                self.membership.release(rep, ok=False)
                fm.errors.inc(rid)
                raise
            self.membership.release(rep, ok=True,
                                    latency=time.perf_counter() - t_hop)
            try:
                payload = json.loads(out)
            except ValueError:
                payload = {}
            if status == 404 and "missing" in payload:
                missing.extend(payload["missing"])
                continue
            if status != 200:
                return status, {"Content-Type": "application/json"}, out
            if "predictions" in payload:
                for pos, p in zip(positions, payload["predictions"]):
                    merged_preds[pos] = p
                versions[rid] = payload.get("model_version")
            invalidated += int(payload.get("invalidated", 0))
        if sp is not None:
            sp.set("split", len(groups))
        ctype = {"Content-Type": "application/json"}
        if missing:
            body = json.dumps({"error": f"{len(missing)} id(s) not "
                                        "resident", "missing": missing})
            if sp is not None:
                sp.set("status", 404)
            return 404, ctype, body.encode()
        if path == "/featurestore/invalidate":
            resp = {"invalidated": invalidated, "split": len(groups)}
        elif path == "/featurestore/put":
            resp = {"stored": n, "split": len(groups)}
        else:
            vs = set(versions.values())
            resp = {"predictions": merged_preds, "rows": n,
                    "model_version": (vs.pop() if len(vs) == 1
                                      else sorted(versions.values())),
                    "split": len(groups)}
        if sp is not None:
            sp.set("status", 200)
        return 200, ctype, json.dumps(resp).encode()

    def _broadcast_invalidate(self, path_qs: str, body: bytes,
                              headers: Dict[str, str], sp=None
                              ) -> Tuple[int, Dict[str, str], bytes]:
        """``{"all": true}`` goes to every in-rotation replica."""
        total = 0
        reached = 0
        for rid in sorted(r.replica_id
                          for r in self.membership.in_rotation()):
            rep = self.membership.acquire_specific(rid)
            if rep is None:
                continue
            try:
                status, _, out = self._forward(rep, "POST", path_qs,
                                               body, headers)
            except ForwardError as e:
                self.membership.release(rep, ok=False)
                fleet_metrics().errors.inc(e.replica_id)
                continue
            self.membership.release(rep, ok=True)
            if status == 200:
                reached += 1
                try:
                    total += int(json.loads(out).get("invalidated", 0))
                except ValueError:
                    pass  # non-JSON 200 from a replica: count nothing
        if sp is not None:
            sp.set("status", 200)
        return 200, {"Content-Type": "application/json"}, json.dumps(
            {"invalidated": total, "replicas": reached}).encode()

    # -------------------------------------------------------------- admin
    def health(self) -> dict:
        desc = self.membership.describe()
        return {
            "status": "ok" if desc["in_rotation"] > 0 else "degraded",
            "role": "fleet_router",
            "members": desc["in_rotation"],
            "registered": desc["registered"],
            "inflight": self._inflight,
            "inflight_budget": self.inflight_budget,
            "models": self.membership.models_hosted(),
            # the elastic supervisor pins the fleet size while a
            # rollout/canary soak runs — a drain mid-soak would remove
            # the soak's pinned path-groups and invalidate the gate
            "rollout_in_progress": self._rollout_lock.locked(),
            "uptime_seconds": round(time.perf_counter() - self.t0, 3),
        }

    def run_rollout(self, model_path: str, req: dict
                    ) -> Tuple[int, dict]:
        """One staged canary rollout (fleet/rollout.py); serialized —
        a second rollout while one runs gets 409."""
        from xgboost_tpu.fleet.rollout import RolloutController
        if not self._rollout_lock.acquire(blocking=False):
            return 409, {"error": "a rollout is already in progress"}
        try:
            ctl = RolloutController(self.membership, self._forward,
                                    state=self._rollout_state)
            kw = dict(self.rollout_defaults)
            for k in ("canaries", "soak_sec", "gate_error_rate",
                      "gate_p99_ms", "model"):
                if k in req:
                    kw[k] = req[k]
            report = ctl.rollout(model_path, **kw)
            with self._inflight_lock:
                self._last_rollout = report
            return (200 if report["status"] == "ok" else 500), report
        except Exception as e:
            report = {"status": "error",
                      "error": f"{type(e).__name__}: {e}"}
            with self._inflight_lock:
                self._last_rollout = report
            return 500, report
        finally:
            self._rollout_lock.release()

    def run_rollback(self, model: str = "") -> Tuple[int, dict]:
        from xgboost_tpu.fleet.rollout import RolloutController
        # serialized against rollouts: a rollback racing an in-flight
        # rollout's fleet push would interleave writes to the same
        # model files and leave a mixed fleet behind an authoritative-
        # looking report
        if not self._rollout_lock.acquire(blocking=False):
            return 409, {"error": "a rollout is in progress — retry "
                                  "after it completes (its gate rolls "
                                  "a failing push back itself)"}
        try:
            ctl = RolloutController(self.membership, self._forward,
                                    state=self._rollout_state)
            report = ctl.rollback(model=model)
            with self._inflight_lock:
                self._last_rollout = report
            return 200, report
        finally:
            self._rollout_lock.release()

    def rollout_status(self) -> dict:
        with self._inflight_lock:
            return dict(self._last_rollout)

    # --------------------------------------------------------------- placer
    def placer_acquire(self, placer_id: str,
                       lease_sec: Optional[float] = None) -> dict:
        """Grant (or renew) the single-holder placer lease.  A second
        placer asking while the lease is live is told who holds it and
        stands by; the holder renews by re-asking.  Monotonic clock
        throughout (XGT006)."""
        from xgboost_tpu.obs import event
        now = time.monotonic()
        sec = float(lease_sec) if lease_sec else self._placer_lease_sec
        renewal = False
        with self._placer_lock:
            free = (self._placer_holder is None
                    or now >= self._placer_deadline
                    or self._placer_holder == placer_id)
            took_over = free and self._placer_holder not in (None,
                                                             placer_id)
            if free:
                renewal = self._placer_holder == placer_id
                self._placer_holder = placer_id
                self._placer_deadline = now + sec
                self._placer_lease_sec = sec
            holder = self._placer_holder
        if free and not renewal:
            event("placer.lease", placer_id=placer_id,
                  took_over=took_over)
        return {"granted": free, "holder": holder, "lease_sec": sec}

    def placer_record_plan(self, placer_id: str,
                           plan: dict) -> Tuple[int, dict]:
        """Record the placer's target assignment (observability +
        takeover hand-off).  Only the lease holder may write — a
        zombie placer that lost its lease gets 409, not a split-brain
        plan."""
        now = time.monotonic()
        with self._placer_lock:
            if (self._placer_holder != placer_id
                    or now >= self._placer_deadline):
                return 409, {"error": "not the placer lease holder",
                             "holder": self._placer_holder}
            self._placer_plan = dict(plan)
        return 200, {"recorded": True}

    def placer_status(self) -> dict:
        now = time.monotonic()
        with self._placer_lock:
            return {
                "holder": self._placer_holder,
                "lease_remaining_sec": round(
                    max(self._placer_deadline - now, 0.0), 3),
                "plan": dict(self._placer_plan),
            }

    # ---------------------------------------------------------- lifecycle
    def _hc_loop(self) -> None:
        # ±20% jitter: N routers (or a router restarted with its fleet)
        # must not probe every replica in lockstep forever
        while not self._stop.wait(jittered(self.hc_sec)):
            try:
                self.membership.health_check()
                self._pool.prune(self.membership.urls())
                # advertisement drift (a rollout moved a tenant's hash)
                # arrives on heartbeats; fold it into the snapshot here
                # rather than fsync-ing on every heartbeat
                self.save_state()
            except Exception as e:  # the health loop must survive anything
                from xgboost_tpu.obs.metrics import swallowed_error
                swallowed_error("fleet.router.health_loop", e)

    def start(self) -> "FleetRouter":
        if self.hc_sec > 0:
            self._hc_thread = threading.Thread(
                target=self._hc_loop, daemon=True, name="xgbtpu-fleet-hc")
            self._hc_thread.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="xgbtpu-fleet-router")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        if self.hc_sec > 0:
            self._hc_thread = threading.Thread(
                target=self._hc_loop, daemon=True, name="xgbtpu-fleet-hc")
            self._hc_thread.start()
        if threading.current_thread() is threading.main_thread():
            try:
                signal.signal(signal.SIGTERM,
                              lambda *_: threading.Thread(
                                  target=self.shutdown,
                                  daemon=True).start())
            except ValueError:
                pass
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        with self._inflight_lock:
            if self._shut:
                return
            self._shut = True
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._pool.close()
        if self._hc_thread is not None:
            self._hc_thread.join(self.hc_sec + 2.0)
            self._hc_thread = None
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None


def run_router(host: str = "127.0.0.1", port: int = 8000,
               lease_sec: float = 10.0, hc_sec: float = 2.0,
               inflight_budget: int = 256, breaker_failures: int = 3,
               breaker_cooldown_sec: float = 5.0, retry: bool = True,
               forward_timeout: float = 30.0, max_body_mb: float = 64.0,
               deadline_ms: float = 0.0,
               slow_eject_factor: float = 3.0,
               slow_eject_cooldown_sec: float = 5.0,
               rollout_defaults: Optional[dict] = None,
               state_path: str = "",
               tenant_inflight: int = 0, tenant_rate: float = 0.0,
               tenant_burst: float = 8.0,
               quiet: bool = False, block: bool = True
               ) -> Optional[FleetRouter]:
    """Build and run the fleet router (CLI ``task=fleet_router``).
    ``block=False`` returns the started router (tests, launchers)."""
    rt = FleetRouter(host=host, port=port, lease_sec=lease_sec,
                     hc_sec=hc_sec, inflight_budget=inflight_budget,
                     breaker_failures=breaker_failures,
                     breaker_cooldown_sec=breaker_cooldown_sec,
                     retry=retry, forward_timeout=forward_timeout,
                     max_body_mb=max_body_mb, deadline_ms=deadline_ms,
                     slow_eject_factor=slow_eject_factor,
                     slow_eject_cooldown_sec=slow_eject_cooldown_sec,
                     rollout_defaults=rollout_defaults,
                     state_path=state_path,
                     tenant_inflight=tenant_inflight,
                     tenant_rate=tenant_rate, tenant_burst=tenant_burst,
                     quiet=quiet)
    if not quiet:
        print(f"[fleet] router on http://{rt.host}:{rt.port} "
              f"(lease {lease_sec}s, budget {inflight_budget} in-flight)",
              file=sys.stderr)
    if block:
        rt.serve_forever()
        return None
    return rt.start()

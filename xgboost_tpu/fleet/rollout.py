"""Staged canary model rollout across the fleet, keyed by content hash.

The fleet-wide analog of the single-replica hot-reload protocol
(serving/registry.py): a new model is pushed to a FEW canary replicas
first, the canaries soak under live traffic, a gate reads their error
rate and latency from their own ``/metrics``, and only a passing gate
rolls the remaining replicas.  Every step verifies what a replica
ACTUALLY serves via the ``model_hash`` its ``/healthz`` reports
(ModelRegistry content hashes — not what the controller *hopes* it
pushed), and one command rolls the whole fleet back instantly.

Push mechanics: each replica registered a ``model_path`` (the file its
registry watches); the controller atomically rewrites that file
(reliability.integrity.atomic_write — a crash mid-push tears nothing)
and forces ``POST /-/reload``.  Rollback is the instant engine-ring
swap (``POST /-/rollback``, no disk I/O) plus restoration of the
previous file bytes, so a later replica restart comes back on the
rolled-back model, not the bad push.

Replicas sharing one model file (a fleet launched off a single path)
are pushed as one unit: the canary set closes over path groups, so a
"canary" file write can never leak into uncanaried replicas through
their reload pollers.
"""

from __future__ import annotations

import hashlib
import json
import re
import time
from typing import Callable, Dict, List, Optional

from xgboost_tpu.obs import event
from xgboost_tpu.obs.metrics import fleet_metrics
from xgboost_tpu.fleet.membership import Membership, Replica

# metric names the gate reads from a canary's /metrics exposition.
# The value class must admit a '-' ANYWHERE, not just leading: repr()
# renders small floats in e-notation ("9.5e-05") and dropping those
# would feed the gate a silent 0.0; float() below is the real parser.
_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{[^}]*\})? "
                        r"([-+0-9.eEnaif]+)$")


def scrape_samples(text: str) -> Dict[str, float]:
    """Parse unlabeled samples (``name value``) out of a Prometheus
    text exposition; labeled samples are skipped (the gate reads plain
    counters/gauges only)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line.strip())
        if m and "{" not in line.split(" ", 1)[0]:
            try:
                out[m.group(1)] = float(m.group(2))
            except ValueError:
                continue
    return out


# one-label samples (name{label="value"} value) — the shape every
# LabeledCounter/LabeledGauge in obs/metrics.py renders
_LABELED_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)\{([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"\} '
    r"([-+0-9.eEnaif]+)$")


def scrape_labeled_samples(text: str, family: str
                           ) -> Dict[str, float]:
    """Parse the single-label samples of one metric ``family`` out of a
    Prometheus text exposition: label value -> sample value.  The
    placer reads per-tenant load this way
    (``xgbtpu_tenant_requests_total{model="a"} 42`` -> ``{"a": 42.0}``);
    :func:`scrape_samples` deliberately skips labeled samples, so this
    is its labeled counterpart rather than a change to the gate's
    parser."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = _LABELED_RE.match(line.strip())
        if m and m.group(1) == family:
            try:
                out[m.group(3)] = float(m.group(4))
            except ValueError:
                continue
    return out


class RolloutController:
    """Drives staged rollouts over a :class:`Membership` using the
    router's forward function (``(rep, method, path_qs, body, headers)
    -> (status, headers, body)``)."""

    def __init__(self, membership: Membership, forward: Callable,
                 state: Optional[dict] = None):
        self.membership = membership
        self.forward = forward
        # backups of replaced model files (path -> previous bytes),
        # shared across controller instances via the router's state
        # dict so an operator rollback can restore files pushed by an
        # earlier rollout request
        self.state = state if state is not None else {}

    # ------------------------------------------------------------ plumbing
    def _call(self, rep: Replica, method: str, path: str,
              payload: Optional[dict] = None) -> Optional[dict]:
        """One control-plane call to a replica; None = unreachable."""
        body = json.dumps(payload).encode() if payload is not None else b""
        try:
            status, _, out = self.forward(rep, method, path, body,
                                          {"Content-Type":
                                           "application/json"})
        except Exception as e:
            from xgboost_tpu.obs.metrics import swallowed_error
            swallowed_error("fleet.rollout.call", e)
            return None
        if status >= 400:
            return None
        try:
            return json.loads(out)
        except ValueError:
            return None

    @staticmethod
    def _model_path(rep: Replica, model: str = "") -> Optional[str]:
        """The file to rewrite on ``rep`` for this rollout: its bare
        registered path, or — for a named tenant — the path its catalog
        advertisement maps the model to."""
        if not model:
            return rep.model_path
        return (rep.models.get(model) or {}).get("path")

    @staticmethod
    def _admin_path(path: str, model: str = "") -> str:
        return f"{path}?model={model}" if model else path

    def _served_hash(self, rep: Replica, model: str = "") -> Optional[str]:
        """What the replica ACTUALLY serves: the top-level hash, or —
        for a named tenant — its row in /healthz ``models`` (per-model
        content hashes, serving/http.py)."""
        h = self._call(rep, "GET", "/healthz")
        if h is None:
            return None
        if not model:
            return h.get("model_hash")
        return (h.get("models", {}).get(model) or {}).get("model_hash")

    def _metrics_snapshot(self, rep: Replica) -> Optional[Dict[str, float]]:
        try:
            status, _, out = self.forward(rep, "GET", "/metrics", b"", {})
        except Exception as e:
            from xgboost_tpu.obs.metrics import swallowed_error
            swallowed_error("fleet.rollout.scrape", e)
            return None
        if status != 200:
            return None
        return scrape_samples(out.decode("utf-8", "replace"))

    # ---------------------------------------------------------------- push
    def _push(self, rep: Replica, raw: bytes, expect_hash: str,
              model: str = "") -> dict:
        """Write + force-reload + verify one replica (one tenant's
        path/reload/hash when ``model`` names one).  Returns a
        per-replica report entry."""
        from xgboost_tpu.reliability.integrity import atomic_write
        path = self._model_path(rep, model)
        entry = {"replica_id": rep.replica_id, "path": path}
        if model:
            entry["model"] = model
        if not path:
            entry["result"] = (f"model {model!r} not hosted" if model
                               else "no model_path registered")
            return entry
        try:
            atomic_write(path, raw)
        except OSError as e:
            entry["result"] = f"write failed: {e}"
            return entry
        resp = self._call(rep, "POST",
                          self._admin_path("/-/reload", model))
        if resp is None:
            entry["result"] = "reload unreachable"
            return entry
        got = self._served_hash(rep, model)
        entry["served_hash"] = got
        entry["result"] = ("ok" if got == expect_hash
                           else f"hash mismatch (serves {got})")
        return entry

    def _unpush(self, rep: Replica, model: str = "") -> dict:
        """Instant engine rollback + file restore for one replica
        (scoped to one tenant's registry when ``model`` names one —
        the other tenants' engines and files are untouched)."""
        from xgboost_tpu.reliability.integrity import atomic_write
        entry = {"replica_id": rep.replica_id}
        if model:
            entry["model"] = model
        resp = self._call(rep, "POST",
                          self._admin_path("/-/rollback", model))
        entry["engine_rollback"] = bool(resp and resp.get("rolled_back"))
        path = self._model_path(rep, model)
        backup = self.state.get(path)
        if backup is not None:
            try:
                atomic_write(path, backup)
                entry["file_restored"] = True
            except OSError as e:
                entry["file_restored"] = f"failed: {e}"
        return entry

    # ---------------------------------------------------------------- gate
    def _gate(self, rep: Replica, before: Optional[Dict[str, float]],
              gate_error_rate: float, gate_p99_ms: float) -> dict:
        """Read one canary's own /metrics and judge it.  An unreachable
        canary FAILS the gate — a rollout must not proceed past a
        replica it cannot observe (the chaos-killed-canary case) — and
        so does one that is no longer in the ``serving`` state (killed
        or draining mid-soak: its metrics may still answer over a
        lingering keep-alive connection, but it is not a canary
        anymore)."""
        h = self._call(rep, "GET", "/healthz")
        if h is None or h.get("state") != "serving":
            return {"replica_id": rep.replica_id, "pass": False,
                    "reason": "canary unreachable or not serving "
                              f"(state {h.get('state') if h else None!r})"}
        after = self._metrics_snapshot(rep)
        if after is None or before is None:
            return {"replica_id": rep.replica_id, "pass": False,
                    "reason": "canary metrics unreachable"}
        d_req = (after.get("xgbtpu_serving_requests_total", 0.0)
                 - before.get("xgbtpu_serving_requests_total", 0.0))
        d_err = (after.get("xgbtpu_serving_errors_total", 0.0)
                 - before.get("xgbtpu_serving_errors_total", 0.0))
        err_rate = d_err / d_req if d_req > 0 else 0.0
        p99_ms = after.get("xgbtpu_serving_latency_p99_seconds", 0.0) * 1e3
        verdict = {"replica_id": rep.replica_id,
                   "soak_requests": d_req, "soak_errors": d_err,
                   "error_rate": round(err_rate, 6),
                   "p99_ms": round(p99_ms, 3)}
        if err_rate > gate_error_rate:
            verdict["pass"] = False
            verdict["reason"] = (f"error rate {err_rate:.4f} > "
                                 f"gate {gate_error_rate}")
        elif p99_ms > gate_p99_ms:
            verdict["pass"] = False
            verdict["reason"] = f"p99 {p99_ms:.1f}ms > gate {gate_p99_ms}ms"
        else:
            verdict["pass"] = True
        return verdict

    # -------------------------------------------------------------- public
    def rollout(self, model_path: str, canaries: int = 1,
                soak_sec: float = 3.0, gate_error_rate: float = 0.02,
                gate_p99_ms: float = 250.0, model: str = "") -> dict:
        """One staged rollout of the model file at ``model_path``.

        Stages: verify bytes -> push to ``canaries`` path-groups ->
        soak ``soak_sec`` under whatever traffic the router is carrying
        -> gate on the canaries' own error-rate/latency metrics ->
        fleet-wide push, or rollback of the canaries.  Returns a full
        report (also kept on ``GET /fleet/rollout``).

        When ``model`` names a catalog tenant the rollout is scoped to
        that tenant's lane: only replicas advertising the model are
        touched, each replica's file target is its OWN advertised path
        for that model, and push/gate/rollback leave every other
        tenant's engines, files, and backups untouched."""
        from xgboost_tpu.reliability.integrity import (read_file,
                                                       verify_model_bytes)
        raw = read_file(model_path)
        verify_model_bytes(raw, name=model_path)  # never push torn bytes
        expect = hashlib.sha256(raw).hexdigest()
        report: dict = {"model_path": model_path, "model_hash": expect,
                        "started_ts": round(time.time(), 3)}
        if model:
            report["model"] = model
        members = sorted(self.membership.in_rotation(),
                         key=lambda r: r.replica_id)
        if model:
            members = [r for r in members
                       if self._model_path(r, model)]
            if not members:
                report.update(status="error",
                              error=f"no replica in rotation hosts "
                                    f"model {model!r}")
                return report
        if not members:
            report.update(status="error", error="no replicas in rotation")
            return report
        # canary selection closes over model-path groups (replicas
        # sharing a file reload together whether we like it or not)
        canaries = max(1, int(canaries))
        canary_set: List[Replica] = []
        canary_paths = set()
        for rep in members:
            path = self._model_path(rep, model)
            if len(canary_set) < canaries or path in canary_paths:
                canary_set.append(rep)
                canary_paths.add(path)
        rest = [r for r in members if r not in canary_set
                and self._model_path(r, model) not in canary_paths]
        report["canaries"] = [r.replica_id for r in canary_set]
        event("fleet.rollout_start", model_hash=expect,
              canaries=report["canaries"], model=model or None)

        # refresh the rollback backups for THIS rollout, before any
        # file is touched: a backup taken only on first-ever push would
        # go stale after one successful rollout, and a later rollback
        # would restore the pre-FIRST-rollout bytes — the engine ring
        # pops to version N-1 while the file (and the poller) goes to
        # N-2, silently splitting the fleet
        for path in {self._model_path(r, model) for r in members}:
            if not path:
                continue
            try:
                self.state[path] = read_file(path)
            except OSError as e:
                from xgboost_tpu.obs.metrics import swallowed_error
                swallowed_error("fleet.rollout.backup", e)
                self.state.pop(path, None)  # never restore stale bytes

        before = {r.replica_id: self._metrics_snapshot(r)
                  for r in canary_set}
        pushes = [self._push(r, raw, expect, model=model)
                  for r in canary_set]
        report["canary_push"] = pushes
        failed_push = [p for p in pushes if p.get("result") != "ok"]
        if not failed_push and soak_sec > 0:
            time.sleep(soak_sec)
        verdicts = ([] if failed_push else
                    [self._gate(r, before[r.replica_id],
                                gate_error_rate, gate_p99_ms)
                     for r in canary_set])
        report["canary_gate"] = verdicts
        if failed_push or not all(v["pass"] for v in verdicts):
            report["rollback"] = [self._unpush(r, model=model)
                                  for r in canary_set]
            report["status"] = "rolled_back"
            report["reason"] = (failed_push[0]["result"] if failed_push
                                else next(v["reason"] for v in verdicts
                                          if not v["pass"]))
            fleet_metrics().rollbacks.inc()
            event("fleet.rollout_rolled_back", model_hash=expect,
                  reason=report["reason"], model=model or None)
            return report

        report["fleet_push"] = [self._push(r, raw, expect, model=model)
                                for r in rest]
        bad = [p for p in report["fleet_push"] if p.get("result") != "ok"]
        report["status"] = "ok" if not bad else "partial"
        report["serving_hash"] = expect
        fleet_metrics().rollouts.inc()
        event("fleet.rollout_done", model_hash=expect,
              status=report["status"], model=model or None)
        return report

    def rollback(self, model: str = "") -> dict:
        """The one-command fleet rollback: every registered replica
        swaps its previous engine back in (instant, no disk) and any
        file this controller's state pushed is restored.  With
        ``model`` the sweep is scoped to replicas hosting that tenant
        and only its registry/file are rolled back."""
        reps = [self.membership.get(rid) for rid in self.membership.ids()]
        if model:
            reps = [r for r in reps
                    if r is not None and self._model_path(r, model)]
        entries = [self._unpush(r, model=model)
                   for r in reps if r is not None]
        fleet_metrics().rollbacks.inc()
        event("fleet.rollback", replicas=len(entries), model=model or None)
        out = {"status": "rolled_back", "replicas": entries}
        if model:
            out["model"] = model
        return out

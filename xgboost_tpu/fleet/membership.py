"""Replica membership: heartbeat leases, health, consistent hashing.

The reference's tracker tier (``tracker/rabit_tracker.py``, SURVEY.md
L0) is a rendezvous service: workers connect, get assigned a rank,
report liveness, and a restarted worker sends ``recover`` to rejoin the
job.  The serving-fleet analog lives here:

- :class:`Membership` (router side) — replicas register over HTTP and
  renew a **heartbeat lease**; a replica whose lease expires, whose
  ``/healthz`` stops answering, or whose drain state machine left
  ``serving`` drops out of rotation automatically.  A restarted replica
  simply registers again under the same id — the ``recover`` path —
  and is back in rotation on the next health pass.
- :class:`HashRing` — consistent hashing for ``/predict_by_id``
  dispatch: an entity id maps to the same replica across requests (so
  device-resident feature rows concentrate there), and a membership
  change remaps only the keys owned by the changed replica.
- :class:`LeaseClient` (replica side) — the registration/heartbeat
  client the HTTP server runs when ``serve_router_url`` is set; it
  re-registers on lease loss and deregisters on drain.  The chaos
  kinds ``heartbeat_loss`` / ``replica_kill`` (reliability/faults.py)
  hook its loop, so fleet recovery is provable the same way checkpoint
  recovery is.

All lease arithmetic uses ``time.monotonic()`` — leases are durations,
and an NTP step must not expire the whole fleet (XGT006).
"""

from __future__ import annotations

import bisect
import hashlib
import json
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

from xgboost_tpu.reliability.rc import REPLICA_KILL_RC

# breaker states (per replica, managed by Membership under its lock)
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half-open"


class Replica:
    """One registered replica: identity, lease, health, breaker, load.

    All mutable fields are guarded by the owning :class:`Membership`'s
    lock; read-mostly snapshots go out through ``describe()``."""

    def __init__(self, replica_id: str, url: str,
                 model_path: Optional[str] = None,
                 model_hash: Optional[str] = None,
                 pid: Optional[int] = None,
                 models: Optional[Dict[str, dict]] = None,
                 device: Optional[dict] = None):
        self.replica_id = replica_id
        self.url = url.rstrip("/")
        self.model_path = model_path
        self.model_hash = model_hash
        self.pid = pid
        # catalog advertisement: {model_name: {"path":..., "hash":...,
        # "bytes":...}} — which named models this replica can serve
        # (empty = a pre-catalog replica that only answers bare
        # /predict)
        self.models: Dict[str, dict] = dict(models or {})
        # device budget advertisement: {"budget_bytes":..,
        # "used_bytes":..} — the placer bin-packs against this
        self.device: dict = dict(device or {})
        self.lease_deadline = 0.0       # monotonic
        self.registered_count = 0       # bumps on every (re-)register
        self.health_ok = True           # last /healthz verdict
        self.health_state = "serving"   # replica's drain state
        self.outstanding = 0            # requests in flight via router
        # circuit breaker (consecutive-failure trip, half-open probe)
        self.breaker = BREAKER_CLOSED
        self.consecutive_failures = 0
        self.breaker_opened_at = 0.0    # monotonic
        self.probe_inflight = False
        # latency-aware ejection (distinct from the breaker: a
        # slow-but-alive replica never fails a request, so the failure
        # counter never sees it — the EWMA does)
        self.lat_ewma = 0.0             # seconds; 0 = no samples yet
        self.lat_samples = 0
        self.ejected = False
        self.ejected_at = 0.0           # monotonic
        self.eject_probe_inflight = False
        # thread id that was GRANTED the readmission probe: release()
        # attributes the probe outcome only to that dispatch, so a
        # concurrent entity-id hop (not ejection-gated) finishing fast
        # cannot readmit a still-wedged replica
        self.eject_probe_tid = 0

    def lease_live(self, now: float) -> bool:
        return now < self.lease_deadline

    def describe(self, now: float) -> dict:
        return {
            "replica_id": self.replica_id,
            "url": self.url,
            "model_path": self.model_path,
            "model_hash": self.model_hash,
            "models": sorted(self.models),
            "models_detail": {m: dict(v) for m, v in self.models.items()},
            "device": dict(self.device),
            "pid": self.pid,
            "lease_remaining_sec": round(self.lease_deadline - now, 3),
            "health_ok": self.health_ok,
            "state": self.health_state,
            "outstanding": self.outstanding,
            "breaker": self.breaker,
            "consecutive_failures": self.consecutive_failures,
            "registered_count": self.registered_count,
            "ejected": self.ejected,
            "latency_ewma_ms": round(self.lat_ewma * 1e3, 3),
        }


class HashRing:
    """Consistent-hash ring over replica ids (virtual nodes).

    ``route(key, eligible)`` walks clockwise from the key's point to
    the first vnode whose replica is in ``eligible`` — so keys owned by
    a dead/draining replica fail over to its ring successor while every
    other key stays put (feature-store residency concentrates and
    survives membership churn)."""

    def __init__(self, vnodes: int = 64):
        self.vnodes = int(vnodes)
        # (points, owners) swapped in ONE assignment so lock-free
        # readers (route_ids hashes outside the membership lock) always
        # see a consistent pair
        self._nodes: tuple = ((), ())

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(
            hashlib.sha1(key.encode("utf-8", "replace")).digest()[:8],
            "big")

    def rebuild(self, replica_ids: List[str]) -> None:
        pts = []
        for rid in replica_ids:
            for v in range(self.vnodes):
                pts.append((self._hash(f"{rid}#{v}"), rid))
        pts.sort()
        self._nodes = (tuple(p for p, _ in pts),
                       tuple(r for _, r in pts))

    def route(self, key: str, eligible) -> Optional[str]:
        """First eligible replica clockwise from ``key``'s point."""
        points, owners = self._nodes  # one read: rebuild swaps atomically
        n = len(points)
        if n == 0:
            return None
        start = bisect.bisect_left(points, self._hash(str(key)))
        for i in range(n):
            owner = owners[(start + i) % n]
            if owner in eligible:
                return owner
        return None


class Membership:
    """The router's replica table: register/heartbeat/expire + health.

    ``in_rotation()`` is the dispatch view: lease live, last health
    check OK, drain state ``serving``.  The breaker is tracked here too
    (it is per-replica state the dispatcher consults), with the classic
    three states: CLOSED (normal) -> OPEN after
    ``breaker_failures`` consecutive errors (no traffic) ->
    HALF-OPEN after ``breaker_cooldown_sec`` (exactly one probe
    request) -> CLOSED on success / OPEN again on failure."""

    #: EWMA smoothing for per-replica dispatch latency (~last 25 obs)
    LAT_ALPHA = 0.2
    #: minimum EWMA samples (per replica) before ejection may fire —
    #: one cold-start compile must not eject a fresh replica
    EJECT_MIN_SAMPLES = 10

    def __init__(self, lease_sec: float = 10.0,
                 breaker_failures: int = 3,
                 breaker_cooldown_sec: float = 5.0,
                 slow_eject_factor: float = 3.0,
                 slow_eject_cooldown_sec: float = 5.0,
                 vnodes: int = 64):
        self.lease_sec = float(lease_sec)
        self.breaker_failures = int(breaker_failures)
        self.breaker_cooldown_sec = float(breaker_cooldown_sec)
        # latency ejection: EWMA above factor x the PEERS' median
        # ejects from least-loaded dispatch (0 disables); after the
        # cooldown, ONE probe request decides readmission
        self.slow_eject_factor = float(slow_eject_factor)
        self.slow_eject_cooldown_sec = float(slow_eject_cooldown_sec)
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        self._ring = HashRing(vnodes)
        self._ring_stale = True

    # ---------------------------------------------------------- lifecycle
    def register(self, replica_id: str, url: str,
                 model_path: Optional[str] = None,
                 model_hash: Optional[str] = None,
                 pid: Optional[int] = None,
                 models: Optional[Dict[str, dict]] = None,
                 device: Optional[dict] = None) -> dict:
        """Add (or revive — the tracker ``recover`` path) a replica and
        grant a heartbeat lease.  Returns the lease grant."""
        from xgboost_tpu.obs import event
        from xgboost_tpu.obs.metrics import fleet_metrics
        now = time.monotonic()
        with self._lock:
            rep = self._replicas.get(replica_id)
            recovered = rep is not None
            if rep is None:
                rep = Replica(replica_id, url, model_path, model_hash, pid,
                              models=models, device=device)
                self._replicas[replica_id] = rep
            else:
                # a restarted process re-registers under its old id:
                # fresh endpoint/pid; breaker, health AND ejection
                # state start clean (the fresh process neither inherits
                # the wedged era's EWMA nor its ejection — and the
                # EJECT_MIN_SAMPLES cold-start guard applies to it like
                # any new replica)
                rep.url = url.rstrip("/")
                rep.model_path = model_path or rep.model_path
                rep.model_hash = model_hash or rep.model_hash
                rep.pid = pid if pid is not None else rep.pid
                if models is not None:
                    rep.models = dict(models)
                if device is not None:
                    rep.device = dict(device)
                rep.breaker = BREAKER_CLOSED
                rep.consecutive_failures = 0
                rep.probe_inflight = False
                rep.outstanding = 0
                rep.ejected = False
                rep.ejected_at = 0.0
                rep.eject_probe_inflight = False
                rep.eject_probe_tid = 0
                rep.lat_ewma = 0.0
                rep.lat_samples = 0
            rep.health_ok = True
            rep.health_state = "serving"
            rep.registered_count += 1
            rep.lease_deadline = now + self.lease_sec
            self._ring_stale = True
            total = len(self._replicas)
        fm = fleet_metrics()
        fm.members_registered.set(total)
        if recovered:
            fm.ejected.set(replica_id, 0.0)
        event("fleet.register", replica_id=replica_id, url=url,
              recovered=recovered)
        return {"lease_sec": self.lease_sec, "recovered": recovered}

    def heartbeat(self, replica_id: str,
                  model_hash: Optional[str] = None,
                  models: Optional[Dict[str, dict]] = None,
                  device: Optional[dict] = None) -> bool:
        """Renew a lease.  False = unknown replica (the client should
        re-register — its lease expired or the router restarted).
        ``models``/``device`` keep the catalog + budget advertisement
        fresh: the payload is DIFFED against the table so a mid-lease
        catalog change (placement delta, eviction, rollout hash bump)
        is visible as an event the moment it lands — model-aware
        routing and the placer never act on a map older than one
        heartbeat."""
        now = time.monotonic()
        added: List[str] = []
        removed: List[str] = []
        changed: List[str] = []
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None:
                return False
            rep.lease_deadline = now + self.lease_sec
            if model_hash:
                rep.model_hash = model_hash
            if models is not None and models != rep.models:
                added = sorted(m for m in models if m not in rep.models)
                removed = sorted(m for m in rep.models if m not in models)
                changed = sorted(
                    m for m in models if m in rep.models
                    and models[m] != rep.models[m])
                rep.models = dict(models)
            if device is not None:
                rep.device = dict(device)
        if added or removed or changed:
            from xgboost_tpu.obs import event
            from xgboost_tpu.obs.metrics import fleet_metrics
            fleet_metrics().advert_updates.inc()
            event("fleet.models_changed", replica_id=replica_id,
                  added=added, removed=removed, changed=changed)
        return True

    def deregister(self, replica_id: str) -> bool:
        """Remove a replica (drain shutdown announces itself)."""
        from xgboost_tpu.obs import event
        from xgboost_tpu.obs.metrics import fleet_metrics
        with self._lock:
            rep = self._replicas.pop(replica_id, None)
            self._ring_stale = True
            total = len(self._replicas)
        fleet_metrics().members_registered.set(total)
        if rep is not None:
            event("fleet.deregister", replica_id=replica_id)
        return rep is not None

    # ------------------------------------------------------------- views
    def get(self, replica_id: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(replica_id)

    def ids(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    def urls(self):
        """Base URLs of every registered replica (any state) — the
        router's connection pool prunes against this set."""
        with self._lock:
            return {r.url for r in self._replicas.values()}

    def in_rotation(self) -> List[Replica]:
        """Replicas eligible for dispatch: lease live, healthy,
        drain state ``serving``.  (Breaker gating is separate — an
        OPEN breaker blocks dispatch but a half-open probe may pass.)"""
        now = time.monotonic()
        with self._lock:
            return [r for r in self._replicas.values()
                    if r.lease_live(now) and r.health_ok
                    and r.health_state == "serving"]

    def hosting(self, model: str) -> set:
        """Replica ids advertising ``model`` in their catalog.  Empty
        model = no filter (every replica hosts its own bare default).
        A pre-catalog replica (empty advertisement) hosts no NAMED
        model — routing one there would bounce off its 404."""
        with self._lock:
            if not model:
                return set(self._replicas)
            return {rid for rid, r in self._replicas.items()
                    if model in r.models}

    def models_hosted(self) -> Dict[str, int]:
        """model name -> number of replicas advertising it (the
        router's /fleet/members summary)."""
        out: Dict[str, int] = {}
        with self._lock:
            for r in self._replicas.values():
                for m in r.models:
                    out[m] = out.get(m, 0) + 1
        return out

    def describe(self) -> dict:
        now = time.monotonic()
        with self._lock:
            reps = [r.describe(now) for r in self._replicas.values()]
        rotation = {r.replica_id for r in self.in_rotation()}
        for d in reps:
            d["in_rotation"] = d["replica_id"] in rotation
        return {"replicas": sorted(reps, key=lambda d: d["replica_id"]),
                "in_rotation": len(rotation),
                "registered": len(reps)}

    # ----------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """Serializable membership state for the router's zero-downtime
        restart (``fleet_state_path``): identity + endpoint + catalog
        advertisement of every LEASE-LIVE replica.  Transient state
        (breaker, EWMA, outstanding) is deliberately dropped — a
        restarted router re-learns it in seconds, while a stale 'open'
        breaker would wrongly blackhole a recovered replica."""
        now = time.monotonic()
        with self._lock:
            return {"replicas": [
                {"replica_id": r.replica_id, "url": r.url,
                 "model_path": r.model_path, "model_hash": r.model_hash,
                 "pid": r.pid, "models": r.models, "device": r.device}
                for r in self._replicas.values() if r.lease_live(now)]}

    def restore(self, state: dict) -> int:
        """Re-register every snapshotted replica with a FRESH lease:
        restored members take traffic immediately (zero-downtime
        restart), and any that died while the router was down fall out
        on the first health pass / lease expiry — exactly how a crashed
        replica is handled in steady state."""
        n = 0
        for d in state.get("replicas", []):
            try:
                self.register(d["replica_id"], d["url"],
                              model_path=d.get("model_path"),
                              model_hash=d.get("model_hash"),
                              pid=d.get("pid"),
                              models=d.get("models"),
                              device=d.get("device"))
                n += 1
            except (KeyError, TypeError) as e:
                from xgboost_tpu.obs.metrics import swallowed_error
                swallowed_error("fleet.membership.restore", e)
        return n

    # ---------------------------------------------------------- dispatch
    def _breaker_allows_locked(self, rep: Replica, now: float) -> bool:
        if rep.breaker == BREAKER_CLOSED:
            return True
        if rep.breaker == BREAKER_OPEN:
            if now - rep.breaker_opened_at < self.breaker_cooldown_sec:
                return False
            rep.breaker = BREAKER_HALF_OPEN
            rep.probe_inflight = False
        # half-open: exactly one probe request at a time
        if rep.probe_inflight:
            return False
        rep.probe_inflight = True
        return True

    def _eject_allows_locked(self, rep: Replica, now: float) -> bool:
        """Latency-ejection gate (the breaker's slow twin): an ejected
        replica takes no traffic until its cooldown elapses, then
        exactly ONE probe request at a time decides readmission."""
        if not rep.ejected:
            return True
        if now - rep.ejected_at < self.slow_eject_cooldown_sec:
            return False
        if rep.eject_probe_inflight:
            return False
        rep.eject_probe_inflight = True
        # the probe outcome belongs to THIS dispatch (acquire and
        # release run on one thread end to end)
        rep.eject_probe_tid = threading.get_ident()
        return True

    @staticmethod
    def _giveback_probe_slots_locked(allowed, chosen) -> None:
        """Un-take the single-probe slots of candidates that passed the
        gates but were not picked (both the breaker's half-open slot
        and the ejection's readmission slot)."""
        for r in allowed:
            if r is chosen:
                continue
            if r.breaker == BREAKER_HALF_OPEN and r.probe_inflight:
                r.probe_inflight = False
            if r.ejected and r.eject_probe_inflight:
                r.eject_probe_inflight = False
                r.eject_probe_tid = 0

    def acquire(self, exclude=(), model: str = "") -> Optional[Replica]:
        """Pick the LEAST-LOADED dispatch target (fewest outstanding
        requests) over in-rotation, breaker- and ejection-permitting
        replicas and count it as outstanding.  ``exclude`` removes
        replicas already tried (the retry path); ``model`` restricts
        the pool to replicas HOSTING that catalog model (least-loaded
        within the hosting set — model-aware routing).  Entity-id
        traffic uses :meth:`acquire_specific` on the resolved ring
        owner instead.  Callers MUST pair with :meth:`release`."""
        now = time.monotonic()
        rotation = {r.replica_id for r in self.in_rotation()}
        if model:
            rotation &= self.hosting(model)
        with self._lock:
            candidates = [r for rid, r in self._replicas.items()
                          if rid in rotation and rid not in exclude]
            allowed = []
            for r in candidates:
                if not self._breaker_allows_locked(r, now):
                    continue
                if not self._eject_allows_locked(r, now):
                    # give back the breaker's half-open slot the first
                    # gate just took — a leaked slot blocks every
                    # future breaker probe on this replica
                    if r.breaker == BREAKER_HALF_OPEN and r.probe_inflight:
                        r.probe_inflight = False
                    continue
                allowed.append(r)
            # the gates mark single-probe slots taken; give back the
            # slots of candidates we do not pick
            chosen: Optional[Replica] = None
            if allowed:
                chosen = min(allowed,
                             key=lambda r: (r.outstanding,
                                            r.replica_id))
            self._giveback_probe_slots_locked(allowed, chosen)
            if chosen is None:
                return None
            chosen.outstanding += 1
            return chosen

    def acquire_specific(self, replica_id: str) -> Optional[Replica]:
        """Count a dispatch against ONE named replica (the router's
        split-merge path already resolved ring ownership): in-rotation
        and breaker-permitting, else None.  Pair with :meth:`release`.

        Deliberately NOT ejection-gated: entity-id traffic is sticky by
        design (the owner holds the resident rows — there is no correct
        replica to route around TO), and the invalidate broadcast must
        reach a wedged-but-alive replica or it serves stale rows after
        readmission.  A slow owner answers its entity traffic late;
        latency ejection shapes only the LEAST-LOADED pool, where an
        alternative exists (:meth:`acquire`)."""
        now = time.monotonic()
        rotation = {r.replica_id for r in self.in_rotation()}
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or replica_id not in rotation:
                return None
            if not self._breaker_allows_locked(rep, now):
                return None
            rep.outstanding += 1
            return rep

    def route_ids(self, ids: List, model: str = "") -> Dict[str, List[int]]:
        """Partition entity ids by their consistent-hash owner among
        in-rotation replicas: ``{replica_id: [positions...]}`` in input
        order.  Empty when no replica is available.  ``model`` keys
        ownership per (model, entity): the hash input is prefixed with
        the model name AND the eligible set shrinks to its hosting
        replicas, so each tenant's hot rows concentrate independently.

        Only the ring FRESHNESS check holds the membership lock; the
        per-id hashing runs outside it (the ring's node arrays swap
        atomically on rebuild), so a large id list cannot stall every
        concurrent dispatch/heartbeat behind SHA-1 work."""
        eligible = {r.replica_id for r in self.in_rotation()}
        if model:
            eligible &= self.hosting(model)
        out: Dict[str, List[int]] = {}
        if not eligible:
            return out
        with self._lock:
            if self._ring_stale:
                self._ring.rebuild(sorted(self._replicas))
                self._ring_stale = False
            ring = self._ring
        prefix = f"{model}\x00" if model else ""
        for i, eid in enumerate(ids):
            rid = ring.route(prefix + str(eid), eligible)
            if rid is not None:
                out.setdefault(rid, []).append(i)
        return out

    def _peer_median_lat_locked(self, rep: Replica) -> float:
        """Median of the LEASE-LIVE peers' latency EWMAs — the
        ejection comparator.  Excluding ``rep`` itself matters: in a
        2-replica fleet a median that includes the wedged replica's
        own EWMA can never be exceeded by ``factor >= 2`` no matter
        how slow it gets (b > f*(a+b)/2 is unsatisfiable), silently
        disabling the feature in the most common small-fleet shape.
        Excluding lease-dead members matters too: a killed replica's
        stale (possibly wedged-era) EWMA would otherwise skew the
        comparator forever — only deregister() removes entries.  0.0
        when no live peer has samples (a fleet of one has no 'slow
        relative to whom')."""
        now = time.monotonic()
        vals = sorted(r.lat_ewma for r in self._replicas.values()
                      if r is not rep and r.lat_samples > 0
                      and r.lease_live(now))
        if not vals:
            return 0.0
        n = len(vals)
        return (vals[n // 2] if n % 2
                else 0.5 * (vals[n // 2 - 1] + vals[n // 2]))

    def release(self, rep: Replica, ok: Optional[bool],
                latency: Optional[float] = None) -> None:
        """Report a dispatch outcome: drives load counts, the breaker
        state machine, AND (with ``latency``, successful hops only) the
        per-replica latency EWMA behind slow ejection.  A replica whose
        EWMA exceeds ``slow_eject_factor`` x its PEERS' median leaves
        least-loaded dispatch until a post-cooldown probe comes back
        fast — the stall analog of the breaker, for replicas that never
        FAIL a request but wreck the fleet p99 answering it.

        ``ok=None`` is a NEUTRAL release: the hop was cut short by the
        REQUEST'S deadline budget, not by the replica — load counts and
        probe slots are returned, but neither the breaker nor the EWMA
        is charged (a few tight-budget clients must not trip a healthy
        replica's breaker for everyone else)."""
        from xgboost_tpu.obs import event
        from xgboost_tpu.obs.metrics import fleet_metrics
        tripped = False
        ejected_now = False
        readmitted = False
        with self._lock:
            rep.outstanding = max(0, rep.outstanding - 1)
            if rep.breaker == BREAKER_HALF_OPEN:
                rep.probe_inflight = False
            # probe attribution is by thread token: a concurrent
            # entity-id hop releasing on an ejected replica must not
            # be mistaken for the readmission probe (nor free its slot)
            was_eject_probe = (rep.ejected and rep.eject_probe_inflight
                               and rep.eject_probe_tid
                               == threading.get_ident())
            if was_eject_probe:
                rep.eject_probe_inflight = False
                rep.eject_probe_tid = 0
            if ok is None:
                return
            if ok:
                rep.consecutive_failures = 0
                if rep.breaker != BREAKER_CLOSED:
                    rep.breaker = BREAKER_CLOSED
            else:
                rep.consecutive_failures += 1
                if rep.breaker == BREAKER_HALF_OPEN:
                    # failed probe: back to OPEN for another cooldown
                    rep.breaker = BREAKER_OPEN
                    rep.breaker_opened_at = time.monotonic()
                elif (rep.breaker == BREAKER_CLOSED
                      and rep.consecutive_failures
                      >= self.breaker_failures):
                    rep.breaker = BREAKER_OPEN
                    rep.breaker_opened_at = time.monotonic()
                    tripped = True
                if was_eject_probe:
                    # a FAILED readmission probe stays ejected for
                    # another cooldown (the breaker will handle the
                    # failure side on its own)
                    rep.ejected_at = time.monotonic()
            if ok and latency is not None:
                rep.lat_ewma = (latency if rep.lat_samples == 0
                                else (1 - self.LAT_ALPHA) * rep.lat_ewma
                                + self.LAT_ALPHA * latency)
                rep.lat_samples += 1
                median = self._peer_median_lat_locked(rep)
                if was_eject_probe:
                    if (median <= 0.0
                            or latency <= self.slow_eject_factor * median):
                        # the probe came back at fleet speed: readmit,
                        # and restart the EWMA from the probe (the old
                        # wedged-era average must not re-eject it)
                        rep.ejected = False
                        rep.lat_ewma = latency
                        rep.lat_samples = 1
                        readmitted = True
                    else:
                        rep.ejected_at = time.monotonic()
                elif (not rep.ejected
                      and self.slow_eject_factor > 0.0
                      and rep.lat_samples >= self.EJECT_MIN_SAMPLES
                      and median > 0.0
                      and rep.lat_ewma
                      > self.slow_eject_factor * median):
                    rep.ejected = True
                    rep.ejected_at = time.monotonic()
                    rep.eject_probe_inflight = False
                    ejected_now = True
            state = rep.breaker
            ewma = rep.lat_ewma
            is_ejected = rep.ejected
        fm = fleet_metrics()
        fm.breaker_open.set(rep.replica_id,
                            0.0 if state == BREAKER_CLOSED else 1.0)
        if latency is not None:
            fm.replica_latency.set(rep.replica_id, ewma)
        if ejected_now or readmitted:
            fm.ejected.set(rep.replica_id, 1.0 if is_ejected else 0.0)
        if ejected_now:
            fm.slow_ejections.inc()
            event("fleet.slow_eject", replica_id=rep.replica_id,
                  latency_ewma_ms=round(ewma * 1e3, 3))
        if readmitted:
            event("fleet.slow_readmit", replica_id=rep.replica_id,
                  probe_latency_ms=round(ewma * 1e3, 3))
        if tripped:
            fm.breaker_trips.inc()
            event("fleet.breaker_open", replica_id=rep.replica_id,
                  consecutive_failures=rep.consecutive_failures)

    # ------------------------------------------------------------- health
    def health_check(self, timeout: float = 2.0) -> None:
        """One pass over every lease-live replica's ``/healthz``:
        drain/stopped/unreachable replicas leave rotation, recovered
        ones rejoin, and the reported model hash is recorded (the
        rollout controller reads it).  Called from the router's
        background loop."""
        now = time.monotonic()
        with self._lock:
            targets = [(r.replica_id, r.url)
                       for r in self._replicas.values()
                       if r.lease_live(now)]
        for rid, url in targets:
            ok, state, mhash = self._probe(url, timeout)
            with self._lock:
                rep = self._replicas.get(rid)
                if rep is None or rep.url != url:
                    continue  # deregistered/re-registered mid-probe
                rep.health_ok = ok
                rep.health_state = state
                if mhash:
                    rep.model_hash = mhash
        from xgboost_tpu.obs.metrics import fleet_metrics
        fleet_metrics().members.set(len(self.in_rotation()))

    @staticmethod
    def _probe(url: str, timeout: float):
        """GET /healthz -> (reachable_and_ok, state, model_hash)."""
        try:
            with urllib.request.urlopen(url + "/healthz",
                                        timeout=timeout) as resp:
                h = json.loads(resp.read())
            return True, h.get("state", "serving"), h.get("model_hash")
        except Exception as e:
            # unreachable is exactly the signal this probe exists to
            # turn into "out of rotation"; the reason rides along in
            # the recorded state for /fleet/members
            return False, f"unreachable ({type(e).__name__})", None


class LeaseClient:
    """Replica-side registration/heartbeat client (the worker half of
    the tracker protocol).  Runs a daemon thread that registers with
    the router, renews the lease at ``lease_sec / 3``, and
    RE-registers whenever the router forgot us (router restart, lease
    expiry during a stall) — the ``recover`` path.

    Chaos seams (reliability/faults.py): ``heartbeat_loss`` skips
    renewals (the lease decays and the router drops us from rotation);
    ``replica_kill`` fires ``on_kill`` — ``os._exit(43)`` in a real
    replica process, a server hard-stop in in-process tests."""

    def __init__(self, router_url: str, replica_id: str, self_url: str,
                 model_path: Optional[str] = None,
                 model_hash_fn: Optional[Callable[[], Optional[str]]] = None,
                 models_fn: Optional[Callable[[], dict]] = None,
                 device_fn: Optional[Callable[[], Optional[dict]]] = None,
                 on_kill: Optional[Callable[[], None]] = None):
        self.router_url = router_url.rstrip("/")
        self.replica_id = replica_id
        self.self_url = self_url.rstrip("/")
        self.model_path = model_path
        self.model_hash_fn = model_hash_fn or (lambda: None)
        # catalog advertisement: () -> {name: {"path":..., "hash":...}}
        # carried on register AND every heartbeat (rollouts move
        # hashes, placement deltas move whole entries)
        self.models_fn = models_fn or (lambda: None)
        # device budget advertisement: () -> {"budget_bytes":..,
        # "used_bytes":..} — the placer bin-packs against this
        self.device_fn = device_fn or (lambda: None)
        self.on_kill = on_kill or (lambda: os._exit(REPLICA_KILL_RC))
        self.lease_sec = 10.0
        self.registered = False
        self.heartbeats_sent = 0
        self.heartbeats_skipped = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ protocol
    def _post(self, path: str, payload: dict, timeout: float = 3.0) -> dict:
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.router_url + path, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    def register(self) -> bool:
        """One registration attempt; returns success."""
        try:
            grant = self._post("/fleet/register", {
                "replica_id": self.replica_id,
                "url": self.self_url,
                "model_path": self.model_path,
                "model_hash": self.model_hash_fn(),
                "models": self.models_fn(),
                "device": self.device_fn(),
                "pid": os.getpid(),
            })
            self.lease_sec = float(grant.get("lease_sec", self.lease_sec))
            self.registered = True
            return True
        except Exception as e:
            # router down/unreachable: stay up and keep retrying — a
            # replica must serve direct traffic even with no router
            from xgboost_tpu.obs.metrics import swallowed_error
            swallowed_error("fleet.lease_client.register", e)
            self.registered = False
            return False

    def _heartbeat_once(self) -> None:
        from xgboost_tpu.reliability import faults
        faults.check("replica_kill", path=self.replica_id)
        try:
            faults.check("heartbeat_loss", path=self.replica_id)
        except faults.InjectedFault:
            # chaos: lose this renewal — the lease decays toward expiry
            self.heartbeats_skipped += 1
            return
        try:
            resp = self._post("/fleet/heartbeat",
                              {"replica_id": self.replica_id,
                               "model_hash": self.model_hash_fn(),
                               "models": self.models_fn(),
                               "device": self.device_fn()})
            self.heartbeats_sent += 1
            if not resp.get("known", True):
                # the router forgot us (restart / expired lease):
                # recover by re-registering
                self.register()
        except Exception as e:
            from xgboost_tpu.obs.metrics import swallowed_error
            swallowed_error("fleet.lease_client.heartbeat", e)
            self.registered = False

    def deregister(self) -> None:
        """Announce shutdown (the drain path calls this)."""
        try:
            self._post("/fleet/deregister",
                       {"replica_id": self.replica_id})
        except Exception as e:
            from xgboost_tpu.obs.metrics import swallowed_error
            swallowed_error("fleet.lease_client.deregister", e)
        self.registered = False

    # ----------------------------------------------------------- lifecycle
    def _loop(self) -> None:
        from xgboost_tpu.reliability import faults
        from xgboost_tpu.reliability.deadline import jittered
        # lease/3 nominal, ±20% jitter: a fleet restarted together must
        # not renew in lockstep forever (every heartbeat tick would be
        # a synchronized burst at the router)
        while not self._stop.wait(
                jittered(max(self.lease_sec / 3.0, 0.05))):
            try:
                if not self.registered:
                    self.register()
                else:
                    self._heartbeat_once()
            except faults.InjectedFault as f:
                if f.kind == "replica_kill":
                    # simulated sudden death: no drain, no deregister —
                    # the router must notice via lease/health alone
                    self.on_kill()
                    return

    def start(self) -> "LeaseClient":
        self.register()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="xgbtpu-fleet-lease")
        self._thread.start()
        return self

    def stop(self, deregister: bool = True) -> None:
        self._stop.set()
        if deregister and self.registered:
            self.deregister()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None

"""Quantize a DMatrix into dense bin-id device arrays.

This is the TPU-native representational shift (SURVEY.md §7): instead of
the reference's CSR/CSC sorted-column scans
(``src/tree/updater_colmaker-inl.hpp:362-414``), data is quantized ONCE
per training run using the weighted quantile sketch and stored as a dense
``(n_rows, n_features)`` array of small-int bin ids in HBM.  All tree
growth then operates on bins (histogram method — the reference's own
scalable path, ``learner-inl.hpp:91-97``).

Binning scheme:
  - bin 0 is reserved for MISSING (absent CSR entries — the reference's
    missing-value semantics with learned default direction,
    ``model.h:555-566``).
  - a present value v maps to bin ``1 + searchsorted(cuts_f, v, 'right')``.
  - a split at cut index j of feature f sends rows left iff ``v < cuts_f[j]``
    ⇔ ``bin(v) <= j + 1``; missing rows follow the learned default.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Optional

import numpy as np

from xgboost_tpu.data import DMatrix
from xgboost_tpu.sketch import (QuantileSummary, make_summary, prune_summary,
                                propose_cuts, sketch_column)

# Auto bin alignment trims at most this many cuts (the measured win is
# landing on the sublane multiple just BELOW the proposed count; see
# align_cut_lists).  Single source of truth — the learner and
# compute_cuts both defer to this default.
DEFAULT_TRIM_MARGIN = 4


@dataclasses.dataclass
class CutMatrix:
    """Per-feature cut points, padded to a rectangle for device use.

    cut_values[f, j] for j < n_cuts[f] are strictly increasing; padding is
    +inf (so searchsorted against the padded row is still correct).
    """

    cut_values: np.ndarray  # (F, max_cuts) float32, +inf padded
    n_cuts: np.ndarray      # (F,) int32

    @property
    def num_feature(self) -> int:
        return self.cut_values.shape[0]

    @property
    def max_bin(self) -> int:
        # value bins 1..max_cuts+1 plus missing bin 0
        return self.cut_values.shape[1] + 2


def compute_cuts(dmat: DMatrix, max_bin: int = 256, sketch_eps: float = 0.03,
                 sketch_ratio: float = 2.0,
                 hess_weights: Optional[np.ndarray] = None,
                 bin_align: int = 0,
                 bin_align_margin: Optional[int] = DEFAULT_TRIM_MARGIN
                 ) -> CutMatrix:
    """Propose cut points for every feature via the weighted quantile sketch.

    Replaces the reference's per-round distributed sketch + cut proposal
    (``updater_histmaker-inl.hpp:353-462``) with one global pass; the
    summary machinery (merge/prune bounds) is identical.  ``bin_align``
    (learner-selected on TPU) aligns the bin count for the int8
    histogram kernel — see :func:`align_cut_lists`.
    """
    F = dmat.num_col
    per_feature = []
    for f in range(F):
        rows, vals = dmat.column_values(f)
        w = None if hess_weights is None else hess_weights[rows]
        if len(vals) > (1 << 16):
            summary = sketch_column(vals, w, sketch_eps, sketch_ratio)
        else:
            summary = prune_summary(
                make_summary(vals, w),
                max(2, int(sketch_ratio / max(sketch_eps, 1.0 / max_bin))))
        cuts = propose_cuts(summary, max_bin - 1)  # leave room for missing bin
        per_feature.append(cuts)
    return pack_cuts(align_cut_lists(per_feature, bin_align,
                                     bin_align_margin))


def align_cut_lists(per_feature, quantum: int = 32,
                    trim_margin: Optional[int] = DEFAULT_TRIM_MARGIN):
    """Trim the densest features' cut lists so the total bin count
    ``max_cuts + 2`` lands on a multiple of ``quantum``.

    The int8 MXU histogram kernel's one-hot operand tiles sublanes in
    32s: B = 67 bins occupy 96 physical sublanes, B = 64 occupy 64 —
    a measured ~19% round-rate difference at the bench shape for a
    3-cut resolution change (tools/hist_r5_ab.py; higgs-1M AUC is
    unchanged at the bench's precision).  Trimmed features keep evenly
    rank-spaced cuts (quantile-uniform coverage).  No-op when quantum
    is 0, when already aligned, or when the aligned count would drop
    below 8 cuts.

    ``trim_margin`` caps how many cuts may be trimmed: the win only
    exists when B sits just ABOVE a sublane multiple (67 -> 64 frees a
    whole 32-sublane tier for 3 cuts of resolution).  B = 63 is 31
    above the lower multiple — trimming to 32 would halve histogram
    resolution to save a single padded sublane, so alignment is
    skipped and the kernel pads instead (advisor finding, round 4).
    ``trim_margin=None`` removes the cap (explicit hist_bin_align>0
    opts into unconditional alignment).
    """
    if quantum <= 0 or not per_feature:
        return per_feature
    B = max((len(c) for c in per_feature), default=1) + 2
    excess = B % quantum
    if excess == 0:
        return per_feature
    if trim_margin is not None and excess > trim_margin:
        # Keeping all cuts costs quantum - excess padded sublanes in the
        # kernel — cheaper than losing `excess` cuts of resolution.
        return per_feature
    target = (B // quantum) * quantum - 2    # cuts so B % quantum == 0
    if target < 8:
        return per_feature
    out = []
    for cuts in per_feature:
        if len(cuts) > target:
            idx = np.unique(np.round(
                np.linspace(0, len(cuts) - 1, target)).astype(np.int64))
            cuts = np.asarray(cuts)[idx]
        out.append(cuts)
    return out


def _rank0() -> bool:
    """Rank-gate library-level warnings (the CLI silences rank != 0)."""
    try:
        import jax
        return jax.process_index() == 0
    except Exception as e:
        # no backend yet (or none at all): act as rank 0 so the warning
        # still prints somewhere; the probe failure itself is counted
        from xgboost_tpu.obs.metrics import swallowed_error
        swallowed_error("binning.rank0_probe", e, emit_event=False)
        return True


def pack_cuts(per_feature) -> CutMatrix:
    """Pack per-feature cut lists into an inf-padded rectangular CutMatrix."""
    F = len(per_feature)
    max_cuts = max(1, max((len(c) for c in per_feature), default=1))
    cut_values = np.full((F, max_cuts), np.inf, dtype=np.float32)
    n_cuts = np.zeros(F, dtype=np.int32)
    for f, cuts in enumerate(per_feature):
        cut_values[f, :len(cuts)] = cuts
        n_cuts[f] = len(cuts)
    return CutMatrix(cut_values, n_cuts)


def compute_cuts_exact(dmat: DMatrix, max_exact_bin: int = 4096) -> CutMatrix:
    """Cuts at EVERY distinct feature value — exact greedy as quantization.

    Enumerating a split before each distinct value is the same candidate
    set as the reference's sorted-column forward scan
    (``updater_colmaker-inl.hpp:362-414``); the sequential scan itself
    does not vectorize, but with cuts at all distinct values the
    histogram updater enumerates the identical partitions (only the
    recorded threshold differs: the reference stores a midpoint, we store
    the distinct value).  Features with more than ``max_exact_bin``
    distinct values fall back to that many quantile cuts.
    """
    F = dmat.num_col
    per_feature = []
    n_capped = 0
    for f in range(F):
        _, vals = dmat.column_values(f)
        uniq = np.unique(vals)
        if len(uniq) > max_exact_bin:
            n_capped += 1
            cuts = propose_cuts(
                prune_summary(make_summary(vals), 2 * max_exact_bin),
                max_exact_bin)
        else:
            # every distinct value is a cut, INCLUDING the minimum: the
            # "v < min" split separates nothing among present values but
            # with the learned default direction it is the
            # missing-vs-present split — essential for sparse indicator
            # features (all-ones columns in libsvm one-hot data)
            cuts = uniq.astype(np.float32)
        per_feature.append(cuts)
    if n_capped and _rank0():
        print(f"[grow_colmaker] {n_capped}/{F} features exceed "
              f"max_exact_bin={max_exact_bin} distinct values and were "
              "quantized to that many cuts — dsplit=row exact mode is "
              "approximate past the cap (single-controller AND "
              "dsplit=col training use the uncapped exact grower; the "
              "reference itself runs histmaker, not exact, under row "
              "split)", file=sys.stderr)
    return pack_cuts(per_feature)


def bin_matrix(dmat: DMatrix, cuts: CutMatrix) -> np.ndarray:
    """Quantize to a dense (n_rows, F) bin-id array (0 = missing)."""
    n, F = dmat.num_row, cuts.num_feature
    dtype = np.uint8 if cuts.max_bin <= 256 else np.uint16
    out = np.zeros((n, F), dtype=dtype)
    rows = np.repeat(np.arange(n, dtype=np.int64), np.diff(dmat.indptr))
    cols = dmat.indices
    # explicitly-stored NaNs are missing (bin 0) — same as an absent CSR
    # entry and as bin_dense_device's isnan mask (searchsorted would
    # otherwise send NaN to the LAST bin, routing the same data
    # differently depending on which quantizer ran; advisor, round 4)
    in_range = (cols < F) & ~np.isnan(dmat.values)
    rows, cols, vals = rows[in_range], cols[in_range], dmat.values[in_range]
    for f in range(F):
        m = cols == f
        if not m.any():
            continue
        b = 1 + np.searchsorted(cuts.cut_values[f, :cuts.n_cuts[f]],
                                vals[m], side="right")
        out[rows[m], f] = b.astype(dtype)
    return out


def bin_dense_device(X, cut_values):
    """Device-side quantization of a dense (N, F) float matrix (NaN =
    missing -> bin 0): ``1 + #{c: x >= cut[c]}`` — identical to the
    host ``searchsorted(side="right")`` since cut lists are sorted and
    inf-padded.  One fused (N, F, C) compare-reduce: ~2 ms at 1M x 28
    on v5e where the host loop takes seconds (prediction-time path;
    PROFILE.md round 4)."""
    import jax
    import jax.numpy as jnp
    X = jnp.asarray(X, jnp.float32)
    cv = jnp.asarray(cut_values, jnp.float32)
    # the +inf PADDING columns must not count: x=+inf satisfies
    # inf >= inf, which would yield bin 1 + max_cuts instead of the
    # host searchsorted's 1 + n_cuts[f] (real cuts are finite — they
    # come from sketch summaries, which filter non-finite values)
    b = 1 + jnp.sum((X[:, :, None] >= cv[None, :, :])
                    & jnp.isfinite(cv)[None, :, :],
                    axis=2).astype(jnp.int32)
    b = jnp.where(jnp.isnan(X), 0, b)
    return b.astype(jnp.uint8 if cv.shape[1] + 2 <= 256 else jnp.uint16)


def bin_dense(X: np.ndarray, cuts: CutMatrix, missing: float = np.nan) -> np.ndarray:
    """Quantize a dense float matrix directly (prediction-time fast path)."""
    n, F = X.shape
    dtype = np.uint8 if cuts.max_bin <= 256 else np.uint16
    out = np.zeros((n, F), dtype=dtype)
    for f in range(min(F, cuts.num_feature)):
        col = X[:, f]
        present = ~np.isnan(col) if np.isnan(missing) else col != missing
        b = 1 + np.searchsorted(cuts.cut_values[f, :cuts.n_cuts[f]],
                                col[present], side="right")
        out[present, f] = b.astype(dtype)
    return out

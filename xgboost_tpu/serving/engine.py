"""PredictEngine: recompile-free batched prediction on a loaded model.

The serving core (SERVING.md): a model is loaded ONCE, its tree stack
pinned on device, and every incoming batch is padded up to a small set
of power-of-two row buckets so the margin computation always runs an
already-compiled executable.  The per-bucket executables are built with
the jax AOT API (``jit(...).lower(...).compile()``): calling a compiled
executable can never retrace or recompile, so after :meth:`warmup` the
steady state is zero compiles by construction (tested via
``jax.monitoring`` compile events in tests/test_serving.py).

Bitwise parity: tree traversal, margin accumulation and the objective's
pred_transform are all row-independent, so the unpadded rows of a
padded batch are bit-identical to ``Learner.predict`` on the same rows
(padding rows ride along on bin 0 and are sliced off host-side).

Round 7 (the transfer wall): bucket executables default to the FUSED
quantize+traverse program — raw f32 rows (plus the device-resident cut
matrix) in, margins out, quantize in-graph — killing the per-request
host ``bin_matrix`` pass, and ``predict_resident`` runs the same
executables on device-resident feature-store rows with zero upload.
``XGBTPU_SERVE_FUSED=0`` restores the host-quantize two-step baseline.
"""

from __future__ import annotations

import os
import threading
import time as _time
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

DEFAULT_MIN_BUCKET = 8
DEFAULT_MAX_BUCKET = 8192


def power_of_two_buckets(min_bucket: int = DEFAULT_MIN_BUCKET,
                         max_bucket: int = DEFAULT_MAX_BUCKET) -> List[int]:
    """The default shape-bucket ladder: powers of two within
    [min_bucket, max_bucket].  ``max_bucket`` is a HARD cap (operators
    set it to bound device memory): a non-power-of-two max truncates
    the ladder below it, and larger requests chunk through the top
    bucket.  When no power of two fits the range, the single bucket is
    ``max_bucket`` itself (buckets need not be powers of two)."""
    if min_bucket < 1 or max_bucket < min_bucket:
        raise ValueError(f"bad bucket range {min_bucket}:{max_bucket}")
    b, out = 1, []
    while b < min_bucket:
        b <<= 1
    while b <= max_bucket:
        out.append(b)
        b <<= 1
    return out or [max_bucket]


def pad_to_width(X: np.ndarray, num_feature: int) -> np.ndarray:
    """NaN-pad narrow feature rows to the model's width (NaN = missing
    quantizes to bin 0 on every path).  The ONE definition of
    missing-width semantics — the fused/two-step engine payloads and
    the feature store all route through it."""
    if X.shape[1] < num_feature:
        X = np.pad(X, ((0, 0), (0, num_feature - X.shape[1])),
                   constant_values=np.nan)
    return X


class PredictEngine:
    """Batched, recompile-free prediction over one loaded model.

    Args:
      model: a model file path, raw model bytes, or a trained/loaded
        :class:`~xgboost_tpu.learner.Booster`.
      buckets: explicit row-bucket ladder (sorted ascending); default is
        powers of two ``min_bucket..max_bucket``.  Requests larger than
        the top bucket are chunked through it.
      warmup: pre-compile (and execute once) every bucket at
        construction so the first real request already hits the cache.
      metrics: optional :class:`xgboost_tpu.obs.ServingMetrics`.
    """

    def __init__(self, model, buckets: Optional[Sequence[int]] = None,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 max_bucket: int = DEFAULT_MAX_BUCKET,
                 warmup: bool = False, metrics=None,
                 fused: Optional[bool] = None):
        from xgboost_tpu.learner import Booster
        if isinstance(model, Booster):
            booster = model
        else:
            booster = Booster()
            if isinstance(model, (bytes, bytearray)):
                booster.load_raw(bytes(model))
            else:
                booster.load_model(model)
        if booster.gbtree is None:
            raise ValueError("PredictEngine needs a trained/loaded model")
        if booster.param.booster == "gblinear":
            raise NotImplementedError(
                "PredictEngine serves gbtree models (binned tree "
                "traversal); gblinear predict is already a single matmul "
                "— serve it via Learner.predict")
        if getattr(booster.gbtree, "exact_raw", False):
            raise NotImplementedError(
                "exact-mode (grow_colmaker) models route on raw values; "
                "the serving engine's binned bucket cache does not apply")
        self.booster = booster
        self.gbtree = booster.gbtree
        self.obj = booster.obj
        self.cuts = self.gbtree.cuts
        self.num_feature = (booster.num_feature
                            or self.cuts.num_feature)
        self._K = max(1, booster.param.num_output_group)
        self._max_depth = self.gbtree.cfg.max_depth
        self._n_roots = self.gbtree.cfg.n_roots
        self.buckets = (sorted(set(int(b) for b in buckets)) if buckets
                        else power_of_two_buckets(min_bucket, max_bucket))
        if self.buckets[0] < 1:
            raise ValueError("buckets must be >= 1")
        self.metrics = metrics
        self.compile_count = 0          # bumped at the ONLY compile site
        self._compiled: Dict[int, object] = {}   # bucket rows -> executable
        self._base_cache: Dict[int, object] = {}  # bucket rows -> (B, K) base
        self._lock = threading.Lock()
        # device-resident model state, uploaded once
        import jax
        import jax.numpy as jnp
        self._stack, self._group = self.gbtree._stack(0)
        self._bin_dtype = (np.uint8 if self.cuts.max_bin <= 256
                           else np.uint16)
        self._base_scalar = float(
            self.obj.prob_to_margin(booster.param.base_score))
        self._jax, self._jnp = jax, jnp
        # chunked tree-parallel traversal layout (models/tree.py): the
        # serving T is fixed, so the chunk count is a constant of the
        # engine — recorded on serve.predict spans and used to
        # attribute per-chunk traversal seconds in /metrics
        from xgboost_tpu.models.tree import predict_chunk_layout
        self._tree_chunk = self.gbtree.pred_chunk
        _, _, self._n_chunks = predict_chunk_layout(
            int(self._stack.feature.shape[0]), max(self._tree_chunk, 1))
        # FUSED quantize+traverse buckets (round 7): the executable
        # takes RAW f32 rows + the device-resident cut matrix and
        # quantizes in-graph — no host bin_matrix pass per request, and
        # the same executables serve device-resident feature-store rows
        # with zero upload (predict_resident).  Bit-parity with the
        # two-step path holds because the in-graph quantize IS
        # binning.bin_dense_device; ``XGBTPU_SERVE_FUSED=0`` (or
        # fused=False) restores the host-quantize baseline.
        if fused is None:
            fused = os.environ.get("XGBTPU_SERVE_FUSED", "1") != "0"
        self._fused = bool(fused)
        self._cuts_dev = self.gbtree.cut_values_dev
        self._warming = False
        if warmup:
            self.warmup()

    # ------------------------------------------------------------- buckets
    def bucket_for(self, n_rows: int) -> int:
        """Smallest bucket >= n_rows (the top bucket for larger batches;
        callers chunk through it)."""
        i = bisect_left(self.buckets, n_rows)
        return self.buckets[min(i, len(self.buckets) - 1)]

    # ------------------------------------------------------------- compile
    def _margin_fn(self):
        from xgboost_tpu.models.tree import (predict_margin_binned,
                                             predict_margin_fused)
        max_depth, K, n_roots = self._max_depth, self._K, self._n_roots
        tree_chunk = self._tree_chunk

        if self._fused:
            def fn(stack, group, X, cut_values, base):
                return predict_margin_fused(stack, group, X, cut_values,
                                            base, max_depth, K,
                                            n_roots=n_roots,
                                            tree_chunk=tree_chunk)
            return fn

        def fn(stack, group, binned, base):
            return predict_margin_binned(stack, group, binned, base,
                                         max_depth, K, n_roots=n_roots,
                                         tree_chunk=tree_chunk)
        return fn

    def _executable(self, bucket: int):
        """The AOT-compiled margin executable for one row bucket (fused:
        raw f32 rows + cut matrix in; two-step: pre-binned ids in)."""
        exe = self._compiled.get(bucket)
        if exe is not None:
            return exe
        with self._lock:
            exe = self._compiled.get(bucket)
            if exe is not None:
                return exe
            import jax
            base_aval = jax.ShapeDtypeStruct(
                (bucket, self._K), np.float32)
            if self._fused:
                x_aval = jax.ShapeDtypeStruct(
                    (bucket, self.cuts.num_feature), np.float32)
                exe = jax.jit(self._margin_fn()).lower(
                    self._stack, self._group, x_aval, self._cuts_dev,
                    base_aval).compile()
            else:
                binned_aval = jax.ShapeDtypeStruct(
                    (bucket, self.cuts.num_feature), self._bin_dtype)
                exe = jax.jit(self._margin_fn()).lower(
                    self._stack, self._group, binned_aval,
                    base_aval).compile()
            self.compile_count += 1
            if self.metrics is not None:
                self.metrics.compiles.inc()
            self._compiled[bucket] = exe
            return exe

    def _base_for(self, bucket: int):
        base = self._base_cache.get(bucket)
        if base is None:
            base = self._jnp.full((bucket, self._K), self._base_scalar,
                                  self._jnp.float32)
            self._base_cache[bucket] = base
        return base

    def warmup(self) -> None:
        """Pre-compile every bucket AND run each once end to end, so the
        transform/eager-op caches are hot too (a reloaded model warms up
        OFF the serving path before the registry swaps it in).

        Row/padding counters are suppressed for the warmup rows — they
        count "real (caller-supplied) rows", and a reload would
        otherwise burst ~2x sum(buckets) phantom rows into dashboards;
        ``compiles_total`` still counts (it is the warmup's product)."""
        F = self.cuts.num_feature
        saved, self.metrics = self.metrics, None
        self._warming = True
        c0 = self.compile_count
        try:
            for b in self.buckets:
                self.predict(np.zeros((b, F), np.float32))
                self.predict(np.zeros((b, F), np.float32),
                             output_margin=True)
        finally:
            self.metrics = saved
            self._warming = False
            if saved is not None and self.compile_count > c0:
                saved.compiles.inc(self.compile_count - c0)

    # ------------------------------------------------------------- predict
    def predict(self, X, output_margin: bool = False) -> np.ndarray:
        """Predict a 2-D float batch; bitwise-equal to
        ``booster.predict(DMatrix(X))`` on the supplied rows."""
        X = np.asarray(X, np.float32)
        if X.ndim != 2:
            raise ValueError(f"expected 2-D rows, got shape {X.shape}")
        if X.shape[1] > self.num_feature:
            raise ValueError(
                f"data has {X.shape[1]} features, model was trained "
                f"with {self.num_feature}")
        n = X.shape[0]
        if n == 0:
            # run the objective transform on a 0-row margin so the empty
            # result's shape/dtype matches non-empty calls exactly (e.g.
            # multi:softmax argmax squeezes to (n,), not (n, K))
            out = np.asarray(self.obj.pred_transform(
                self._jnp.zeros((0, self._K), self._jnp.float32),
                output_margin=output_margin))
            if out.ndim == 2 and out.shape[1] == 1:
                out = out[:, 0]
            return out
        top = self.buckets[-1]
        if n > top:  # chunk oversized batches through the top bucket
            parts = [self.predict(X[i:i + top], output_margin)
                     for i in range(0, n, top)]
            return np.concatenate(parts, axis=0)
        bucket = self.bucket_for(n)
        if self._fused:
            # raw f32 rows upload; quantize happens IN the executable.
            # Padding (rows and missing columns) is NaN -> bin 0,
            # matching the two-step path's zero-bin padding.
            payload = pad_to_width(X, self.num_feature)
            if bucket > n:
                payload = np.pad(payload, ((0, bucket - n), (0, 0)),
                                 constant_values=np.nan)
        else:
            payload = self._bin(X)
            if bucket > n:
                payload = np.pad(payload, ((0, bucket - n), (0, 0)))
        if self.metrics is not None:
            self.metrics.rows.inc(n)
            self.metrics.padded_rows.inc(bucket - n)
        # the innermost serving span: the device margin computation,
        # nested under serve.batch -> serve.request when the event log
        # is on (a no-op otherwise).  The executable is resolved BEFORE
        # the timed region (a first-touch bucket compile would dwarf
        # every real traversal sample), and the launch is blocked on so
        # the per-chunk histogram measures device time, not async
        # dispatch — the transform right after would sync here anyway.
        # Warmup traffic is suppressed like the ServingMetrics row
        # counters (phantom rows + warm-path cache effects).
        from xgboost_tpu.obs.metrics import (predict_metrics,
                                             timed_device_put)
        pm = None if self._warming else predict_metrics()
        exe = self._executable(bucket)
        # the batch upload stays OUTSIDE the timed traversal region and
        # is blocked on + accounted separately (transfer counters): the
        # chunk histogram must attribute TRAVERSAL, not transfer — the
        # cost split the transfer-wall work exists to pin
        dev = timed_device_put(
            payload, pm.observe_transfer if pm is not None else None)
        return self._margin_out(exe, dev, bucket, n, output_margin,
                                pm, transfer_bytes=payload.nbytes)

    def predict_resident(self, X_dev, n: int,
                         output_margin: bool = False) -> np.ndarray:
        """Predict a DEVICE-resident ``(bucket, F)`` f32 block with ZERO
        host→device feature bytes — the feature-store fast path
        (serving/featurestore.py): rows were uploaded once at ``put``
        time, gathered on device by entity id, and quantize+traverse
        runs in the same AOT bucket executables ``predict`` uses, so
        results are bit-identical to uploading the same rows.  Rows
        past ``n`` are padding (NaN rows -> bin 0), sliced off
        host-side.  The block's row count must be a warmed bucket
        (callers pad via :meth:`bucket_for`) — steady state stays
        zero-compile AND zero-upload."""
        bucket = int(X_dev.shape[0])
        if self.metrics is not None:
            self.metrics.rows.inc(n)
            self.metrics.padded_rows.inc(bucket - n)
        from xgboost_tpu.obs.metrics import predict_metrics
        pm = None if self._warming else predict_metrics()
        exe = self._executable(bucket)
        if not self._fused:
            # two-step engines quantize ON DEVICE (eager, outside the
            # executable) — still zero feature upload
            from xgboost_tpu.binning import bin_dense_device
            X_dev = bin_dense_device(X_dev, self._cuts_dev)
        return self._margin_out(exe, X_dev, bucket, n, output_margin,
                                pm, transfer_bytes=0)

    def _margin_out(self, exe, operand, bucket: int, n: int,
                    output_margin: bool, pm,
                    transfer_bytes: int) -> np.ndarray:
        """Run one bucket executable and transform: the shared tail of
        ``predict`` (host batch) and ``predict_resident`` (store rows).
        """
        from xgboost_tpu.obs import span
        with span("serve.predict", rows=n, bucket=bucket,
                  chunk=self._tree_chunk, chunks=self._n_chunks,
                  fused=self._fused, transfer_bytes=transfer_bytes):
            t0 = _time.perf_counter()
            if self._fused:
                margin = exe(self._stack, self._group, operand,
                             self._cuts_dev, self._base_for(bucket))
            else:
                margin = exe(self._stack, self._group, operand,
                             self._base_for(bucket))
            self._jax.block_until_ready(margin)
            if pm is not None:
                pm.chunk_seconds.observe(
                    (_time.perf_counter() - t0) / max(self._n_chunks, 1))
        if pm is not None:
            pm.rows.inc(n)
        # the transform runs OUTSIDE the compiled margin executable, via
        # the objective's own (row-independent) ops — the exact functions
        # Learner.predict dispatches, so rounding matches bit for bit
        out = np.asarray(self.obj.pred_transform(
            margin, output_margin=output_margin))[:n]
        if out.ndim == 2 and out.shape[1] == 1:
            out = out[:, 0]
        return out

    # ------------------------------------------------------------- binning
    def _bin(self, X: np.ndarray) -> np.ndarray:
        """Host-side quantization of dense float rows (NaN = missing ->
        bin 0), width-padded to the model's feature count."""
        from xgboost_tpu.binning import bin_matrix
        from xgboost_tpu.data import DMatrix
        return bin_matrix(DMatrix(pad_to_width(X, self.num_feature)),
                          self.cuts)

    # ------------------------------------------------------------- info
    @property
    def num_compiled(self) -> int:
        return len(self._compiled)

    def device_bytes(self) -> int:
        """Estimated device bytes this engine pins: the uploaded tree
        stack + cut matrix + cached base blocks, plus per-compiled-
        bucket operand/result buffers.  An estimate (XLA's own
        executable footprint is not visible from here), but consistent
        across models — what the catalog's shared ``serve_catalog_mb``
        budget meters (catalog/catalog.py)."""
        import jax
        n = 0
        for leaf in jax.tree_util.tree_leaves((self._stack, self._group)):
            n += getattr(leaf, "nbytes", 0)
        n += getattr(self._cuts_dev, "nbytes", 0)
        for base in self._base_cache.values():
            n += getattr(base, "nbytes", 0)
        F, K = self.cuts.num_feature, self._K
        for bucket in self._compiled:
            n += bucket * (F + K) * 4  # f32 operand + margin per bucket
        return int(n)

    def describe(self) -> dict:
        return {
            "buckets": list(self.buckets),
            "compiled": sorted(self._compiled),
            "compile_count": self.compile_count,
            "num_feature": self.num_feature,
            "num_trees": self.gbtree.num_trees,
            "objective": self.booster.param.objective,
            "tree_chunk": self._tree_chunk,
            "tree_chunks": self._n_chunks,
            "fused": self._fused,
        }

"""Entry point: ``python -m xgboost_tpu.serving --model m.bin --port 8080``.

Flag names map 1:1 onto the classic CLI's ``task=serve`` parameters
(``serve_port=...`` -> ``--port``); both surfaces are generated from
``xgboost_tpu.config.SERVE_PARAMS``.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from xgboost_tpu.config import SERVE_PARAMS


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m xgboost_tpu.serving",
        description="Serve an xgboost_tpu model over HTTP "
                    "(batched, recompile-free; see SERVING.md)")
    p.add_argument("--model", required=True,
                   help="model file to serve (watched for hot-reload)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress startup banner and access logs")
    for name, (default, help_) in SERVE_PARAMS.items():
        flag = "--" + name[len("serve_"):].replace("_", "-")
        p.add_argument(flag, type=type(default), default=default,
                       help=f"{help_} (default {default})")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    from xgboost_tpu.serving import run_server
    run_server(args.model, host=args.host, port=args.port,
               min_bucket=args.min_bucket, max_bucket=args.max_bucket,
               max_batch_rows=args.max_batch_rows,
               max_wait_ms=args.max_wait_ms,
               max_queue_rows=args.queue_rows, poll_sec=args.poll_sec,
               keep_versions=args.keep_versions,
               warmup=bool(args.warmup), drain_sec=args.drain_sec,
               max_body_mb=args.max_body_mb,
               featurestore_mb=args.featurestore_mb,
               quiet=args.quiet, block=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Model registry: watch a model path, hot-reload atomically, keep
previous versions for instant rollback.

Reload protocol (the "load + warm OFF the serving path, then swap a
reference" design, SERVING.md):

1. a poll notices the file changed (mtime/size fast path, content hash
   to confirm — a rewrite with identical bytes is NOT a reload);
2. the new model is loaded into a FRESH :class:`PredictEngine` and
   warmed (all buckets compiled + executed) while the old engine keeps
   serving;
3. one reference assignment swaps the engines.  In-flight batches hold
   the old engine reference and finish on it — no request ever sees a
   half-loaded model;
4. the old (version, engine) pair is pushed onto a bounded rollback
   ring (``keep_versions`` deep); :meth:`rollback` swaps it straight
   back without touching disk.

Failure paths (RELIABILITY.md): file bytes are CRC-verified BEFORE any
engine build, and content that fails to load is remembered as a
poisoned fingerprint — hashed-and-rejected on later polls instead of
re-built and re-warmed every second — until the file changes again.
``last_reload_error``/``reload_failures`` feed the HTTP ``/healthz``
degraded state.
"""

from __future__ import annotations

import hashlib
import os
import sys
import threading
from collections import deque
from typing import Optional, Tuple

import numpy as np

from xgboost_tpu.obs import event, span
from xgboost_tpu.reliability import faults
from xgboost_tpu.reliability.integrity import read_file, verify_model_bytes
from xgboost_tpu.serving.engine import PredictEngine


class VersionedArray(np.ndarray):
    """ndarray tagged with the model version that PRODUCED it.  The tag
    survives slicing (the batcher scatters one batch's output across
    callers), so a response's ``model_version`` names the model that
    actually ran — not whatever was current when the request arrived,
    which can differ across a hot-reload."""

    model_version: int = 0

    def __array_finalize__(self, obj):
        self.model_version = getattr(obj, "model_version", 0)

    @classmethod
    def tag(cls, arr: np.ndarray, version: int) -> "VersionedArray":
        out = np.asarray(arr).view(cls)
        out.model_version = version
        return out


class ModelRegistry:
    """Owns the live engine + its predecessors for one model path."""

    def __init__(self, path: str, keep_versions: int = 2,
                 warmup: bool = True, poll_sec: float = 1.0,
                 metrics=None, **engine_kwargs):
        self.path = path
        self.keep_versions = int(keep_versions)
        self.warmup = bool(warmup)
        self.poll_sec = float(poll_sec)
        self.metrics = metrics
        self.engine_kwargs = engine_kwargs
        self.version = 0
        self._engine: Optional[PredictEngine] = None
        # the content hash of the model the live engine was BUILT from
        # (not necessarily the on-disk file's — a rollback diverges
        # them): what /healthz reports and the fleet rollout controller
        # verifies (fleet/rollout.py)
        self._hash: Optional[str] = None
        self._previous: deque = deque(maxlen=max(0, self.keep_versions))
        self._fp: Optional[Tuple] = None
        # the failure-path ledger: the fingerprint of content that
        # failed to load (so it is never re-built until the file changes
        # AGAIN), plus what /healthz reports about it
        self._poisoned: Optional[Tuple] = None
        self.last_reload_error: Optional[str] = None
        self.reload_failures = 0
        self.build_attempts = 0
        self._reload_lock = threading.Lock()   # one reload at a time
        self._swap_lock = threading.Lock()     # guards engine/version swap
        self._stop = threading.Event()
        self._poller: Optional[threading.Thread] = None
        self._load_initial()

    # ------------------------------------------------------------- loading
    def _read_fingerprinted(self) -> Tuple[bytes, Tuple]:
        """One read of the watched file -> (raw bytes, (mtime_ns, size,
        sha256)).  The same bytes feed verification AND the engine
        build, so the content that was hashed is the content that
        loads — no torn-rewrite race between a hash pass and a second
        read."""
        st = os.stat(self.path)
        raw = read_file(self.path)
        return raw, (st.st_mtime_ns, st.st_size,
                     hashlib.sha256(raw).hexdigest())

    def _build_engine(self, raw: bytes) -> PredictEngine:
        """Verify + build + warm an engine from raw file bytes.  Raises
        ModelIntegrityError on torn/bit-flipped content BEFORE any
        device work is spent on it."""
        self.build_attempts += 1
        payload = verify_model_bytes(raw, name=self.path)
        faults.check("reload", path=self.path)  # chaos seam
        engine = PredictEngine(bytes(payload), metrics=self.metrics,
                               **self.engine_kwargs)
        if self.warmup:
            engine.warmup()
        return engine

    def _load_initial(self) -> None:
        raw, fp = self._read_fingerprinted()
        engine = self._build_engine(raw)
        with self._swap_lock:
            self._engine, self._fp = engine, fp
            self._hash = fp[2]
            self.version = 1
        if self.metrics is not None:
            self.metrics.model_version.set(self.version)

    @property
    def poisoned(self) -> bool:
        """True while the on-disk file is known-bad (the last reload
        failed and the file has not changed since) — the serving stack
        is healthy but DEGRADED: it cannot pick up the newest bytes."""
        return self._poisoned is not None

    # --------------------------------------------------------------- state
    @property
    def engine(self) -> PredictEngine:
        """The live engine.  Reference reads are atomic; callers that
        need (version, engine) consistent use :meth:`current`."""
        return self._engine

    @property
    def content_hash(self) -> Optional[str]:
        """sha256 of the model content the LIVE engine serves.  Follows
        engine swaps — after a rollback it names the rolled-back-to
        content, not the newer on-disk file — so a fleet controller
        (or a human) can verify what each replica actually runs."""
        return self._hash

    def current(self) -> Tuple[int, PredictEngine]:
        with self._swap_lock:
            return self.version, self._engine

    def describe(self) -> dict:
        """Registry + engine description for operators (the fleet
        rollout controller reads ``model_hash`` to verify a push)."""
        with self._swap_lock:
            d = {"path": self.path,
                 "model_version": self.version,
                 "model_hash": self._hash,
                 "previous_versions": [v for v, _, _ in self._previous],
                 "poisoned": self._poisoned is not None,
                 "reload_failures": self.reload_failures,
                 "last_reload_error": self.last_reload_error,
                 "build_attempts": self.build_attempts}
            engine = self._engine
        d["engine"] = engine.describe()
        return d

    def device_bytes(self) -> int:
        """Device bytes pinned by the LIVE engine plus the warm
        rollback ring — the unit the model catalog's shared budget
        accounts (catalog/catalog.py)."""
        with self._swap_lock:
            engines = [self._engine] + [e for _, e, _ in self._previous]
        return sum(e.device_bytes() for e in engines if e is not None)

    def predict(self, X, output_margin: bool = False):
        """Predict on whatever model is current when the call starts
        (the batcher's per-batch engine resolution); the result is
        tagged with the version that ran (:class:`VersionedArray`)."""
        version, engine = self.current()
        out = engine.predict(X, output_margin=output_margin)
        return VersionedArray.tag(out, version)

    # -------------------------------------------------------------- reload
    def check_reload(self, force: bool = False) -> bool:
        """Poll once: reload + swap if the file content changed.
        Returns True when a new model went live.

        Failure paths (RELIABILITY.md): a load that fails — torn file
        racing the poll, CRC mismatch, injected fault — keeps the old
        model serving and POISONS the new content's fingerprint: the
        bad bytes are hashed-and-rejected (cheap) on later polls
        instead of re-built and re-warmed (a full bucket compile)
        every second, until the file changes again.  ``/healthz``
        surfaces ``last_reload_error`` while poisoned.

        ``force=True`` (the ``POST /-/reload`` endpoint) bypasses BOTH
        short-circuits — the poisoned skip and the stat fast path — and
        re-reads the file: the operator's escape hatch when the failure
        was transient (device OOM during warmup, injected fault) rather
        than bad bytes, and the only way to pick up a rewrite that
        preserved mtime+size (``rsync -a`` / ``cp -p`` of a same-sized
        model), which the stat-compare poll is blind to by design."""
        with self._reload_lock:
            try:
                st = os.stat(self.path)
            except OSError:
                return False  # file mid-replace; next poll sees the result
            stat = (st.st_mtime_ns, st.st_size)
            if (not force and self._fp is not None
                    and stat == self._fp[:2]):
                return False  # per-poll fast path: stat unchanged, no read
            if (not force and self._poisoned is not None
                    and stat == self._poisoned[:2]):
                # known-bad file, not even touched since: skip the read
                self._count_poisoned_skip()
                return False
            try:
                raw, fp = self._read_fingerprinted()
            except OSError:
                return False
            if self._hash is not None and fp[2] == self._hash:
                # file content matches what the LIVE ENGINE serves:
                # not a reload.  Compared against the engine's hash,
                # NOT the last-loaded fingerprint — after a rollback
                # the two diverge, and a push of the very bytes the
                # engine rolled back FROM must load again (the fleet
                # controller's rollback restores files, then a later
                # rollout may legitimately re-push the same model)
                self._fp = fp
                if self._poisoned is not None:
                    # the file was rolled BACK to the live content (an
                    # operator undoing a bad push): it is no longer
                    # known-bad — clear the degraded state
                    self._poisoned = None
                    self.last_reload_error = None
                return False
            if (not force and self._poisoned is not None
                    and fp[2] == self._poisoned[2]):
                # rewritten with the SAME bad bytes: refresh the stat so
                # the next poll short-circuits, but do not rebuild
                self._poisoned = fp
                self._count_poisoned_skip()
                return False
            try:
                with span("serving.reload_build", path=self.path):
                    engine = self._build_engine(raw)
            except Exception as e:
                self.reload_failures += 1
                self.last_reload_error = f"{type(e).__name__}: {e}"
                self._poisoned = fp
                if self.metrics is not None:
                    self.metrics.reload_errors.inc()
                event("serving.reload_failed", path=self.path,
                      error=self.last_reload_error)
                print(f"[serving] reload failed, keeping v{self.version} "
                      f"(file poisoned until it changes): {e}",
                      file=sys.stderr)
                return False
            with self._swap_lock:
                self._previous.append((self.version, self._engine,
                                       self._hash))
                self._engine, self._fp = engine, fp
                self._hash = fp[2]
                self._poisoned = None
                self.last_reload_error = None
                self.version += 1
                v = self.version
            if self.metrics is not None:
                self.metrics.reloads.inc()
                self.metrics.model_version.set(v)
            event("serving.reload", path=self.path, model_version=v)
            return True

    @staticmethod
    def _count_poisoned_skip() -> None:
        from xgboost_tpu.profiling import reliability_metrics
        reliability_metrics().poisoned_reloads.inc()

    def rollback(self) -> bool:
        """Swap the most recent previous version back in (no disk I/O —
        its engine is still warm).  Returns False when the ring is
        empty.

        Deliberately NOT serialized behind ``_reload_lock``: rollback is
        the emergency path and must stay instant even while a (slow)
        reload build holds that lock — it only mutates in-memory state,
        so the swap lock suffices.  A reload that completes after the
        rollback still swaps its model in (it was requested by a newer
        file change); roll back again to undo it."""
        with self._swap_lock:
            if not self._previous:
                return False
            old_version, old_engine, old_hash = self._previous.pop()
            # the outgoing engine goes onto the ring in turn, so an
            # accidental rollback is itself reversible (rollback twice
            # toggles between the two newest versions)
            self._previous.append((self.version, self._engine,
                                   self._hash))
            self._engine = old_engine
            self._hash = old_hash
            # _fp still holds the on-disk fingerprint, so the next
            # poll will NOT re-load the model just rolled back from;
            # the rollback sticks until the file actually changes
            self.version += 1
            v = self.version
        if self.metrics is not None:
            self.metrics.model_version.set(v)
        event("serving.rollback", to_engine_of=old_version,
              model_version=v)
        print(f"[serving] rolled back to engine of v{old_version} "
              f"(now v{v})", file=sys.stderr)
        return True

    # ---------------------------------------------------------------- poll
    def start(self) -> None:
        """Start the background poll thread (no-op when poll_sec <= 0)."""
        if self.poll_sec <= 0 or self._poller is not None:
            return
        self._poller = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="xgbtpu-model-poll")
        self._poller.start()

    def _poll_loop(self) -> None:
        from xgboost_tpu.reliability.deadline import jittered
        # ±20% jitter: a fleet of replicas watching the same published
        # model file must not stat it in lockstep every poll tick
        while not self._stop.wait(jittered(self.poll_sec)):
            try:
                self.check_reload()
            except Exception as e:  # the poller must survive anything
                print(f"[serving] poll error: {e}", file=sys.stderr)

    def stop(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(self.poll_sec + 5.0)
            self._poller = None

"""Model registry: watch a model path, hot-reload atomically, keep
previous versions for instant rollback.

Reload protocol (the "load + warm OFF the serving path, then swap a
reference" design, SERVING.md):

1. a poll notices the file changed (mtime/size fast path, content hash
   to confirm — a rewrite with identical bytes is NOT a reload);
2. the new model is loaded into a FRESH :class:`PredictEngine` and
   warmed (all buckets compiled + executed) while the old engine keeps
   serving;
3. one reference assignment swaps the engines.  In-flight batches hold
   the old engine reference and finish on it — no request ever sees a
   half-loaded model;
4. the old (version, engine) pair is pushed onto a bounded rollback
   ring (``keep_versions`` deep); :meth:`rollback` swaps it straight
   back without touching disk.
"""

from __future__ import annotations

import hashlib
import os
import sys
import threading
from collections import deque
from typing import Optional, Tuple

import numpy as np

from xgboost_tpu.serving.engine import PredictEngine


class VersionedArray(np.ndarray):
    """ndarray tagged with the model version that PRODUCED it.  The tag
    survives slicing (the batcher scatters one batch's output across
    callers), so a response's ``model_version`` names the model that
    actually ran — not whatever was current when the request arrived,
    which can differ across a hot-reload."""

    model_version: int = 0

    def __array_finalize__(self, obj):
        self.model_version = getattr(obj, "model_version", 0)

    @classmethod
    def tag(cls, arr: np.ndarray, version: int) -> "VersionedArray":
        out = np.asarray(arr).view(cls)
        out.model_version = version
        return out


class ModelRegistry:
    """Owns the live engine + its predecessors for one model path."""

    def __init__(self, path: str, keep_versions: int = 2,
                 warmup: bool = True, poll_sec: float = 1.0,
                 metrics=None, **engine_kwargs):
        self.path = path
        self.keep_versions = int(keep_versions)
        self.warmup = bool(warmup)
        self.poll_sec = float(poll_sec)
        self.metrics = metrics
        self.engine_kwargs = engine_kwargs
        self.version = 0
        self._engine: Optional[PredictEngine] = None
        self._previous: deque = deque(maxlen=max(0, self.keep_versions))
        self._fp: Optional[Tuple] = None
        self._reload_lock = threading.Lock()   # one reload at a time
        self._swap_lock = threading.Lock()     # guards engine/version swap
        self._stop = threading.Event()
        self._poller: Optional[threading.Thread] = None
        self._load_initial()

    # ------------------------------------------------------------- loading
    def _fingerprint(self, fast: bool = False) -> Tuple:
        """(mtime_ns, size, sha256).  With ``fast=True`` and an
        unchanged stat, the stored hash is reused — the per-poll fast
        path never reads the file; the hash is only recomputed to
        confirm an apparent change (a touch with identical bytes must
        NOT trigger a reload)."""
        st = os.stat(self.path)
        if (fast and self._fp is not None
                and (st.st_mtime_ns, st.st_size) == self._fp[:2]):
            return self._fp
        h = hashlib.sha256()
        with open(self.path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return (st.st_mtime_ns, st.st_size, h.hexdigest())

    def _build_engine(self) -> Tuple[PredictEngine, Tuple]:
        fp = self._fingerprint()
        engine = PredictEngine(self.path, metrics=self.metrics,
                               **self.engine_kwargs)
        if self.warmup:
            engine.warmup()
        return engine, fp

    def _load_initial(self) -> None:
        engine, fp = self._build_engine()
        with self._swap_lock:
            self._engine, self._fp = engine, fp
            self.version = 1
        if self.metrics is not None:
            self.metrics.model_version.set(self.version)

    # --------------------------------------------------------------- state
    @property
    def engine(self) -> PredictEngine:
        """The live engine.  Reference reads are atomic; callers that
        need (version, engine) consistent use :meth:`current`."""
        return self._engine

    def current(self) -> Tuple[int, PredictEngine]:
        with self._swap_lock:
            return self.version, self._engine

    def predict(self, X, output_margin: bool = False):
        """Predict on whatever model is current when the call starts
        (the batcher's per-batch engine resolution); the result is
        tagged with the version that ran (:class:`VersionedArray`)."""
        version, engine = self.current()
        out = engine.predict(X, output_margin=output_margin)
        return VersionedArray.tag(out, version)

    # -------------------------------------------------------------- reload
    def check_reload(self) -> bool:
        """Poll once: reload + swap if the file content changed.
        Returns True when a new model went live.  A failed load (e.g. a
        half-written file racing the poll) keeps the old model serving
        and retries on the next poll."""
        with self._reload_lock:
            try:
                fp = self._fingerprint(fast=True)
            except OSError:
                return False  # file mid-replace; next poll sees the result
            if fp == self._fp:
                return False
            if self._fp is not None and fp[2] == self._fp[2]:
                self._fp = fp  # touched but byte-identical: not a reload
                return False
            try:
                engine, fp = self._build_engine()
            except Exception as e:
                if self.metrics is not None:
                    self.metrics.reload_errors.inc()
                print(f"[serving] reload failed, keeping v{self.version}: "
                      f"{e}", file=sys.stderr)
                return False
            with self._swap_lock:
                self._previous.append((self.version, self._engine))
                self._engine, self._fp = engine, fp
                self.version += 1
                v = self.version
            if self.metrics is not None:
                self.metrics.reloads.inc()
                self.metrics.model_version.set(v)
            return True

    def rollback(self) -> bool:
        """Swap the most recent previous version back in (no disk I/O —
        its engine is still warm).  Returns False when the ring is
        empty.

        Deliberately NOT serialized behind ``_reload_lock``: rollback is
        the emergency path and must stay instant even while a (slow)
        reload build holds that lock — it only mutates in-memory state,
        so the swap lock suffices.  A reload that completes after the
        rollback still swaps its model in (it was requested by a newer
        file change); roll back again to undo it."""
        with self._swap_lock:
            if not self._previous:
                return False
            old_version, old_engine = self._previous.pop()
            # the outgoing engine goes onto the ring in turn, so an
            # accidental rollback is itself reversible (rollback twice
            # toggles between the two newest versions)
            self._previous.append((self.version, self._engine))
            self._engine = old_engine
            # _fp still holds the on-disk fingerprint, so the next
            # poll will NOT re-load the model just rolled back from;
            # the rollback sticks until the file actually changes
            self.version += 1
            v = self.version
        if self.metrics is not None:
            self.metrics.model_version.set(v)
        print(f"[serving] rolled back to engine of v{old_version} "
              f"(now v{v})", file=sys.stderr)
        return True

    # ---------------------------------------------------------------- poll
    def start(self) -> None:
        """Start the background poll thread (no-op when poll_sec <= 0)."""
        if self.poll_sec <= 0 or self._poller is not None:
            return
        self._poller = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="xgbtpu-model-poll")
        self._poller.start()

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_sec):
            try:
                self.check_reload()
            except Exception as e:  # the poller must survive anything
                print(f"[serving] poll error: {e}", file=sys.stderr)

    def stop(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(self.poll_sec + 5.0)
            self._poller = None

"""Queue-based micro-batcher: coalesce concurrent requests into one
device call, scatter results back to callers.

Concurrent ``submit`` calls within a window (first request arms a
``max_wait_ms`` deadline; ``max_batch_rows`` caps the coalesced size)
are stacked into ONE engine call — the serving analog of the training
side's "one launch per round" stance: device dispatch overhead is paid
per batch, not per request.

Backpressure is explicit: the queue is bounded in ROWS (the unit that
costs device time/memory), and a submit that would exceed it raises
:class:`QueueFull` immediately instead of growing memory without bound
— the HTTP front end maps that to 503.

Abandoned requests are SHED: when a caller's ``submit(timeout=...)``
wait expires, the request is marked abandoned and the worker skips it
at flush time — no device dispatch is paid for a result nobody reads
(counted on ``xgbtpu_reliability_shed_requests_total``).

Deadlines compose with shedding (reliability/deadline.py): a request
submitted with a :class:`~xgboost_tpu.reliability.deadline.Deadline`
whose budget runs out while it waits in the queue is dropped at flush
time BEFORE dispatch — its caller gets
:class:`~xgboost_tpu.reliability.deadline.DeadlineExceeded` (HTTP 504
at the front end) and the drop counts on
``xgbtpu_deadline_dropped_total``.  Shedding covers callers that gave
up; the deadline drop covers callers whose BUDGET gave up, which the
worker can see without waiting for anyone's timeout.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

import numpy as np


class QueueFull(RuntimeError):
    """The batch queue is at capacity; retry later (HTTP 503)."""


class _Request:
    __slots__ = ("X", "output_margin", "done", "result", "error", "t0",
                 "abandoned", "trace_id", "deadline", "tenant")

    def __init__(self, X: np.ndarray, output_margin: bool, deadline=None,
                 tenant: str = ""):
        self.X = X
        self.output_margin = output_margin
        # catalog tenant (model name) the request belongs to: the
        # accept queue dequeues across tenants by weighted round-robin
        self.tenant = tenant
        self.done = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.t0 = time.perf_counter()
        # optional Deadline budget: the worker drops this request
        # pre-dispatch once it expires (the caller is answered with
        # DeadlineExceeded instead of a late result)
        self.deadline = deadline
        # set by submit() when its caller's wait timed out: the caller
        # is gone, so the worker sheds the request instead of paying
        # device dispatch for a result nobody will read
        self.abandoned = False
        # the submitter's ambient trace id (e.g. the HTTP X-Request-Id):
        # crosses the queue so the worker's batch span can name the
        # requests it coalesced (OBSERVABILITY.md)
        from xgboost_tpu.obs import current_trace_id
        self.trace_id = current_trace_id()


class MicroBatcher:
    """Coalesces concurrent predict requests into single engine calls.

    Args:
      predict_fn: callable ``(X, output_margin=...) -> np.ndarray``.
        Resolved per BATCH, so a hot-reload between batches is picked up
        atomically (pass ``lambda X, **kw: registry.engine.predict(X,
        **kw)``); requests already inside a batch finish on the engine
        the batch started with.
      max_batch_rows: cap on rows coalesced into one device call.
      max_wait_ms: how long the first request of a batch waits for
        company before the batch launches anyway.
      max_queue_rows: bound on rows waiting in the queue (backpressure).
      metrics: optional :class:`xgboost_tpu.obs.ServingMetrics`.
    """

    def __init__(self, predict_fn: Callable, max_batch_rows: int = 1024,
                 max_wait_ms: float = 2.0, max_queue_rows: int = 8192,
                 metrics=None):
        self.predict_fn = predict_fn
        self.max_batch_rows = int(max_batch_rows)
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue_rows = int(max_queue_rows)
        self.metrics = metrics
        # the Queue is now only a WAKE-TOKEN channel (one True per
        # accepted request, None = close sentinel); the requests
        # themselves wait in per-tenant deques so the worker dequeues
        # across tenants by smooth weighted round-robin — a heavy
        # tenant below its quota can no longer queue ahead of a light
        # one just by arriving first
        self._q: "queue.Queue[Optional[bool]]" = queue.Queue()
        self._tenant_q: Dict[str, Deque[_Request]] = {}
        self._tenant_weights: Dict[str, float] = {}
        self._wrr_current: Dict[str, float] = {}
        self._queued_rows = 0
        self._lock = threading.Lock()
        self._closed = False
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="xgbtpu-batcher")
        self._worker.start()

    # ------------------------------------------------------------- submit
    def set_tenant_weight(self, tenant: str, weight: float) -> None:
        """Set a tenant's WRR share (default 1.0; a tenant with weight
        2 is dequeued twice as often as a weight-1 tenant while both
        have work queued).  ``weight <= 0`` resets to the default."""
        with self._lock:
            if weight <= 0:
                self._tenant_weights.pop(tenant, None)
            else:
                self._tenant_weights[tenant] = float(weight)

    def submit(self, X, output_margin: bool = False,
               timeout: Optional[float] = None,
               deadline=None, tenant: str = "") -> np.ndarray:
        """Enqueue one request and block until its predictions arrive.

        Raises :class:`QueueFull` when accepting the rows would exceed
        ``max_queue_rows`` (reject-don't-buffer backpressure).  With a
        ``deadline`` (:class:`~xgboost_tpu.reliability.deadline.
        Deadline`), the wait is bounded by the remaining budget and the
        worker drops the entry pre-dispatch once it expires (the caller
        sees :class:`~xgboost_tpu.reliability.deadline.
        DeadlineExceeded`)."""
        X = np.asarray(X, np.float32)
        if X.ndim != 2:
            raise ValueError(f"expected 2-D rows, got shape {X.shape}")
        n = X.shape[0]
        if self.metrics is not None:
            # counted BEFORE admission: "requests received" includes the
            # ones backpressure rejects (reject ratio must be computable
            # as rejected_total / requests_total)
            self.metrics.requests.inc()
        if deadline is not None:
            # the caller has no reason to outwait its own budget (plus
            # a small grace so a pre-dispatch drop resolves the wait
            # with the typed error, not a bare TimeoutError race)
            budget = deadline.remaining() + 0.05
            timeout = budget if timeout is None else min(timeout, budget)
        req = _Request(X, output_margin, deadline=deadline, tenant=tenant)
        with self._lock:
            # closed-check AND enqueue under the same lock as close()'s
            # closed-set: a request can never land BEHIND the close
            # sentinel (which would leave its caller blocked forever)
            if self._closed:
                raise RuntimeError("batcher is closed")
            # backpressure bounds rows WAITING behind other requests.  A
            # single oversized request is admitted when the queue is
            # empty (the engine chunks it through the top bucket; its
            # memory is already materialized by the caller) — otherwise
            # a request larger than max_queue_rows would 503 forever,
            # even on an idle server
            if (self._queued_rows + n > self.max_queue_rows
                    and self._queued_rows > 0):
                if self.metrics is not None:
                    self.metrics.rejected.inc()
                raise QueueFull(
                    f"queue holds {self._queued_rows} rows; adding {n} "
                    f"exceeds max_queue_rows={self.max_queue_rows}")
            self._queued_rows += n
            if self.metrics is not None:
                self.metrics.queue_rows.set(self._queued_rows)
            self._tenant_q.setdefault(tenant, deque()).append(req)
            self._q.put(True)  # one wake token per accepted request
        if not req.done.wait(timeout):
            # mark-then-raise: the request still sits in the queue, but
            # the worker will skip it at flush time (counted in
            # reliability metrics as a shed request).  Benign race: if
            # the flush already started, the result is computed and
            # simply dropped — never a wrong answer to a later caller.
            req.abandoned = True
            if deadline is not None and deadline.expired():
                from xgboost_tpu.reliability.deadline import \
                    DeadlineExceeded
                raise DeadlineExceeded(
                    "deadline budget spent waiting for dispatch")
            raise TimeoutError("prediction timed out")
        if self.metrics is not None:
            self.metrics.latency.observe(time.perf_counter() - req.t0)
        if req.error is not None:
            raise req.error
        return req.result

    # ------------------------------------------------------------- worker
    def _dequeue_rows(self, n: int) -> None:
        with self._lock:
            self._queued_rows -= n
            if self.metrics is not None:
                self.metrics.queue_rows.set(self._queued_rows)

    def _next_request(self) -> _Request:
        """Pop the next request by smooth weighted round-robin across
        the tenants with queued work.  Called once per consumed wake
        token, so a non-empty deque is guaranteed."""
        with self._lock:
            total = sum(self._weight(t) for t in self._tenant_q)
            best = None
            for t in self._tenant_q:
                c = self._wrr_current.get(t, 0.0) + self._weight(t)
                self._wrr_current[t] = c
                if best is None or c > self._wrr_current[best]:
                    best = t
            self._wrr_current[best] -= total
            dq = self._tenant_q[best]
            req = dq.popleft()
            if not dq:
                # drained tenants leave the rotation (and drop their
                # WRR credit — an idle tenant must not bank priority)
                del self._tenant_q[best]
                self._wrr_current.pop(best, None)
        from xgboost_tpu.obs.metrics import tenant_dequeues
        tenant_dequeues().inc(best if best else "default")
        return req

    def _weight(self, tenant: str) -> float:
        return self._tenant_weights.get(tenant, 1.0)

    def _run(self) -> None:
        carry: Optional[_Request] = None
        while True:
            if carry is not None:
                req, carry = carry, None
            else:
                if self._q.get() is None:  # close sentinel
                    return
                req = self._next_request()
            batch: List[_Request] = [req]
            rows = req.X.shape[0]
            deadline = time.perf_counter() + self.max_wait_ms / 1e3
            while rows < self.max_batch_rows:
                wait = deadline - time.perf_counter()
                if wait <= 0:
                    break
                try:
                    tok = self._q.get(timeout=wait)
                except queue.Empty:
                    break
                if tok is None:
                    self._q.put(None)  # re-arm the sentinel for after flush
                    break
                nxt = self._next_request()
                if (nxt.X.shape[1] != req.X.shape[1]
                        or nxt.output_margin != req.output_margin
                        or rows + nxt.X.shape[0] > self.max_batch_rows):
                    # incompatible or overflowing: flush what we have,
                    # lead the next batch with this request
                    carry = nxt
                    break
                batch.append(nxt)
                rows += nxt.X.shape[0]
            self._flush(batch)

    def _flush(self, batch: List[_Request]) -> None:
        self._dequeue_rows(sum(r.X.shape[0] for r in batch))
        # drop entries whose DEADLINE expired in the queue: unlike an
        # abandoned request (caller gone, nothing to tell it), the
        # caller here may still be waiting — answer it with the typed
        # 504-mapping error instead of paying device dispatch for a
        # result that arrives past its budget
        expired = [r for r in batch if not r.abandoned
                   and r.deadline is not None and r.deadline.expired()]
        if expired:
            from xgboost_tpu.profiling import reliability_metrics
            from xgboost_tpu.reliability.deadline import DeadlineExceeded
            reliability_metrics().deadline_dropped.inc(len(expired))
            for r in expired:
                r.error = DeadlineExceeded(
                    "deadline expired before dispatch")
                r.abandoned = True
                r.done.set()
        # shed requests whose caller already timed out: their rows would
        # cost device dispatch (and inflate the batch's bucket) for a
        # result nobody is waiting on
        live = [r for r in batch if not r.abandoned]
        if len(live) < len(batch):
            from xgboost_tpu.profiling import reliability_metrics
            reliability_metrics().shed_requests.inc(
                len(batch) - len(live) - len(expired))
            for r in batch:
                if r.abandoned and r.error is None:
                    r.done.set()
            if not live:
                return
        rows = sum(r.X.shape[0] for r in live)
        if self.metrics is not None:
            self.metrics.batches.inc()
            self.metrics.batch_rows.observe(rows)
        from xgboost_tpu.obs import span
        try:
            # one span per coalesced device batch, naming the traces it
            # carries — the link between a request's serve.request span
            # and the batch that actually ran it
            with span("serve.batch", rows=rows, requests=len(live),
                      request_ids=[r.trace_id for r in live
                                   if r.trace_id is not None][:32]):
                X = (live[0].X if len(live) == 1
                     else np.concatenate([r.X for r in live], axis=0))
                out = self.predict_fn(X,
                                      output_margin=live[0].output_margin)
            off = 0
            for r in live:
                n = r.X.shape[0]
                r.result = out[off:off + n]
                off += n
        except BaseException as e:  # propagate to every caller in the batch
            if self.metrics is not None:
                self.metrics.errors.inc(len(live))
            for r in live:
                r.error = e
        finally:
            for r in live:
                r.done.set()

    # -------------------------------------------------------------- close
    @property
    def queued_rows(self) -> int:
        return self._queued_rows

    def close(self, timeout: float = 5.0) -> None:
        """Drain the queue and stop the worker."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(None)  # ordered after every accepted request
        self._worker.join(timeout)

"""Stdlib-only HTTP front end for the serving stack.

Endpoints (SERVING.md):

- ``POST /predict`` — body is CSV rows (default) or libsvm rows
  (``?format=libsvm`` or ``Content-Type: text/libsvm``); responds
  ``{"predictions": [...], "model_version": v, "rows": n}``.
  ``?output_margin=1`` returns raw margins.  A full batch queue maps to
  HTTP 503 (the batcher's reject-with-backpressure contract).
- ``GET /healthz`` — liveness + model version + queue depth + p50/p99.
- ``GET /metrics`` — Prometheus text exposition (ServingMetrics).
- ``POST /-/reload`` — force one reload poll (also happens on the
  background poll timer); ``POST /-/rollback`` swaps the previous
  version back in.

``ThreadingHTTPServer`` gives one thread per connection; all of them
funnel into the single MicroBatcher queue, which is where concurrency
turns into coalesced device batches.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from xgboost_tpu.serving.batcher import MicroBatcher, QueueFull
from xgboost_tpu.serving.registry import ModelRegistry


def parse_csv_rows(text: str) -> np.ndarray:
    """CSV rows -> (n, F) float32 (empty fields / 'nan' = missing)."""
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        rows.append([float(tok) if tok.strip() not in ("", "na", "nan")
                     else np.nan for tok in line.split(",")])
    if not rows:
        return np.zeros((0, 0), np.float32)
    width = max(len(r) for r in rows)
    out = np.full((len(rows), width), np.nan, np.float32)
    for i, r in enumerate(rows):
        out[i, :len(r)] = r
    return out


def parse_libsvm_rows(text: str, num_feature: int) -> np.ndarray:
    """libsvm rows -> (n, F) float32 with NaN for absent features.  A
    leading label token (no ':') is tolerated and ignored — serving
    inputs are features-only, but clients often replay training files."""
    rows = []
    for line in text.splitlines():
        toks = line.split("#", 1)[0].split()
        if not toks:
            continue
        feats = {}
        for j, tok in enumerate(toks):
            if ":" not in tok:
                if j == 0:
                    continue  # label column
                raise ValueError(f"bad libsvm token {tok!r}")
            idx, _, val = tok.partition(":")
            feats[int(idx)] = float(val)
        rows.append(feats)
    out = np.full((len(rows), num_feature), np.nan, np.float32)
    for i, feats in enumerate(rows):
        for idx, val in feats.items():
            if 0 <= idx < num_feature:
                out[i, idx] = val
    return out


class _Handler(BaseHTTPRequestHandler):
    # the server instance carries registry/batcher/metrics (see
    # PredictServer below)
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route access logs through quiet
        if not self.server.quiet:
            super().log_message(fmt, *args)

    # --------------------------------------------------------------- util
    def _send(self, code: int, body: bytes,
              content_type: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj).encode())

    # ---------------------------------------------------------------- GET
    def do_GET(self):
        url = urlparse(self.path)
        if url.path == "/healthz":
            reg: ModelRegistry = self.server.registry
            m = self.server.metrics
            q = m.quantiles((0.5, 0.99))
            self._send_json(200, {
                "status": "ok",
                "model_version": reg.version,
                "queue_rows": self.server.batcher.queued_rows,
                "buckets_compiled": reg.engine.num_compiled,
                "latency_p50_ms": round(q[0.5] * 1e3, 3),
                "latency_p99_ms": round(q[0.99] * 1e3, 3),
            })
            return
        if url.path == "/metrics":
            self._send(200, self.server.metrics.render().encode(),
                       "text/plain; version=0.0.4")
            return
        self._send_json(404, {"error": f"no route {url.path}"})

    # --------------------------------------------------------------- POST
    def do_POST(self):
        url = urlparse(self.path)
        # ALWAYS drain the body: under HTTP/1.1 keep-alive, unread body
        # bytes would be parsed as the next request line on the reused
        # connection (e.g. a POST /-/reload with a JSON body).  Bodies
        # we cannot drain deterministically (chunked encoding, bad or
        # negative Content-Length) get an error AND a closed connection
        # — never a blocking read(-1), never poisoned pipelining.
        te = (self.headers.get("Transfer-Encoding") or "").lower()
        if "chunked" in te:
            self.close_connection = True
            self._send_json(411, {"error": "chunked bodies not "
                                           "supported; send Content-Length"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if length < 0:
            self.close_connection = True
            self._send_json(400, {"error": "bad Content-Length"})
            return
        body = self.rfile.read(length).decode("utf-8", "replace")
        if url.path == "/predict":
            self._predict(url, body)
            return
        if url.path == "/-/reload":
            reloaded = self.server.registry.check_reload()
            self._send_json(200, {"reloaded": reloaded,
                                  "model_version":
                                      self.server.registry.version})
            return
        if url.path == "/-/rollback":
            ok = self.server.registry.rollback()
            self._send_json(200 if ok else 409,
                            {"rolled_back": ok,
                             "model_version": self.server.registry.version})
            return
        self._send_json(404, {"error": f"no route {url.path}"})

    def _predict(self, url, body: str) -> None:
        try:
            qs = parse_qs(url.query)
            fmt = qs.get("format", [None])[0]
            if fmt is None:
                ctype = (self.headers.get("Content-Type") or "").lower()
                fmt = "libsvm" if "libsvm" in ctype else "csv"
            output_margin = qs.get("output_margin", ["0"])[0] in ("1", "true")
            reg: ModelRegistry = self.server.registry
            if fmt == "libsvm":
                X = parse_libsvm_rows(body, reg.engine.num_feature)
            elif fmt == "csv":
                X = parse_csv_rows(body)
            else:
                self._send_json(400, {"error": f"unknown format {fmt!r}"})
                return
            if X.shape[0] == 0:
                self._send_json(400, {"error": "no rows in request body"})
                return
        except Exception as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        try:
            preds = self.server.batcher.submit(X, output_margin=output_margin)
        except QueueFull as e:
            self._send_json(503, {"error": str(e)})
            return
        except ValueError as e:
            # deterministic client-input errors surfaced by the engine
            # (e.g. more columns than model features) are 400s, not
            # server faults — keeps 5xx alerting honest
            self._send_json(400, {"error": str(e)})
            return
        except Exception as e:
            self._send_json(500, {"error": str(e)})
            return
        # the version that actually PRODUCED these predictions (tagged
        # by the registry; reg.version may have moved during a reload)
        version = getattr(preds, "model_version", reg.version)
        self._send_json(200, {"predictions": np.asarray(preds).tolist(),
                              "model_version": version,
                              "rows": int(X.shape[0])})


class PredictServer:
    """Bundles registry + batcher + metrics behind ThreadingHTTPServer.

    ``port=0`` binds an ephemeral port (tests); the bound port is on
    ``self.port``.  Use :meth:`start` for a background thread or
    :meth:`serve_forever` to block.
    """

    def __init__(self, registry: ModelRegistry, batcher: MicroBatcher,
                 metrics, host: str = "127.0.0.1", port: int = 8080,
                 quiet: bool = True):
        self.registry = registry
        self.batcher = batcher
        self.metrics = metrics
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.registry = registry
        self._httpd.batcher = batcher
        self._httpd.metrics = metrics
        self._httpd.quiet = quiet
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PredictServer":
        self.registry.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="xgbtpu-http")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self.registry.start()
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        self.registry.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        self.batcher.close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None


def run_server(model_path: str, host: str = "127.0.0.1", port: int = 8080,
               min_bucket: int = 8, max_bucket: int = 8192,
               max_batch_rows: int = 1024, max_wait_ms: float = 2.0,
               max_queue_rows: int = 8192, poll_sec: float = 1.0,
               keep_versions: int = 2, warmup: bool = True,
               quiet: bool = False, block: bool = True
               ) -> Optional[PredictServer]:
    """Build the full serving stack for one model file and run it.

    With ``block=False`` the server runs on a background thread and the
    :class:`PredictServer` is returned (tests, embedding)."""
    import sys

    from xgboost_tpu.profiling import ServingMetrics
    metrics = ServingMetrics()
    registry = ModelRegistry(model_path, keep_versions=keep_versions,
                             warmup=warmup, poll_sec=poll_sec,
                             metrics=metrics, min_bucket=min_bucket,
                             max_bucket=max_bucket)
    batcher = MicroBatcher(registry.predict, max_batch_rows=max_batch_rows,
                           max_wait_ms=max_wait_ms,
                           max_queue_rows=max_queue_rows, metrics=metrics)
    server = PredictServer(registry, batcher, metrics, host=host, port=port,
                           quiet=quiet)
    if not quiet:
        eng = registry.engine
        print(f"[serving] model {model_path} (v{registry.version}, "
              f"{eng.gbtree.num_trees} trees, {eng.num_feature} features) "
              f"on http://{server.host}:{server.port} — buckets "
              f"{eng.buckets}", file=sys.stderr)
    if block:
        server.serve_forever()
        return None
    return server.start()

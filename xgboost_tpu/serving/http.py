"""Stdlib-only HTTP front end for the serving stack.

Endpoints (SERVING.md):

- ``POST /predict`` — body is CSV rows (default) or libsvm rows
  (``?format=libsvm`` or ``Content-Type: text/libsvm``); responds
  ``{"predictions": [...], "model_version": v, "rows": n}``.
  ``?output_margin=1`` returns raw margins.  A full batch queue maps to
  HTTP 503 (the batcher's reject-with-backpressure contract).
  ``?model=NAME`` selects a model from the replica's catalog
  (xgboost_tpu.catalog); the bare path resolves to the configured
  default model — the catalog-of-one path IS the single-model path.
  An unknown model name is 404.  ``?model=`` also applies to
  ``/predict_by_id``, the ``/featurestore/*`` admin routes, and
  ``/-/reload`` / ``/-/rollback``.
- ``POST /predict_by_id`` — JSON ``{"ids": [...]}``: predictions for
  DEVICE-RESIDENT entities (serving/featurestore.py) with zero
  host→device feature bytes; absent ids → 404 listing them.  Enabled
  by ``serve_featurestore_mb > 0``.
- ``POST /featurestore/put`` — JSON ``{"ids": [...], "rows": [[...]]}``
  pins entity rows on device (LRU-evicting past the byte budget);
  ``POST /featurestore/invalidate`` — ``{"ids": [...]}`` or
  ``{"all": true}`` drops them.
- ``GET /healthz`` — liveness + model version + queue depth + p50/p99,
  plus the failure-path fields (RELIABILITY.md): drain ``state``,
  ``status: degraded`` while the watched model file is poisoned,
  ``reload_failures`` count and ``last_reload_error``.
- ``GET /metrics`` — Prometheus text exposition (ServingMetrics +
  the process-wide ReliabilityMetrics).
- ``POST /-/reload`` — force one reload poll (also happens on the
  background poll timer); ``POST /-/rollback`` swaps the previous
  version back in.

Shutdown is a drain state machine (``serving -> draining -> stopped``):
SIGTERM (or :meth:`PredictServer.drain`) stops admitting ``/predict``
with 503, waits for in-flight requests to finish (bounded by
``drain_grace``), then exits — a rolling restart loses zero accepted
requests.

``ThreadingHTTPServer`` gives one thread per connection; all of them
funnel into the single MicroBatcher queue, which is where concurrency
turns into coalesced device batches.
"""

from __future__ import annotations

import json
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from xgboost_tpu.obs import span, trace, trace_context
from xgboost_tpu.obs.server import PROM_CONTENT_TYPE
from xgboost_tpu.reliability.deadline import Deadline, DeadlineExceeded
from xgboost_tpu.serving.batcher import MicroBatcher, QueueFull
from xgboost_tpu.serving.registry import ModelRegistry


def parse_csv_rows(text: str) -> np.ndarray:
    """CSV rows -> (n, F) float32 (empty fields / 'nan' = missing)."""
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        rows.append([float(tok) if tok.strip() not in ("", "na", "nan")
                     else np.nan for tok in line.split(",")])
    if not rows:
        return np.zeros((0, 0), np.float32)
    width = max(len(r) for r in rows)
    out = np.full((len(rows), width), np.nan, np.float32)
    for i, r in enumerate(rows):
        out[i, :len(r)] = r
    return out


def parse_libsvm_rows(text: str, num_feature: int) -> np.ndarray:
    """libsvm rows -> (n, F) float32 with NaN for absent features.  A
    leading label token (no ':') is tolerated and ignored — serving
    inputs are features-only, but clients often replay training files.
    A feature index beyond the model's width is a client error (400),
    same as the CSV path's too-many-columns check — silently dropping
    it would return confidently wrong predictions for a mis-deployed
    client."""
    rows = []
    for line in text.splitlines():
        toks = line.split("#", 1)[0].split()
        if not toks:
            continue
        feats = {}
        for j, tok in enumerate(toks):
            if ":" not in tok:
                if j == 0:
                    continue  # label column
                raise ValueError(f"bad libsvm token {tok!r}")
            idx, _, val = tok.partition(":")
            feats[int(idx)] = float(val)
        rows.append(feats)
    out = np.full((len(rows), num_feature), np.nan, np.float32)
    for i, feats in enumerate(rows):
        for idx, val in feats.items():
            if not 0 <= idx < num_feature:
                raise ValueError(
                    f"feature index {idx} out of range for a "
                    f"{num_feature}-feature model")
            out[i, idx] = val
    return out


def read_request_body(handler, max_bytes: int):
    """Drain and validate a POST body on a keep-alive connection — THE
    body-hygiene discipline, shared by the replica handler here and the
    fleet router's (fleet/router.py).  Under HTTP/1.1 keep-alive,
    unread body bytes would be parsed as the next request line on the
    reused connection; bodies we cannot drain deterministically
    (chunked encoding, bad/negative Content-Length) get an error AND a
    closed connection, and anything over ``max_bytes`` is refused with
    413 BEFORE buffering.  Returns the raw bytes, or None when an
    error response has already been sent (the handler must have
    ``close_connection``/``_send_json``, i.e. be one of ours)."""
    te = (handler.headers.get("Transfer-Encoding") or "").lower()
    if "chunked" in te:
        handler.close_connection = True
        handler._send_json(411, {"error": "chunked bodies not "
                                          "supported; send "
                                          "Content-Length"})
        return None
    try:
        length = int(handler.headers.get("Content-Length", 0))
    except ValueError:
        length = -1
    if length < 0:
        handler.close_connection = True
        handler._send_json(400, {"error": "bad Content-Length"})
        return None
    if length > max_bytes:
        handler.close_connection = True
        handler._send_json(413, {"error": f"request body {length} "
                                          f"bytes exceeds limit "
                                          f"{max_bytes}"})
        return None
    return handler.rfile.read(length)


class _Handler(BaseHTTPRequestHandler):
    # the server instance carries registry/batcher/metrics (see
    # PredictServer below)
    protocol_version = "HTTP/1.1"
    # TCP_NODELAY: the response goes out as two writes (header buffer,
    # then body) — with Nagle on, the body write stalls behind the
    # peer's delayed ACK of the header segment, a flat ~40 ms added to
    # EVERY response on an otherwise sub-millisecond predict
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # route access logs through quiet
        if not self.server.quiet:
            super().log_message(fmt, *args)

    # --------------------------------------------------------------- util
    def _send(self, code: int, body: bytes,
              content_type: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        rid = getattr(self, "_request_id", None)
        if rid is not None:
            # the id that correlates this response with its span in the
            # event log (and with the client's own tracing)
            self.send_header("X-Request-Id", rid)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj).encode())

    # ---------------------------------------------------------------- GET
    def do_GET(self):
        # handler instances persist across a keep-alive connection:
        # a request id set by an earlier /predict must not leak onto
        # this response
        self._request_id = None
        url = urlparse(self.path)
        if url.path == "/healthz":
            reg: ModelRegistry = self.server.registry
            ps: PredictServer = self.server.pserver
            m = self.server.metrics
            q = m.quantiles((0.5, 0.99))
            # "degraded" = still serving, but the watched file is
            # poisoned (its newest bytes cannot be loaded) — alerts fire
            # while traffic keeps flowing on the last good model
            health = {
                "status": "degraded" if reg.poisoned else "ok",
                "state": ps.state,
                "model_version": reg.version,
                # content hash of what the live engine ACTUALLY serves
                # (follows rollbacks) — the fleet rollout controller
                # verifies pushes against it (fleet/rollout.py)
                "model_hash": reg.content_hash,
                "uptime_seconds": round(time.perf_counter() - ps.t0, 3),
                "queue_rows": self.server.batcher.queued_rows,
                "inflight": ps.inflight,
                "buckets_compiled": reg.engine.num_compiled,
                "reload_failures": reg.reload_failures,
                "last_reload_error": reg.last_reload_error,
                "latency_p50_ms": round(q[0.5] * 1e3, 3),
                "latency_p99_ms": round(q[0.99] * 1e3, 3),
            }
            if ps.featurestore is not None:
                health["featurestore_rows"] = len(ps.featurestore)
            if ps.catalog is not None:
                # per-model rows (name -> path/resident/version/hash/
                # buckets/device bytes) — the rollout controller verifies
                # per-tenant pushes against models[m]["model_hash"]
                cd = ps.catalog.describe()
                health["models"] = cd["models"]
                health["catalog"] = {
                    k: cd[k] for k in ("default", "configured",
                                       "resident", "bytes_used",
                                       "bytes_budget")}
            self._send_json(200, health)
            return
        if url.path == "/metrics":
            # the full Prometheus exposition content type (scrapers key
            # the text-format parser off version=0.0.4 + charset)
            self._send(200, self.server.metrics.render().encode(),
                       PROM_CONTENT_TYPE)
            return
        self._send_json(404, {"error": f"no route {url.path}"})

    # --------------------------------------------------------------- POST
    def do_POST(self):
        self._request_id = None  # no leak across keep-alive requests
        url = urlparse(self.path)
        # ALWAYS drain the body (read_request_body: keep-alive hygiene,
        # 411 chunked / 400 bad length / 413 reject-before-buffering)
        raw = read_request_body(self, self.server.pserver.max_body_bytes)
        if raw is None:
            return
        body = raw.decode("utf-8", "replace")
        if url.path == "/predict":
            self._predict(url, body)
            return
        if url.path == "/predict_by_id":
            self._predict_by_id(url, body)
            return
        if url.path in ("/featurestore/put", "/featurestore/invalidate"):
            # the mutating store routes pass the same drain admission
            # gate as predictions: a draining server must not accept
            # new device uploads, and in-flight ones must be visible to
            # the inflight counter the drain waits on
            ps: PredictServer = self.server.pserver
            if not ps.enter_request():
                self.close_connection = True
                self._send_json(503, {"error": "server is draining",
                                      "state": ps.state})
                return
            try:
                if url.path == "/featurestore/put":
                    self._featurestore_put(url, body)
                else:
                    self._featurestore_invalidate(url, body)
            finally:
                ps.exit_request()
            return
        if url.path == "/-/reload":
            # forced: bypasses the poisoned-fingerprint skip, so an
            # operator can retry after a TRANSIENT build failure.
            # ?model= scopes the reload to one catalog entry (the
            # per-tenant rollout path); bare = the default model
            reg = self._resolve_registry(url)
            if reg is None:
                return
            reloaded = reg.check_reload(force=True)
            self._send_json(200, {"reloaded": reloaded,
                                  "model_version": reg.version})
            return
        if url.path == "/-/rollback":
            reg = self._resolve_registry(url)
            if reg is None:
                return
            ok = reg.rollback()
            self._send_json(200 if ok else 409,
                            {"rolled_back": ok,
                             "model_version": reg.version})
            return
        if url.path == "/-/catalog":
            self._catalog_delta(body)
            return
        self._send_json(404, {"error": f"no route {url.path}"})

    def _catalog_delta(self, body: str) -> None:
        """Placer manifest delta: ``{"add": {name: path, ...},
        "remove": [name, ...]}``.  Attach is tolerant — a name the
        catalog already holds is skipped, not an error — so a placer
        retrying a push after a timeout converges instead of failing;
        attached models admit lazily on first resolve (or eagerly via a
        follow-up ``/-/reload?model=``).  Detach refuses the pinned
        default (409) and is idempotent for unknown names."""
        import os as _os
        from xgboost_tpu.obs import event
        ps: PredictServer = self.server.pserver
        if ps.catalog is None:
            self._send_json(409, {"error": "no catalog on this replica"})
            return
        try:
            req = json.loads(body) if body.strip() else {}
            add = {str(k): str(v)
                   for k, v in dict(req.get("add") or {}).items()}
            remove = [str(n) for n in list(req.get("remove") or [])]
        except (ValueError, TypeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        added, skipped, removed, errors = [], [], [], []
        for name, path in sorted(add.items()):
            if not _os.path.exists(path):
                errors.append(f"{name}: no model file at {path!r}")
                continue
            try:
                ps.catalog.add_model(name, path)
                added.append(name)
            except ValueError:
                # already attached (placer retry / concurrent push)
                skipped.append(name)
        for name in remove:
            try:
                if ps.catalog.remove_model(name):
                    removed.append(name)
            except ValueError as e:  # pinned default
                errors.append(str(e))
        if added or removed:
            event("serving.catalog_delta", added=added, removed=removed,
                  skipped=skipped, errors=len(errors))
        self._send_json(200 if not errors else 409,
                        {"added": added, "removed": removed,
                         "skipped": skipped, "errors": errors,
                         "models": ps.catalog.names()})

    # ------------------------------------------------------------ catalog
    def _resolve_entry(self, url, sp=None):
        """``(registry, batcher, entry)`` for the request's ``?model=``
        (entry is None on a catalog-less server).  On an unknown model
        a 404 naming the known set is already sent and ``(None, None,
        None)`` returns — mirroring the router's UnknownModel answer so
        clients see one shape fleet-wide."""
        from xgboost_tpu.catalog import UnknownModel
        model = parse_qs(url.query).get("model", [""])[0]
        ps: PredictServer = self.server.pserver
        try:
            return ps.resolve_model(model)
        except UnknownModel as e:
            from xgboost_tpu.obs.metrics import catalog_metrics
            catalog_metrics().unknown_model.inc()
            if sp is not None:
                sp.set("status", 404)
            self._send_json(404, {"error": str(e), "models": e.known})
            return None, None, None
        except Exception as e:
            # admission failed (bad model file, device OOM building the
            # engine): the model EXISTS but cannot serve right now
            if sp is not None:
                sp.set("status", 503)
            self._send_json(503, {"error": f"model {model!r} failed to "
                                           f"load: {e}"})
            return None, None, None

    def _resolve_registry(self, url, sp=None):
        reg, _, _ = self._resolve_entry(url, sp)
        return reg

    def _predict(self, url, body: str) -> None:
        # request tracing (OBSERVABILITY.md): the caller's X-Request-Id
        # (or a generated one) becomes the trace id for every span this
        # request produces, and is echoed on the response — including
        # the 503/400/500 branches — so client logs, server timeline
        # and response headers all correlate on one id
        rid = self.headers.get("X-Request-Id") or trace.new_id()
        self._request_id = rid
        ps: PredictServer = self.server.pserver
        if not ps.enter_request():
            # draining: load balancers read the 503 as "instance going
            # away", retry elsewhere; requests already in flight finish
            self.close_connection = True
            self._send_json(503, {"error": "server is draining",
                                  "state": ps.state})
            return
        try:
            with trace_context(rid):
                with span("serve.request", request_id=rid) as sp:
                    self._predict_admitted(url, body, sp)
        finally:
            ps.exit_request()

    def _deadline_reject(self, reason: str, dl, sp=None) -> None:
        """504 a request whose budget cannot buy useful work — BEFORE
        any parsing/device cost is spent on it (admission by deadline,
        RELIABILITY.md stall matrix).  Counter-backed so 'rejected
        early ≫ completed late' is assertable from /metrics."""
        from xgboost_tpu.profiling import reliability_metrics
        reliability_metrics().deadline_rejected.inc()
        if sp is not None:
            sp.set("status", 504)
        self._send_json(504, {
            "error": reason, "deadline_exceeded": True,
            "remaining_ms": dl.describe_ms() if dl is not None else 0})

    def _predict_admitted(self, url, body: str, sp=None) -> None:
        def _st(code: int) -> None:
            if sp is not None:
                sp.set("status", code)
        ps: PredictServer = self.server.pserver
        dl = Deadline.from_headers(self.headers)
        if dl is not None and dl.expired():
            # spent before we even parse: the router's stamp (or the
            # client's) says nobody is waiting for this answer
            self._deadline_reject("deadline expired on arrival", dl, sp)
            return
        # model resolution BEFORE body parsing: admission of a cold
        # catalog entry (engine build + warmup) is the expensive step,
        # and an unknown model must 404 without paying any parse cost
        reg, batcher, entry = self._resolve_entry(url, sp)
        if reg is None:
            return
        if sp is not None and entry is not None:
            sp.set("model", entry.name)
        try:
            qs = parse_qs(url.query)
            fmt = qs.get("format", [None])[0]
            if fmt is None:
                ctype = (self.headers.get("Content-Type") or "").lower()
                fmt = "libsvm" if "libsvm" in ctype else "csv"
            output_margin = qs.get("output_margin", ["0"])[0] in ("1", "true")
            if fmt == "libsvm":
                X = parse_libsvm_rows(body, reg.engine.num_feature)
            elif fmt == "csv":
                X = parse_csv_rows(body)
            else:
                _st(400)
                self._send_json(400, {"error": f"unknown format {fmt!r}"})
                return
            if X.shape[0] == 0:
                _st(400)
                self._send_json(400, {"error": "no rows in request body"})
                return
        except Exception as e:
            _st(400)
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        if sp is not None:
            sp.set("rows", int(X.shape[0]))
        if dl is not None:
            # admission by deadline: when the remaining budget cannot
            # cover this row-bucket's OBSERVED service time, a 504 now
            # beats device work whose answer lands after the caller
            # hung up (the stall analog of reject-don't-buffer)
            est = ps.service_estimate(int(X.shape[0]))
            if est > 0.0 and dl.remaining() < est:
                # anti-latch: only completed predicts refresh the EWMA,
                # so an estimate inflated by a past backlog could
                # otherwise reject this bucket FOREVER once it exceeds
                # every client's budget — each rejection decays it
                # until requests are admitted and real observations
                # take over
                ps.decay_service(int(X.shape[0]))
                self._deadline_reject(
                    f"remaining budget {dl.describe_ms()}ms cannot "
                    f"cover observed service time {est * 1e3:.1f}ms",
                    dl, sp)
                return
        # chaos seam: `slow_replica` (keyed on this replica's fleet id,
        # like the lease client's heartbeat_loss/replica_kill) wedges
        # the predict path without killing anything — the
        # latency-ejection machinery must route around it
        from xgboost_tpu.reliability import faults
        wedge = faults.delay_for(
            "slow_replica",
            path=(ps.lease_client.replica_id
                  if ps.lease_client is not None else None))
        if wedge > 0.0:
            time.sleep(wedge)
        t_submit = time.perf_counter()
        try:
            preds = batcher.submit(X, output_margin=output_margin,
                                   deadline=dl,
                                   tenant=(entry.name if entry is not None
                                           else ""))
        except QueueFull as e:
            _st(503)
            self._send_json(503, {"error": str(e)})
            return
        except DeadlineExceeded as e:
            # expired in the queue (dropped pre-dispatch) or while
            # waiting: no result exists and none was paid for
            _st(504)
            self._send_json(504, {"error": str(e),
                                  "deadline_exceeded": True})
            return
        except ValueError as e:
            # deterministic client-input errors surfaced by the engine
            # (e.g. more columns than model features) are 400s, not
            # server faults — keeps 5xx alerting honest
            _st(400)
            self._send_json(400, {"error": str(e)})
            return
        except Exception as e:
            _st(500)
            self._send_json(500, {"error": str(e)})
            return
        ps.observe_service(int(X.shape[0]),
                           time.perf_counter() - t_submit)
        # the version that actually PRODUCED these predictions (tagged
        # by the registry; reg.version may have moved during a reload)
        version = getattr(preds, "model_version", reg.version)
        _st(200)
        if sp is not None:
            sp.set("model_version", int(version))
        resp = {"predictions": np.asarray(preds).tolist(),
                "model_version": version,
                "rows": int(X.shape[0])}
        if entry is not None:
            resp["model"] = entry.name
        self._send_json(200, resp)


    # -------------------------------------------------- feature store
    def _entry_store(self, entry):
        """The FeatureStore serving ``entry`` (the default model rides
        the server-level store; other catalog entries own per-model
        stores), or None + a 404 already sent."""
        ps: PredictServer = self.server.pserver
        if entry is None or (ps.catalog is not None
                             and entry.name == ps.catalog.default):
            store = (ps.featurestore_for()
                     if ps.featurestore is not None else None)
        else:
            store = entry.featurestore_for()
        if store is None:
            self._send_json(404, {
                "error": "feature store disabled "
                         "(start with serve_featurestore_mb > 0)"})
        return store

    def _predict_by_id(self, url, body: str) -> None:
        """Zero-upload prediction for device-resident entities: the
        repeat-traffic fast path (SERVING.md feature store)."""
        rid = self.headers.get("X-Request-Id") or trace.new_id()
        self._request_id = rid
        ps: PredictServer = self.server.pserver
        if not ps.enter_request():
            self.close_connection = True
            self._send_json(503, {"error": "server is draining",
                                  "state": ps.state})
            return
        try:
            with trace_context(rid):
                with span("serve.request", request_id=rid,
                          by_id=True) as sp:
                    self._predict_by_id_admitted(url, body, sp)
        finally:
            ps.exit_request()

    def _predict_by_id_admitted(self, url, body: str, sp=None) -> None:
        from xgboost_tpu.serving.featurestore import (FeatureStoreMiss,
                                                      predict_by_id)

        def _st(code: int) -> None:
            if sp is not None:
                sp.set("status", code)
        dl = Deadline.from_headers(self.headers)
        if dl is not None and dl.expired():
            self._deadline_reject("deadline expired on arrival", dl, sp)
            return
        reg, _, entry = self._resolve_entry(url, sp)
        if reg is None:
            return
        if sp is not None and entry is not None:
            sp.set("model", entry.name)
        store = self._entry_store(entry)
        if store is None:
            _st(404)
            return
        try:
            qs = parse_qs(url.query)
            output_margin = qs.get("output_margin",
                                   ["0"])[0] in ("1", "true")
            req = json.loads(body)
            ids = req["ids"]
            if not isinstance(ids, list) or not ids:
                raise ValueError("'ids' must be a non-empty list")
            om = req.get("output_margin", output_margin)
            # same truthiness contract as the query string: "0"/"false"
            # must DISABLE margins (bool("0") is True)
            output_margin = (om is True or om == 1
                             or str(om).lower() in ("1", "true"))
        except (ValueError, KeyError, TypeError) as e:
            _st(400)
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        if sp is not None:
            sp.set("rows", len(ids))
        # (version, engine) resolved atomically: the response names the
        # model that actually ran, across hot-reloads — and a reload's
        # new cuts rebin the SAME resident raw rows on device.  A
        # reload that changed the FEATURE WIDTH swaps the store (empty,
        # same budget): these ids then 404 as misses, not shape errors
        version, engine = reg.current()
        store = self._entry_store(entry)
        if store is None:
            _st(404)
            return
        if store.num_feature != engine.num_feature:
            # the engine snapshot raced a width-changing reload:
            # re-resolve once (the store swap keyed on the registry's
            # CURRENT engine, so the fresh snapshot matches it)
            version, engine = reg.current()
        if store.num_feature != engine.num_feature:
            _st(503)
            self._send_json(503, {
                "error": "model reloading (feature width changed) — "
                         "retry"})
            return
        try:
            preds = predict_by_id(engine, store, ids,
                                  output_margin=output_margin)
        except FeatureStoreMiss as e:
            _st(404)
            self._send_json(404, {"error": str(e), "missing": e.missing})
            return
        except Exception as e:
            _st(500)
            self._send_json(500, {"error": str(e)})
            return
        _st(200)
        if sp is not None:
            sp.set("model_version", int(version))
        resp = {"predictions": np.asarray(preds).tolist(),
                "model_version": version,
                "rows": len(ids)}
        if entry is not None:
            resp["model"] = entry.name
        self._send_json(200, resp)

    def _featurestore_put(self, url, body: str) -> None:
        reg, _, entry = self._resolve_entry(url)
        if reg is None:
            return
        # puts validate against the CURRENT model's width (a width-
        # changing hot-reload swaps in a fresh store of the new width)
        store = self._entry_store(entry)
        if store is None:
            return
        try:
            req = json.loads(body)
            ids, rows = req["ids"], req["rows"]
            if (not isinstance(ids, list) or not ids
                    or not isinstance(rows, list)):
                raise ValueError("'ids' and 'rows' must be lists")
            X = np.asarray(rows, np.float32)
            res = store.put(ids, X)
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        except Exception as e:
            # device failure during the upload/scatter: put committed
            # nothing (staged slot math) — surface it, don't drop the
            # socket with a handler traceback
            self._send_json(500, {"error": str(e)})
            return
        res.update(store.describe())
        self._send_json(200, res)

    def _featurestore_invalidate(self, url, body: str) -> None:
        reg, _, entry = self._resolve_entry(url)
        if reg is None:
            return
        store = self._entry_store(entry)
        if store is None:
            return
        try:
            req = json.loads(body) if body.strip() else {}
            if req.get("all"):
                dropped = store.invalidate()
            else:
                ids = req.get("ids")
                if not isinstance(ids, list) or not ids:
                    raise ValueError(
                        "pass {'ids': [...]} or {'all': true}")
                dropped = store.invalidate(ids)
        except (ValueError, TypeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        self._send_json(200, {"invalidated": dropped,
                              "resident_rows": len(store)})


class PredictServer:
    """Bundles registry + batcher + metrics behind ThreadingHTTPServer.

    ``port=0`` binds an ephemeral port (tests); the bound port is on
    ``self.port``.  Use :meth:`start` for a background thread or
    :meth:`serve_forever` to block.

    Lifecycle is a drain state machine: ``serving`` (admitting
    ``/predict``) -> ``draining`` (new predictions get 503, in-flight
    ones finish, ``/healthz`` still answers) -> ``stopped``.  SIGTERM
    triggers it when :meth:`serve_forever` runs on the main thread;
    :meth:`drain` triggers it programmatically.
    """

    def __init__(self, registry: ModelRegistry, batcher: MicroBatcher,
                 metrics, host: str = "127.0.0.1", port: int = 8080,
                 quiet: bool = True, drain_grace: float = 30.0,
                 max_body_mb: float = 64.0, featurestore=None,
                 catalog=None):
        self.registry = registry
        self.batcher = batcher
        self.metrics = metrics
        # optional ModelCatalog (xgboost_tpu.catalog): N named models on
        # this replica, resolved by ?model=.  registry/batcher above stay
        # the DEFAULT entry's — every existing single-model caller sees
        # the same attributes whether or not a catalog is attached
        self.catalog = catalog
        # optional device-resident FeatureStore (serving/featurestore.py)
        # backing /predict_by_id and the /featurestore/* admin routes;
        # access through featurestore_for() on model-facing paths so a
        # hot-reload that CHANGES THE FEATURE WIDTH swaps in a fresh
        # store instead of feeding wrong-width rows to the new engine
        self.featurestore = featurestore
        self._fs_lock = threading.Lock()
        # per-row-bucket EWMA of observed predict service time (submit
        # -> result), feeding admission-by-deadline: a request whose
        # remaining budget is below its bucket's estimate is 504'd
        # before any device work (reliability/deadline.py)
        self._svc_lock = threading.Lock()
        self._svc_ewma: dict = {}
        # fleet membership (attach_fleet): registration/heartbeat lease
        # client against a fleet router; None = standalone replica
        self.lease_client = None
        self.drain_grace = float(drain_grace)
        self.max_body_bytes = int(max_body_mb * (1 << 20))
        # /healthz uptime_seconds: perf_counter — uptime is a duration,
        # and an NTP step must not make it jump (XGT006)
        self.t0 = time.perf_counter()
        self.state = "serving"          # serving -> draining -> stopped
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._shut = False
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        # handler threads must not be able to pin the process: a wedged
        # device call (the case the drain grace exists for) leaves its
        # handler blocked in batcher.submit() forever, and non-daemon
        # threads would keep the interpreter alive after main returns
        self._httpd.daemon_threads = True
        self._httpd.registry = registry
        self._httpd.batcher = batcher
        self._httpd.metrics = metrics
        self._httpd.quiet = quiet
        self._httpd.pserver = self
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ catalog
    def resolve_model(self, name: str = ""):
        """``(registry, batcher, entry)`` serving model ``name`` (the
        default model when empty).  Without a catalog only the bare
        path exists — a named model raises UnknownModel (the handler's
        404).  With one, a cold entry is admitted on demand (engine
        build + warmup happen on THIS request's thread; hot models are
        a dict probe)."""
        if self.catalog is None:
            if name:
                from xgboost_tpu.catalog import UnknownModel
                raise UnknownModel(name, [])
            return self.registry, self.batcher, None
        entry = self.catalog.resolve(name)
        return entry.registry, entry.batcher, entry

    # ------------------------------------------------------ feature store
    def featurestore_for(self):
        """The live FeatureStore, re-created (same byte budget, empty)
        when the registry's CURRENT engine has a different feature
        width than the store.

        Raw-row storage makes cut/max_bin hot-reloads free (the next
        predict_by_id rebins resident rows on device), but a reload to
        a DIFFERENT FEATURE COUNT makes every resident row meaningless
        for the new model — the swap drops them, and callers see
        404-miss (re-``put`` with new-width features), never a
        shape-mismatched executable call.  The swap keys on the
        registry's current engine, NOT any caller's resolved snapshot:
        a request still in flight across the reload must not wipe a
        store that has already been re-populated at the new width."""
        store = self.featurestore
        if store is None:
            return None
        width = self.registry.engine.num_feature
        if store.num_feature == width:
            return store
        with self._fs_lock:
            store = self.featurestore
            width = self.registry.engine.num_feature
            if store.num_feature != width:
                from xgboost_tpu.obs.metrics import featurestore_metrics
                from xgboost_tpu.serving.featurestore import FeatureStore
                store = FeatureStore(
                    width, budget_mb=store.budget_bytes / (1 << 20))
                self.featurestore = store
                featurestore_metrics().resident_bytes.set(0)
        return store

    # ---------------------------------------------------- service estimate
    @staticmethod
    def _svc_bucket(rows: int) -> int:
        """Power-of-two row bucket for the service-time EWMA — mirrors
        the engine's shape-bucket ladder without coupling to it."""
        b = 1
        while b < rows:
            b <<= 1
        return b

    def observe_service(self, rows: int, seconds: float) -> None:
        """Fold one completed predict into its bucket's service-time
        EWMA (alpha 0.2: stable against one slow batch, responsive to a
        real shift)."""
        key = self._svc_bucket(max(1, int(rows)))
        with self._svc_lock:
            prev = self._svc_ewma.get(key)
            self._svc_ewma[key] = (seconds if prev is None
                                   else 0.8 * prev + 0.2 * seconds)

    def service_estimate(self, rows: int) -> float:
        """Expected service seconds for a request of ``rows`` rows
        (its bucket's EWMA, or — when its bucket has no samples — the
        largest EWMA among smaller buckets as a floor).  0.0 = no
        observations yet — admission stays open until the estimate
        exists, so a cold replica never rejects."""
        key = self._svc_bucket(max(1, int(rows)))
        with self._svc_lock:
            if key in self._svc_ewma:
                return self._svc_ewma[key]
            smaller = [v for k, v in self._svc_ewma.items() if k < key]
        return max(smaller) if smaller else 0.0

    def decay_service(self, rows: int, factor: float = 0.95) -> None:
        """Walk an estimate down on every admission rejection it
        causes: rejections produce no completions, so without this a
        backlog-inflated estimate above every caller's budget would
        latch the bucket into rejecting forever.  Decays the bucket
        that actually SUPPLIED the estimate — the request's own, or
        the smaller bucket whose EWMA served as its floor (decaying
        only the absent request bucket would be a no-op and the latch
        would stand)."""
        key = self._svc_bucket(max(1, int(rows)))
        with self._svc_lock:
            if key not in self._svc_ewma:
                smaller = [k for k in self._svc_ewma if k < key]
                if not smaller:
                    return
                key = max(smaller, key=lambda k: self._svc_ewma[k])
            self._svc_ewma[key] *= factor

    # -------------------------------------------------------------- fleet
    def attach_fleet(self, router_url: str,
                     replica_id: Optional[str] = None,
                     advertise_url: str = "",
                     on_kill=None) -> None:
        """Join a fleet (SERVING.md fleet section): register with the
        router at ``router_url`` and keep a heartbeat lease alive.  The
        lease client starts with :meth:`start`/:meth:`serve_forever`
        and deregisters when the drain begins, so a draining replica
        leaves rotation BEFORE it starts 503ing (the router's health
        checker is the backstop for crashes).  ``replica_id`` defaults
        to ``host:port`` — a restarted replica re-registering under its
        old id is the tracker ``recover`` path."""
        from xgboost_tpu.fleet.membership import LeaseClient
        rid = replica_id or f"{self.host}:{self.port}"
        # the ADVERTISED endpoint is what the router dials — a wildcard
        # bind (0.0.0.0/::) is reachable locally but unroutable from
        # the router's side, so cross-host replicas must say where they
        # actually live (serve_advertise_url)
        self_url = (advertise_url.rstrip("/") if advertise_url
                    else f"http://{self.host}:{self.port}")
        if not advertise_url and self.host in ("0.0.0.0", "::", ""):
            print(f"[fleet] WARNING: advertising wildcard bind "
                  f"{self_url} to the router — unroutable from other "
                  "hosts; set serve_advertise_url", file=sys.stderr)
        self.lease_client = LeaseClient(
            router_url, rid, self_url,
            model_path=self.registry.path,
            model_hash_fn=lambda: self.registry.content_hash,
            # catalog advertisement: every heartbeat carries the model
            # set (name -> path/hash) so the router can route ?model=
            # to replicas that actually HOST the model
            models_fn=(self.catalog.models
                       if self.catalog is not None else None),
            # device budget advertisement: the placer bin-packs tenant
            # models against (budget - used) per replica
            device_fn=(
                (lambda: {"budget_bytes": self.catalog.budget_bytes,
                          "used_bytes": self.catalog.bytes_used()})
                if self.catalog is not None else None),
            on_kill=on_kill)

    # -------------------------------------------------------- drain state
    @property
    def inflight(self) -> int:
        return self._inflight

    def enter_request(self) -> bool:
        """Admission check + in-flight count, one atomic step (a drain
        that begins between the two could otherwise miss a request).
        False = draining/stopped, caller answers 503."""
        with self._inflight_cv:
            if self.state != "serving":
                return False
            self._inflight += 1
            return True

    def exit_request(self) -> None:
        with self._inflight_cv:
            self._inflight -= 1
            self._inflight_cv.notify_all()

    def drain(self, grace: Optional[float] = None) -> float:
        """Stop admitting predictions, wait (bounded by ``grace``) for
        in-flight ones to finish, then shut down.  Returns the drain
        duration in seconds (also on the ``drain_seconds`` gauge)."""
        from xgboost_tpu.profiling import reliability_metrics
        grace = self.drain_grace if grace is None else float(grace)
        t0 = time.perf_counter()
        deadline = t0 + grace
        if self.lease_client is not None:
            # leave the fleet FIRST: the router stops dispatching here
            # before this replica starts answering 503 (requests already
            # routed ride the retry path)
            self.lease_client.stop(deregister=True)
        with self._inflight_cv:
            if self.state == "serving":
                self.state = "draining"
            while self._inflight > 0:
                left = deadline - time.perf_counter()
                if left <= 0:
                    print(f"[serving] drain grace ({grace:.1f}s) expired "
                          f"with {self._inflight} request(s) in flight",
                          file=sys.stderr)
                    # the stragglers are wedged (their submit() has no
                    # timeout); joining their daemon threads would block
                    # forever and defeat the grace bound — skip the join
                    # and let process exit reap them
                    self._httpd.block_on_close = False
                    break
                self._inflight_cv.wait(left)
        # the gauge lands BEFORE the listener closes, so a last /metrics
        # scrape during the drain can observe it (and once more after,
        # with the total, for embedders holding the object)
        reliability_metrics().drain_seconds.set(time.perf_counter() - t0)
        self.shutdown()
        dur = time.perf_counter() - t0
        reliability_metrics().drain_seconds.set(dur)
        from xgboost_tpu.obs import event
        event("serving.drain", grace=grace, duration_s=round(dur, 3),
              stragglers=self._inflight)
        return dur

    def _handle_sigterm(self, signum, frame) -> None:
        # runs on the main thread, which is inside serve_forever's
        # select loop: the actual drain+shutdown must happen elsewhere
        # (shutdown() blocks until that very loop exits)
        print("[serving] SIGTERM: draining (in-flight requests finish, "
              "new /predict gets 503)", file=sys.stderr)
        threading.Thread(target=self.drain, daemon=True,
                         name="xgbtpu-drain").start()

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "PredictServer":
        self.registry.start()
        if self.catalog is not None:
            self.catalog.start()  # idempotent for the default registry
        if self.lease_client is not None:
            self.lease_client.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="xgbtpu-http")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self.registry.start()
        if self.catalog is not None:
            self.catalog.start()
        if self.lease_client is not None:
            self.lease_client.start()
        if threading.current_thread() is threading.main_thread():
            try:
                signal.signal(signal.SIGTERM, self._handle_sigterm)
            except ValueError:
                pass  # exotic embedding; drain() stays available
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        with self._inflight_cv:
            if self._shut:
                return
            self._shut = True
            self.state = "stopped"
        if self.lease_client is not None:
            self.lease_client.stop(deregister=True)
        self.registry.stop()
        if self.catalog is not None:
            self.catalog.stop()  # re-stop of the default entry is a no-op
        self._httpd.shutdown()
        self._httpd.server_close()
        self.batcher.close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None


def run_server(model_path: str = "", host: str = "127.0.0.1",
               port: int = 8080,
               min_bucket: int = 8, max_bucket: int = 8192,
               max_batch_rows: int = 1024, max_wait_ms: float = 2.0,
               max_queue_rows: int = 8192, poll_sec: float = 1.0,
               keep_versions: int = 2, warmup: bool = True,
               drain_sec: float = 30.0, max_body_mb: float = 64.0,
               featurestore_mb: float = 0.0,
               catalog: str = "", catalog_default: str = "",
               catalog_mb: float = 0.0,
               catalog_hysteresis_sec: float = 3.0,
               router_url: str = "", replica_id: str = "",
               advertise_url: str = "",
               quiet: bool = False,
               block: bool = True) -> Optional[PredictServer]:
    """Build the full serving stack and run it.

    Every server is a catalog server: ``model_path`` alone is a
    catalog of one (entry name ``default``, bare ``/predict`` hits
    it — byte-identical behavior to the pre-catalog stack).
    ``catalog`` adds named models (inline ``name=path,...`` or a
    manifest file, see :func:`xgboost_tpu.catalog.parse_manifest`),
    all admitted under one ``catalog_mb`` device budget with
    LRU-evict + ``catalog_hysteresis_sec`` anti-thrash;
    ``catalog_default`` picks which entry bare requests resolve to.

    ``featurestore_mb > 0`` attaches a device-resident
    :class:`~xgboost_tpu.serving.featurestore.FeatureStore` of that
    byte budget PER MODEL, enabling ``POST /predict_by_id``
    (zero-upload repeat traffic) and the ``/featurestore/*`` admin
    routes.

    ``router_url`` joins a fleet (xgboost_tpu.fleet): the replica
    registers with the router there, heartbeats a lease (advertising
    its model set), and deregisters when draining.

    With ``block=False`` the server runs on a background thread and the
    :class:`PredictServer` is returned (tests, embedding)."""
    from xgboost_tpu.catalog import ModelCatalog, parse_manifest
    from xgboost_tpu.profiling import ServingMetrics
    metrics = ServingMetrics()
    manifest = parse_manifest(catalog) if catalog else {}
    default_name = catalog_default or ("default" if model_path
                                       else next(iter(manifest), ""))
    paths = dict(manifest)
    if model_path:
        # an explicit model_in IS the default model, even when the
        # manifest also names one under default_name
        paths[default_name] = model_path
    if not paths:
        raise ValueError("run_server needs model_in or a catalog= "
                         "manifest")
    if default_name not in paths:
        raise ValueError(f"catalog_default {default_name!r} is not in "
                         f"the catalog (holds: {sorted(paths)})")

    def registry_factory(path):
        return ModelRegistry(path, keep_versions=keep_versions,
                             warmup=warmup, poll_sec=poll_sec,
                             metrics=metrics, min_bucket=min_bucket,
                             max_bucket=max_bucket)

    def batcher_factory(reg):
        return MicroBatcher(reg.predict, max_batch_rows=max_batch_rows,
                            max_wait_ms=max_wait_ms,
                            max_queue_rows=max_queue_rows,
                            metrics=metrics)

    registry = registry_factory(paths[default_name])
    batcher = batcher_factory(registry)
    store = None
    if featurestore_mb > 0:
        from xgboost_tpu.serving.featurestore import FeatureStore
        store = FeatureStore(registry.engine.num_feature,
                             budget_mb=featurestore_mb)
    cat = ModelCatalog(budget_mb=catalog_mb,
                       hysteresis_sec=catalog_hysteresis_sec,
                       default=default_name,
                       registry_factory=registry_factory,
                       batcher_factory=batcher_factory)
    cat.add_model(default_name, paths[default_name], registry=registry,
                  batcher=batcher, featurestore_mb=featurestore_mb)
    for name, path in paths.items():
        if name != default_name:
            cat.add_model(name, path, featurestore_mb=featurestore_mb)
    if warmup:
        # admit the whole manifest up front — compiles land at startup,
        # not on first traffic; past the budget the LRU tail re-evicts
        # once it ages out of the hysteresis window
        for name in cat.names():
            if name != default_name:
                try:
                    cat.resolve(name)
                except Exception as e:
                    print(f"[serving] WARNING: model {name!r} failed to "
                          f"warm: {e} (will retry on first request)",
                          file=sys.stderr)
    server = PredictServer(registry, batcher, metrics, host=host, port=port,
                           quiet=quiet, drain_grace=drain_sec,
                           max_body_mb=max_body_mb, featurestore=store,
                           catalog=cat)
    if router_url:
        server.attach_fleet(router_url, replica_id=replica_id or None,
                            advertise_url=advertise_url)
    if not quiet:
        eng = registry.engine
        print(f"[serving] model {paths[default_name]} "
              f"(v{registry.version}, "
              f"{eng.gbtree.num_trees} trees, {eng.num_feature} features) "
              f"on http://{server.host}:{server.port} — buckets "
              f"{eng.buckets}"
              + (f"; catalog of {len(cat)} "
                 f"(default {default_name!r})" if len(cat) > 1 else ""),
              file=sys.stderr)
    if block:
        server.serve_forever()
        return None
    return server.start()

"""Stdlib-only HTTP front end for the serving stack.

Endpoints (SERVING.md):

- ``POST /predict`` — body is CSV rows (default) or libsvm rows
  (``?format=libsvm`` or ``Content-Type: text/libsvm``); responds
  ``{"predictions": [...], "model_version": v, "rows": n}``.
  ``?output_margin=1`` returns raw margins.  A full batch queue maps to
  HTTP 503 (the batcher's reject-with-backpressure contract).
- ``GET /healthz`` — liveness + model version + queue depth + p50/p99,
  plus the failure-path fields (RELIABILITY.md): drain ``state``,
  ``status: degraded`` while the watched model file is poisoned,
  ``reload_failures`` count and ``last_reload_error``.
- ``GET /metrics`` — Prometheus text exposition (ServingMetrics +
  the process-wide ReliabilityMetrics).
- ``POST /-/reload`` — force one reload poll (also happens on the
  background poll timer); ``POST /-/rollback`` swaps the previous
  version back in.

Shutdown is a drain state machine (``serving -> draining -> stopped``):
SIGTERM (or :meth:`PredictServer.drain`) stops admitting ``/predict``
with 503, waits for in-flight requests to finish (bounded by
``drain_grace``), then exits — a rolling restart loses zero accepted
requests.

``ThreadingHTTPServer`` gives one thread per connection; all of them
funnel into the single MicroBatcher queue, which is where concurrency
turns into coalesced device batches.
"""

from __future__ import annotations

import json
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from xgboost_tpu.obs import span, trace, trace_context
from xgboost_tpu.obs.server import PROM_CONTENT_TYPE
from xgboost_tpu.serving.batcher import MicroBatcher, QueueFull
from xgboost_tpu.serving.registry import ModelRegistry


def parse_csv_rows(text: str) -> np.ndarray:
    """CSV rows -> (n, F) float32 (empty fields / 'nan' = missing)."""
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        rows.append([float(tok) if tok.strip() not in ("", "na", "nan")
                     else np.nan for tok in line.split(",")])
    if not rows:
        return np.zeros((0, 0), np.float32)
    width = max(len(r) for r in rows)
    out = np.full((len(rows), width), np.nan, np.float32)
    for i, r in enumerate(rows):
        out[i, :len(r)] = r
    return out


def parse_libsvm_rows(text: str, num_feature: int) -> np.ndarray:
    """libsvm rows -> (n, F) float32 with NaN for absent features.  A
    leading label token (no ':') is tolerated and ignored — serving
    inputs are features-only, but clients often replay training files.
    A feature index beyond the model's width is a client error (400),
    same as the CSV path's too-many-columns check — silently dropping
    it would return confidently wrong predictions for a mis-deployed
    client."""
    rows = []
    for line in text.splitlines():
        toks = line.split("#", 1)[0].split()
        if not toks:
            continue
        feats = {}
        for j, tok in enumerate(toks):
            if ":" not in tok:
                if j == 0:
                    continue  # label column
                raise ValueError(f"bad libsvm token {tok!r}")
            idx, _, val = tok.partition(":")
            feats[int(idx)] = float(val)
        rows.append(feats)
    out = np.full((len(rows), num_feature), np.nan, np.float32)
    for i, feats in enumerate(rows):
        for idx, val in feats.items():
            if not 0 <= idx < num_feature:
                raise ValueError(
                    f"feature index {idx} out of range for a "
                    f"{num_feature}-feature model")
            out[i, idx] = val
    return out


class _Handler(BaseHTTPRequestHandler):
    # the server instance carries registry/batcher/metrics (see
    # PredictServer below)
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route access logs through quiet
        if not self.server.quiet:
            super().log_message(fmt, *args)

    # --------------------------------------------------------------- util
    def _send(self, code: int, body: bytes,
              content_type: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        rid = getattr(self, "_request_id", None)
        if rid is not None:
            # the id that correlates this response with its span in the
            # event log (and with the client's own tracing)
            self.send_header("X-Request-Id", rid)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj).encode())

    # ---------------------------------------------------------------- GET
    def do_GET(self):
        # handler instances persist across a keep-alive connection:
        # a request id set by an earlier /predict must not leak onto
        # this response
        self._request_id = None
        url = urlparse(self.path)
        if url.path == "/healthz":
            reg: ModelRegistry = self.server.registry
            ps: PredictServer = self.server.pserver
            m = self.server.metrics
            q = m.quantiles((0.5, 0.99))
            # "degraded" = still serving, but the watched file is
            # poisoned (its newest bytes cannot be loaded) — alerts fire
            # while traffic keeps flowing on the last good model
            self._send_json(200, {
                "status": "degraded" if reg.poisoned else "ok",
                "state": ps.state,
                "model_version": reg.version,
                "uptime_seconds": round(time.perf_counter() - ps.t0, 3),
                "queue_rows": self.server.batcher.queued_rows,
                "inflight": ps.inflight,
                "buckets_compiled": reg.engine.num_compiled,
                "reload_failures": reg.reload_failures,
                "last_reload_error": reg.last_reload_error,
                "latency_p50_ms": round(q[0.5] * 1e3, 3),
                "latency_p99_ms": round(q[0.99] * 1e3, 3),
            })
            return
        if url.path == "/metrics":
            # the full Prometheus exposition content type (scrapers key
            # the text-format parser off version=0.0.4 + charset)
            self._send(200, self.server.metrics.render().encode(),
                       PROM_CONTENT_TYPE)
            return
        self._send_json(404, {"error": f"no route {url.path}"})

    # --------------------------------------------------------------- POST
    def do_POST(self):
        self._request_id = None  # no leak across keep-alive requests
        url = urlparse(self.path)
        # ALWAYS drain the body: under HTTP/1.1 keep-alive, unread body
        # bytes would be parsed as the next request line on the reused
        # connection (e.g. a POST /-/reload with a JSON body).  Bodies
        # we cannot drain deterministically (chunked encoding, bad or
        # negative Content-Length) get an error AND a closed connection
        # — never a blocking read(-1), never poisoned pipelining.
        te = (self.headers.get("Transfer-Encoding") or "").lower()
        if "chunked" in te:
            self.close_connection = True
            self._send_json(411, {"error": "chunked bodies not "
                                           "supported; send Content-Length"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if length < 0:
            self.close_connection = True
            self._send_json(400, {"error": "bad Content-Length"})
            return
        max_body = self.server.pserver.max_body_bytes
        if length > max_body:
            # reject-don't-buffer applies to the HTTP layer too: the
            # bound is enforced BEFORE any body bytes are read, so an
            # oversized post cannot balloon a handler thread
            self.close_connection = True
            self._send_json(413, {"error": f"request body {length} bytes "
                                           f"exceeds limit {max_body}"})
            return
        body = self.rfile.read(length).decode("utf-8", "replace")
        if url.path == "/predict":
            self._predict(url, body)
            return
        if url.path == "/-/reload":
            # forced: bypasses the poisoned-fingerprint skip, so an
            # operator can retry after a TRANSIENT build failure
            reloaded = self.server.registry.check_reload(force=True)
            self._send_json(200, {"reloaded": reloaded,
                                  "model_version":
                                      self.server.registry.version})
            return
        if url.path == "/-/rollback":
            ok = self.server.registry.rollback()
            self._send_json(200 if ok else 409,
                            {"rolled_back": ok,
                             "model_version": self.server.registry.version})
            return
        self._send_json(404, {"error": f"no route {url.path}"})

    def _predict(self, url, body: str) -> None:
        # request tracing (OBSERVABILITY.md): the caller's X-Request-Id
        # (or a generated one) becomes the trace id for every span this
        # request produces, and is echoed on the response — including
        # the 503/400/500 branches — so client logs, server timeline
        # and response headers all correlate on one id
        rid = self.headers.get("X-Request-Id") or trace.new_id()
        self._request_id = rid
        ps: PredictServer = self.server.pserver
        if not ps.enter_request():
            # draining: load balancers read the 503 as "instance going
            # away", retry elsewhere; requests already in flight finish
            self.close_connection = True
            self._send_json(503, {"error": "server is draining",
                                  "state": ps.state})
            return
        try:
            with trace_context(rid):
                with span("serve.request", request_id=rid) as sp:
                    self._predict_admitted(url, body, sp)
        finally:
            ps.exit_request()

    def _predict_admitted(self, url, body: str, sp=None) -> None:
        def _st(code: int) -> None:
            if sp is not None:
                sp.set("status", code)
        try:
            qs = parse_qs(url.query)
            fmt = qs.get("format", [None])[0]
            if fmt is None:
                ctype = (self.headers.get("Content-Type") or "").lower()
                fmt = "libsvm" if "libsvm" in ctype else "csv"
            output_margin = qs.get("output_margin", ["0"])[0] in ("1", "true")
            reg: ModelRegistry = self.server.registry
            if fmt == "libsvm":
                X = parse_libsvm_rows(body, reg.engine.num_feature)
            elif fmt == "csv":
                X = parse_csv_rows(body)
            else:
                _st(400)
                self._send_json(400, {"error": f"unknown format {fmt!r}"})
                return
            if X.shape[0] == 0:
                _st(400)
                self._send_json(400, {"error": "no rows in request body"})
                return
        except Exception as e:
            _st(400)
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        if sp is not None:
            sp.set("rows", int(X.shape[0]))
        try:
            preds = self.server.batcher.submit(X, output_margin=output_margin)
        except QueueFull as e:
            _st(503)
            self._send_json(503, {"error": str(e)})
            return
        except ValueError as e:
            # deterministic client-input errors surfaced by the engine
            # (e.g. more columns than model features) are 400s, not
            # server faults — keeps 5xx alerting honest
            _st(400)
            self._send_json(400, {"error": str(e)})
            return
        except Exception as e:
            _st(500)
            self._send_json(500, {"error": str(e)})
            return
        # the version that actually PRODUCED these predictions (tagged
        # by the registry; reg.version may have moved during a reload)
        version = getattr(preds, "model_version", reg.version)
        _st(200)
        if sp is not None:
            sp.set("model_version", int(version))
        self._send_json(200, {"predictions": np.asarray(preds).tolist(),
                              "model_version": version,
                              "rows": int(X.shape[0])})


class PredictServer:
    """Bundles registry + batcher + metrics behind ThreadingHTTPServer.

    ``port=0`` binds an ephemeral port (tests); the bound port is on
    ``self.port``.  Use :meth:`start` for a background thread or
    :meth:`serve_forever` to block.

    Lifecycle is a drain state machine: ``serving`` (admitting
    ``/predict``) -> ``draining`` (new predictions get 503, in-flight
    ones finish, ``/healthz`` still answers) -> ``stopped``.  SIGTERM
    triggers it when :meth:`serve_forever` runs on the main thread;
    :meth:`drain` triggers it programmatically.
    """

    def __init__(self, registry: ModelRegistry, batcher: MicroBatcher,
                 metrics, host: str = "127.0.0.1", port: int = 8080,
                 quiet: bool = True, drain_grace: float = 30.0,
                 max_body_mb: float = 64.0):
        self.registry = registry
        self.batcher = batcher
        self.metrics = metrics
        self.drain_grace = float(drain_grace)
        self.max_body_bytes = int(max_body_mb * (1 << 20))
        # /healthz uptime_seconds: perf_counter — uptime is a duration,
        # and an NTP step must not make it jump (XGT006)
        self.t0 = time.perf_counter()
        self.state = "serving"          # serving -> draining -> stopped
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._shut = False
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        # handler threads must not be able to pin the process: a wedged
        # device call (the case the drain grace exists for) leaves its
        # handler blocked in batcher.submit() forever, and non-daemon
        # threads would keep the interpreter alive after main returns
        self._httpd.daemon_threads = True
        self._httpd.registry = registry
        self._httpd.batcher = batcher
        self._httpd.metrics = metrics
        self._httpd.quiet = quiet
        self._httpd.pserver = self
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    # -------------------------------------------------------- drain state
    @property
    def inflight(self) -> int:
        return self._inflight

    def enter_request(self) -> bool:
        """Admission check + in-flight count, one atomic step (a drain
        that begins between the two could otherwise miss a request).
        False = draining/stopped, caller answers 503."""
        with self._inflight_cv:
            if self.state != "serving":
                return False
            self._inflight += 1
            return True

    def exit_request(self) -> None:
        with self._inflight_cv:
            self._inflight -= 1
            self._inflight_cv.notify_all()

    def drain(self, grace: Optional[float] = None) -> float:
        """Stop admitting predictions, wait (bounded by ``grace``) for
        in-flight ones to finish, then shut down.  Returns the drain
        duration in seconds (also on the ``drain_seconds`` gauge)."""
        from xgboost_tpu.profiling import reliability_metrics
        grace = self.drain_grace if grace is None else float(grace)
        t0 = time.perf_counter()
        deadline = t0 + grace
        with self._inflight_cv:
            if self.state == "serving":
                self.state = "draining"
            while self._inflight > 0:
                left = deadline - time.perf_counter()
                if left <= 0:
                    print(f"[serving] drain grace ({grace:.1f}s) expired "
                          f"with {self._inflight} request(s) in flight",
                          file=sys.stderr)
                    # the stragglers are wedged (their submit() has no
                    # timeout); joining their daemon threads would block
                    # forever and defeat the grace bound — skip the join
                    # and let process exit reap them
                    self._httpd.block_on_close = False
                    break
                self._inflight_cv.wait(left)
        # the gauge lands BEFORE the listener closes, so a last /metrics
        # scrape during the drain can observe it (and once more after,
        # with the total, for embedders holding the object)
        reliability_metrics().drain_seconds.set(time.perf_counter() - t0)
        self.shutdown()
        dur = time.perf_counter() - t0
        reliability_metrics().drain_seconds.set(dur)
        from xgboost_tpu.obs import event
        event("serving.drain", grace=grace, duration_s=round(dur, 3),
              stragglers=self._inflight)
        return dur

    def _handle_sigterm(self, signum, frame) -> None:
        # runs on the main thread, which is inside serve_forever's
        # select loop: the actual drain+shutdown must happen elsewhere
        # (shutdown() blocks until that very loop exits)
        print("[serving] SIGTERM: draining (in-flight requests finish, "
              "new /predict gets 503)", file=sys.stderr)
        threading.Thread(target=self.drain, daemon=True,
                         name="xgbtpu-drain").start()

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "PredictServer":
        self.registry.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="xgbtpu-http")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self.registry.start()
        if threading.current_thread() is threading.main_thread():
            try:
                signal.signal(signal.SIGTERM, self._handle_sigterm)
            except ValueError:
                pass  # exotic embedding; drain() stays available
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        with self._inflight_cv:
            if self._shut:
                return
            self._shut = True
            self.state = "stopped"
        self.registry.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        self.batcher.close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None


def run_server(model_path: str, host: str = "127.0.0.1", port: int = 8080,
               min_bucket: int = 8, max_bucket: int = 8192,
               max_batch_rows: int = 1024, max_wait_ms: float = 2.0,
               max_queue_rows: int = 8192, poll_sec: float = 1.0,
               keep_versions: int = 2, warmup: bool = True,
               drain_sec: float = 30.0, max_body_mb: float = 64.0,
               quiet: bool = False,
               block: bool = True) -> Optional[PredictServer]:
    """Build the full serving stack for one model file and run it.

    With ``block=False`` the server runs on a background thread and the
    :class:`PredictServer` is returned (tests, embedding)."""
    from xgboost_tpu.profiling import ServingMetrics
    metrics = ServingMetrics()
    registry = ModelRegistry(model_path, keep_versions=keep_versions,
                             warmup=warmup, poll_sec=poll_sec,
                             metrics=metrics, min_bucket=min_bucket,
                             max_bucket=max_bucket)
    batcher = MicroBatcher(registry.predict, max_batch_rows=max_batch_rows,
                           max_wait_ms=max_wait_ms,
                           max_queue_rows=max_queue_rows, metrics=metrics)
    server = PredictServer(registry, batcher, metrics, host=host, port=port,
                           quiet=quiet, drain_grace=drain_sec,
                           max_body_mb=max_body_mb)
    if not quiet:
        eng = registry.engine
        print(f"[serving] model {model_path} (v{registry.version}, "
              f"{eng.gbtree.num_trees} trees, {eng.num_feature} features) "
              f"on http://{server.host}:{server.port} — buckets "
              f"{eng.buckets}", file=sys.stderr)
    if block:
        server.serve_forever()
        return None
    return server.start()

"""Device-resident feature store: zero-upload prediction for hot
entities.

The millions-of-users access pattern is REPEAT traffic: the same
entities (users, items, devices) are scored over and over, each time
re-shipping the same feature bytes host→device — on tunnel-attached
hosts that upload IS the prediction cost (PROFILE.md: ~3.4-4.5 s of a
4.2 s 1M-row predict).  The store keeps the hot set's RAW f32 feature
rows pinned on device, keyed by entity id, so a ``POST /predict_by_id``
gathers rows on device and runs the engine's fused quantize+traverse
executables with **zero host→device feature bytes** (assertable via
``xgbtpu_predict_transfer_bytes_total`` — it stays flat).

Design points (SERVING.md):

- **Raw features, not bins.**  Rows are stored as the caller supplied
  them (f32, NaN = missing).  Quantization happens per prediction in
  the engine's compiled program against the CURRENT model's cut
  matrix, so a registry hot-reload — even one that changes ``max_bin``
  or the cut points themselves — needs no store invalidation: the next
  ``predict_by_id`` rebins the same resident rows on device
  (reload-safe rebinning, tested).  The one reload that DOES drop the
  store is a feature-width change: resident rows are meaningless for a
  different-width model, so ``PredictServer.featurestore_for`` swaps
  in a fresh store of the new width and callers re-``put``.
- **LRU under a byte budget.**  ``budget_mb`` bounds device memory;
  capacity is ``budget // (F * 4)`` rows.  A ``put`` of a new entity
  beyond capacity evicts the least-recently-USED entity (gathers and
  puts both refresh recency).  Eviction/hit/miss/resident-bytes ride
  the ``xgbtpu_featurestore_*`` metric family.
- **Functional slab updates.**  Rows live in one ``(capacity+1, F)``
  device array whose last slot is a permanent NaN row (the gather
  padding target, quantizing to bin 0 like engine padding).  ``put``
  is a single ``.at[idx].set(rows)`` — readers holding the previous
  slab reference are unaffected (no torn gathers under concurrent
  puts); the id→slot map and slab swap under one lock.
"""

from __future__ import annotations

import threading
import time as _time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class FeatureStoreMiss(KeyError):
    """predict_by_id asked for entities that are not resident."""

    def __init__(self, missing: List[str]):
        super().__init__(f"{len(missing)} entity id(s) not resident")
        self.missing = missing

    def __str__(self) -> str:  # KeyError would quote the message
        return self.args[0]


class FeatureStore:
    """Device-pinned hot-entity feature rows with LRU byte-budget
    eviction.

    Args:
      num_feature: feature width F; rows are NaN-padded/truncated-
        rejected to it at ``put`` time (the model's width — take it
        from the engine).
      budget_mb: device byte budget for resident rows (capacity =
        budget / 4F rows, minimum 1).
    """

    def __init__(self, num_feature: int, budget_mb: float = 64.0):
        if num_feature < 1:
            raise ValueError("num_feature must be >= 1")
        self.num_feature = int(num_feature)
        self.budget_bytes = int(budget_mb * (1 << 20))
        self.capacity = max(1, self.budget_bytes
                            // (4 * self.num_feature))
        # _lock guards _slots/_free/_slab for readers and the commit
        # swap; _put_lock serializes WRITERS (put/invalidate) so a put
        # can stage its slot math and run the device upload OUTSIDE
        # _lock — gathers (all /predict_by_id traffic) never wait on a
        # transfer, only on the brief map/slab swap
        self._lock = threading.Lock()
        self._put_lock = threading.Lock()
        self._slots: "OrderedDict[str, int]" = OrderedDict()  # LRU order
        self._free: List[int] = list(range(self.capacity))
        import jax.numpy as jnp
        self._jnp = jnp
        # slot `capacity` is the permanent NaN padding row: gathers pad
        # their index vector with it, and every feature quantizes NaN to
        # bin 0 — identical to the engine's own batch padding
        self._slab = jnp.full((self.capacity + 1, self.num_feature),
                              jnp.nan, jnp.float32)

    # --------------------------------------------------------------- info
    def __len__(self) -> int:
        return len(self._slots)

    @property
    def resident_bytes(self) -> int:
        return len(self._slots) * self.num_feature * 4

    def device_bytes(self) -> int:
        """Actual device bytes of the pinned slab (allocated up front,
        independent of how many slots are filled) — what per-model
        catalog rows report next to the engine estimate."""
        return int(getattr(self._slab, "nbytes",
                           (self.capacity + 1) * self.num_feature * 4))

    def ids(self) -> List[str]:
        with self._lock:
            return list(self._slots)

    def missing(self, ids: Sequence) -> List[str]:
        """The subset of ``ids`` not resident, in request order —
        O(len(ids)) dict probes under the lock (NOT an O(capacity)
        snapshot; predict_by_id pre-scans every request through
        this)."""
        with self._lock:
            return [k for k in (str(i) for i in ids)
                    if k not in self._slots]

    def describe(self) -> dict:
        with self._lock:
            return {"rows": len(self._slots), "capacity": self.capacity,
                    "num_feature": self.num_feature,
                    "resident_bytes": self.resident_bytes}

    # ---------------------------------------------------------------- put
    def put(self, ids: Sequence, X) -> Dict[str, int]:
        """Pin rows for ``ids`` (existing ids update in place; new ids
        take free slots, evicting LRU entities past capacity).  ``X`` is
        ``(len(ids), f)`` with ``f <= num_feature`` (NaN-pads to model
        width).  A repeated id in one batch keeps its LAST row (the
        semantics of sequential puts; de-duplicated before the scatter,
        whose repeated-index winner JAX leaves undefined).  One upload,
        one functional slab update, COMMITTED only after the device
        write succeeds: slot math is staged on copies, so a failed
        upload (device OOM, runtime error) leaves membership and the
        slab exactly as they were — no id ever maps to a row that was
        not written for it.  Returns ``{"stored": n, "evicted": k}``."""
        from xgboost_tpu.obs.metrics import (featurestore_metrics,
                                             predict_metrics)
        from xgboost_tpu.serving.engine import pad_to_width
        X = np.asarray(X, np.float32)
        if X.ndim != 2 or X.shape[0] != len(ids):
            raise ValueError(
                f"rows {X.shape} do not match {len(ids)} ids")
        if X.shape[1] > self.num_feature:
            raise ValueError(
                f"rows have {X.shape[1]} features, store width is "
                f"{self.num_feature}")
        keys = [str(i) for i in ids]
        last = {k: j for j, k in enumerate(keys)}   # last occurrence wins
        if len(last) != len(keys):
            keys = list(last)
            X = X[list(last.values())]
        if len(keys) > self.capacity:
            raise ValueError(
                f"{len(keys)} rows exceed store capacity "
                f"{self.capacity} (budget {self.budget_bytes} bytes)")
        X = pad_to_width(X, self.num_feature)
        fm = featurestore_metrics()
        with self._put_lock:
            with self._lock:
                slots = self._slots.copy()
                free = list(self._free)
                slab0 = self._slab
            evicted = 0
            idx = np.empty(len(keys), np.int32)
            for j, k in enumerate(keys):
                slot = slots.get(k)
                if slot is None:
                    if free:
                        slot = free.pop()
                    else:
                        _, slot = slots.popitem(last=False)  # LRU
                        evicted += 1
                slots[k] = slot
                slots.move_to_end(k)
                idx[j] = slot
            t0 = _time.perf_counter()
            rows_dev = self._jnp.asarray(X)
            slab = slab0.at[self._jnp.asarray(idx)].set(rows_dev)
            slab.block_until_ready()  # failure raises BEFORE any commit
            # the ONE upload these rows ever cost: every later
            # predict_by_id gathers them on device for free
            predict_metrics().observe_transfer(
                X.nbytes, _time.perf_counter() - t0)
            with self._lock:
                # membership is writer-only (serialized by _put_lock);
                # gather recency refreshes that landed during the
                # upload are folded into a slightly stale LRU order —
                # an approximation, never a correctness issue
                self._slots = slots
                self._free = free
                self._slab = slab
                if evicted:
                    fm.evictions.inc(evicted)
                fm.resident_bytes.set(self.resident_bytes)
        return {"stored": len(keys), "evicted": evicted}

    # --------------------------------------------------------- invalidate
    def invalidate(self, ids: Optional[Sequence] = None) -> int:
        """Drop entities (all of them when ``ids`` is None).  Returns
        how many were resident.  Slots return to the free list; the
        slab rows are left in place (unreachable — no id maps to
        them)."""
        from xgboost_tpu.obs.metrics import featurestore_metrics
        with self._put_lock, self._lock:
            if ids is None:
                n = len(self._slots)
                self._free.extend(self._slots.values())
                self._slots.clear()
            else:
                n = 0
                for k in (str(i) for i in ids):
                    slot = self._slots.pop(k, None)
                    if slot is not None:
                        self._free.append(slot)
                        n += 1
            featurestore_metrics().resident_bytes.set(self.resident_bytes)
        return n

    # -------------------------------------------------------------- gather
    def gather(self, ids: Sequence, pad_to: Optional[int] = None):
        """Device gather of the rows for ``ids``:
        ``(device (pad_to or n, F) f32, missing_ids)``.  When any id is
        missing, no device work happens (``None`` array) — the caller
        surfaces the miss.  Padding indices point at the permanent NaN
        row.  Hits refresh LRU recency; hit/miss counts feed
        ``xgbtpu_featurestore_{hits,misses}_total``."""
        from xgboost_tpu.obs.metrics import featurestore_metrics
        keys = [str(i) for i in ids]
        n = len(keys)
        out_rows = pad_to if pad_to is not None else n
        if pad_to is not None and pad_to < n:
            raise ValueError(f"pad_to={pad_to} < {n} ids")
        fm = featurestore_metrics()
        with self._lock:
            missing = [k for k in keys if k not in self._slots]
            if missing:
                fm.hits.inc(n - len(missing))
                fm.misses.inc(len(missing))
                return None, missing
            idx = np.full(out_rows, self.capacity, np.int32)
            for j, k in enumerate(keys):
                idx[j] = self._slots[k]
                self._slots.move_to_end(k)
            slab = self._slab
        fm.hits.inc(n)
        # index vector is the only host→device traffic (4 bytes/row of
        # METADATA, not features — the transfer counters stay flat)
        return self._jnp.take(slab, self._jnp.asarray(idx),
                              axis=0), []


def predict_by_id(engine, store: FeatureStore, ids: Sequence,
                  output_margin: bool = False) -> np.ndarray:
    """Predict for resident entities with zero feature upload: gather
    rows on device (padded to the engine's warmed bucket), run
    :meth:`PredictEngine.predict_resident`.  Oversized id lists chunk
    through the top bucket like ``predict``.  Raises
    :class:`FeatureStoreMiss` listing absent ids (the HTTP layer maps
    it to 404 so callers know to ``put`` first)."""
    if len(ids) == 0:
        return engine.predict(np.zeros((0, store.num_feature),
                                       np.float32),
                              output_margin=output_margin)
    # pre-scan membership across ALL chunks so the miss error lists
    # every absent id at once (one put-and-retry round trip, not one
    # per chunk) and no device work runs for a doomed request; a
    # concurrent eviction between this scan and a gather still raises
    # that chunk's (smaller) miss.  This IS the dominant miss path, so
    # it feeds the hit/miss counters (gathers only run when the
    # pre-scan found everything resident)
    absent = store.missing(ids)
    if absent:
        from xgboost_tpu.obs.metrics import featurestore_metrics
        fm = featurestore_metrics()
        fm.misses.inc(len(absent))
        fm.hits.inc(len(ids) - len(absent))
        raise FeatureStoreMiss(absent)
    top = engine.buckets[-1]
    parts = []
    for i in range(0, len(ids), top):
        chunk = ids[i:i + top]
        bucket = engine.bucket_for(len(chunk))
        X_dev, missing = store.gather(chunk, pad_to=bucket)
        if missing:
            raise FeatureStoreMiss(missing)
        parts.append(engine.predict_resident(X_dev, len(chunk),
                                             output_margin=output_margin))
    return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)

"""xgboost_tpu.serving — batched, recompile-free prediction service.

The L6 serving subsystem (SERVING.md): :class:`PredictEngine` owns a
shape-bucketed cache of AOT-compiled predict executables over one
loaded model; :class:`MicroBatcher` coalesces concurrent requests into
single device calls with bounded-queue backpressure;
:class:`ModelRegistry` hot-reloads a watched model path atomically with
rollback, CRC verification before build, and poisoned-fingerprint
memory for corrupt files (RELIABILITY.md); :class:`PredictServer` is
the stdlib HTTP front end with ``/predict``, ``/predict_by_id``,
``/healthz`` (degraded / drain states) and Prometheus ``/metrics``,
draining gracefully on SIGTERM; :class:`FeatureStore` pins hot-entity
feature rows on device so repeat traffic predicts with zero
host→device feature bytes (SERVING.md).

Quickstart::

    python -m xgboost_tpu.serving --model m.bin --port 8080

or from the classic CLI: ``python -m xgboost_tpu task=serve
model_in=m.bin serve_port=8080``.
"""

from xgboost_tpu.serving.batcher import MicroBatcher, QueueFull
from xgboost_tpu.serving.engine import PredictEngine, power_of_two_buckets
from xgboost_tpu.serving.featurestore import (FeatureStore,
                                              FeatureStoreMiss,
                                              predict_by_id)
from xgboost_tpu.serving.http import PredictServer, run_server
from xgboost_tpu.serving.registry import ModelRegistry

__all__ = [
    "PredictEngine",
    "MicroBatcher",
    "QueueFull",
    "ModelRegistry",
    "PredictServer",
    "run_server",
    "power_of_two_buckets",
    "FeatureStore",
    "FeatureStoreMiss",
    "predict_by_id",
]

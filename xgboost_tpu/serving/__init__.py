"""xgboost_tpu.serving — batched, recompile-free prediction service.

The L6 serving subsystem (SERVING.md): :class:`PredictEngine` owns a
shape-bucketed cache of AOT-compiled predict executables over one
loaded model; :class:`MicroBatcher` coalesces concurrent requests into
single device calls with bounded-queue backpressure;
:class:`ModelRegistry` hot-reloads a watched model path atomically with
rollback, CRC verification before build, and poisoned-fingerprint
memory for corrupt files (RELIABILITY.md); :class:`PredictServer` is
the stdlib HTTP front end with ``/predict``, ``/healthz`` (degraded /
drain states) and Prometheus ``/metrics``, draining gracefully on
SIGTERM.

Quickstart::

    python -m xgboost_tpu.serving --model m.bin --port 8080

or from the classic CLI: ``python -m xgboost_tpu task=serve
model_in=m.bin serve_port=8080``.
"""

from xgboost_tpu.serving.batcher import MicroBatcher, QueueFull
from xgboost_tpu.serving.engine import PredictEngine, power_of_two_buckets
from xgboost_tpu.serving.http import PredictServer, run_server
from xgboost_tpu.serving.registry import ModelRegistry

__all__ = [
    "PredictEngine",
    "MicroBatcher",
    "QueueFull",
    "ModelRegistry",
    "PredictServer",
    "run_server",
    "power_of_two_buckets",
]

"""Weighted quantile sketch.

Re-implements the semantics of the reference's ``WQSummary`` /
``WQuantileSketch`` (reference ``src/utils/quantile.h:52-770``): bounded-size
weighted quantile summaries with associative ``merge`` (SetCombine,
``quantile.h:225-278``) and ``prune`` (SetPrune, ``quantile.h:189-219``),
plus the validity invariant of ``WQSummary::CheckValid``
(``quantile.h:165-173``).

This host-side (numpy) sketch is used to propose histogram cut points once
per training run (LightGBM-style global binning) — the TPU-native
replacement for the reference's per-round per-node sketches
(``updater_histmaker-inl.hpp:353-462``).  A fixed-size tensorized form of
the same summary (for on-device distributed merging over a mesh, replacing
rabit's ``SerializeReducer``) lives in ``parallel/sketch_device.py``.

The reference also ships a GK (Greenwald-Khanna, unweighted) sketch
(``quantile.h:383-525``) that nothing in its engine instantiates — the
weighted summary subsumes it (unweighted == all weights 1), so no
separate GK variant exists here.

Summary entries are (value, rmin, rmax, wmin):
  rmin = minimum possible rank of value  (sum of weights strictly below)
  rmax = maximum possible rank of value
  wmin = total weight of entries equal to value
Invariant: rmin + wmin <= rmax.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class QuantileSummary:
    """A weighted quantile summary (struct-of-arrays, sorted by value)."""

    value: np.ndarray  # (k,) float64
    rmin: np.ndarray   # (k,) float64
    rmax: np.ndarray   # (k,) float64
    wmin: np.ndarray   # (k,) float64

    @property
    def size(self) -> int:
        return len(self.value)

    @property
    def total_weight(self) -> float:
        return float(self.rmax[-1]) if self.size else 0.0

    # maximum rank error of this summary (reference WQSummary::MaxError)
    def max_error(self) -> float:
        if self.size == 0:
            return 0.0
        prev_rmax = np.concatenate([[0.0], self.rmax[:-1]])
        return float(np.max(np.maximum(
            self.rmin + self.wmin - prev_rmax,
            self.rmax - self.rmin - self.wmin)))

    def check_valid(self, eps: float = 1e-6) -> None:
        """Invariants of reference WQSummary::CheckValid (quantile.h:165-173)."""
        if self.size == 0:
            return
        assert np.all(self.rmin + self.wmin <= self.rmax + eps), "rmin+wmin > rmax"
        assert np.all(self.rmin >= -eps), "negative rmin"
        assert np.all(self.wmin >= -eps), "negative wmin"
        assert np.all(np.diff(self.value) > 0), "values not strictly increasing"
        assert np.all(np.diff(self.rmin) >= -eps), "rmin not monotone"
        assert np.all(np.diff(self.rmax) >= -eps), "rmax not monotone"

    # -- rank bounds helpers (reference Entry::RMinNext / RMaxPrev) --
    def _rmin_next(self) -> np.ndarray:
        return self.rmin + self.wmin

    def _rmax_prev(self) -> np.ndarray:
        return self.rmax - self.wmin


def empty_summary() -> QuantileSummary:
    z = np.zeros(0, dtype=np.float64)
    return QuantileSummary(z.copy(), z.copy(), z.copy(), z.copy())


def make_summary(values: np.ndarray, weights: np.ndarray | None = None) -> QuantileSummary:
    """Build an exact summary from raw weighted data (vectorized).

    Equivalent to pushing every element into the reference's
    WQuantileSketch and taking the unpruned summary.
    """
    values = np.asarray(values, dtype=np.float64).ravel()
    if weights is None:
        # unweighted fast path: a plain value sort + run-length counts;
        # the general path's stable argsort + ufunc.at dominated
        # external-memory sketch ingest (~8x slower per column)
        values = values[np.isfinite(values)]
        if values.size == 0:
            return empty_summary()
        v = np.sort(values)
        edges = np.flatnonzero(
            np.concatenate([[True], v[1:] != v[:-1]]))
        gv = v[edges]
        gw = np.diff(np.concatenate(
            [edges, [v.size]])).astype(np.float64)
        rmax = np.cumsum(gw)
        return QuantileSummary(gv, rmax - gw, rmax, gw)
    weights = np.asarray(weights, dtype=np.float64).ravel()
    mask = np.isfinite(values) & (weights > 0)
    values, weights = values[mask], weights[mask]
    if values.size == 0:
        return empty_summary()
    order = np.argsort(values, kind="stable")
    v, w = values[order], weights[order]
    # group duplicates
    boundary = np.concatenate([[True], v[1:] != v[:-1]])
    group_id = np.cumsum(boundary) - 1
    n_groups = group_id[-1] + 1
    gw = np.zeros(n_groups, dtype=np.float64)
    np.add.at(gw, group_id, w)
    gv = v[boundary]
    rmax = np.cumsum(gw)
    rmin = rmax - gw
    return QuantileSummary(gv, rmin, rmax, gw)


def merge_summaries(a: QuantileSummary, b: QuantileSummary) -> QuantileSummary:
    """Associative merge — semantics of WQSummary::SetCombine (quantile.h:225-278).

    Vectorized: for an entry of `a` at value v, its combined rank bounds add
    the rank bounds contributed by `b` at v: rmin += RMinNext of the last b
    entry with value < v; rmax += RMaxPrev of the first b entry with
    value > v (or b's total weight if none).  Entries with equal values
    combine directly.
    """
    if a.size == 0:
        return b
    if b.size == 0:
        return a

    def contrib(x: QuantileSummary, other: QuantileSummary):
        # index of first other-entry with value >= x.value
        lo = np.searchsorted(other.value, x.value, side="left")
        # index of first other-entry with value > x.value
        hi = np.searchsorted(other.value, x.value, side="right")
        exact = hi > lo  # other has an entry exactly at x.value
        rmin_next = np.concatenate([[0.0], other._rmin_next()])
        rmax_prev = np.concatenate([other._rmax_prev(),
                                    [other.total_weight]])
        add_rmin = np.where(exact, other.rmin[np.minimum(lo, other.size - 1)],
                            rmin_next[lo])
        add_rmax = np.where(exact, other.rmax[np.minimum(lo, other.size - 1)],
                            rmax_prev[hi])
        add_wmin = np.where(exact, other.wmin[np.minimum(lo, other.size - 1)], 0.0)
        return add_rmin, add_rmax, add_wmin

    a_rmin, a_rmax, a_wmin = contrib(a, b)
    b_rmin, b_rmax, b_wmin = contrib(b, a)

    allv = np.concatenate([a.value, b.value])
    allrmin = np.concatenate([a.rmin + a_rmin, b.rmin + b_rmin])
    allrmax = np.concatenate([a.rmax + a_rmax, b.rmax + b_rmax])
    allwmin = np.concatenate([a.wmin + a_wmin, b.wmin + b_wmin])
    order = np.argsort(allv, kind="stable")
    allv, allrmin, allrmax, allwmin = (allv[order], allrmin[order],
                                       allrmax[order], allwmin[order])
    # deduplicate equal values (each side already contains the other's mass)
    keep = np.concatenate([[True], allv[1:] != allv[:-1]])
    return QuantileSummary(allv[keep], allrmin[keep], allrmax[keep], allwmin[keep])


def prune_summary(s: QuantileSummary, maxsize: int) -> QuantileSummary:
    """Prune to <= maxsize entries — semantics of WQSummary::SetPrune
    (quantile.h:189-219): always keep the extreme entries; select interior
    entries nearest to evenly spaced ranks, using the (RMinNext, RMaxPrev)
    straddle test to bound rank error.
    """
    if s.size <= maxsize or maxsize < 2:
        return s
    begin = s.rmax[0]
    rng = s.rmin[-1] - begin
    n = maxsize - 2
    k = np.arange(1, n)
    dx2 = 2.0 * (k * rng / n + begin)
    mid = s.rmin + s.rmax  # 2 * midpoint rank of each entry
    # i(k): last entry with  mid[i+1] <= dx2  (scan pointer of the reference)
    i = np.searchsorted(mid, dx2, side="right") - 1
    i = np.clip(i, 0, s.size - 2)
    # choose entry i or i+1 by the straddle test
    rmin_next = s._rmin_next()
    rmax_prev = s._rmax_prev()
    use_i = dx2 < rmin_next[i] + rmax_prev[np.minimum(i + 1, s.size - 1)]
    sel = np.where(use_i, i, i + 1)
    sel = np.concatenate([[0], sel, [s.size - 1]])
    sel = np.unique(sel)
    return QuantileSummary(s.value[sel], s.rmin[sel], s.rmax[sel], s.wmin[sel])


def sketch_column(values: np.ndarray, weights: np.ndarray | None,
                  eps: float, sketch_ratio: float = 2.0,
                  chunk: int = 1 << 22) -> QuantileSummary:
    """Sketch one feature column to a bounded-size summary.

    max summary size = sketch_ratio / eps, mirroring
    TrainParam::max_sketch_size (reference ``src/tree/param.h:170-175``).
    Large inputs are processed in chunks and merged+pruned pairwise — the
    multi-level merge of the reference's quantile sketch engine
    (``quantile.h:621-709``) collapsed into a flat fold, which preserves
    the rank-error bound because merge is associative and prune is applied
    at bounded size.
    """
    maxsize = max(2, int(sketch_ratio / eps))
    values = np.asarray(values, dtype=np.float64).ravel()
    if weights is None:
        weights = np.ones_like(values)
    acc = empty_summary()
    for start in range(0, max(len(values), 1), chunk):
        part = make_summary(values[start:start + chunk],
                            np.asarray(weights)[start:start + chunk])
        part = prune_summary(part, maxsize)
        acc = prune_summary(merge_summaries(acc, part), maxsize)
    return acc


def query_quantile(s: QuantileSummary, rank: float) -> float:
    """Value whose rank interval is closest to `rank` (reference
    WQSummary::Query semantics, used for cut proposal)."""
    if s.size == 0:
        return 0.0
    mid = (s.rmin + s.rmax) * 0.5
    idx = int(np.argmin(np.abs(mid - rank)))
    return float(s.value[idx])


def propose_cuts(s: QuantileSummary, max_bin: int) -> np.ndarray:
    """Propose up to max_bin-1 strictly increasing cut values from a summary.

    The TPU binning scheme: a value v maps to bin 1+searchsorted(cuts, v,
    'right') (bin 0 is reserved for missing); a split at cut index j means
    "go left iff v < cuts[j]" — matching the reference's split condition
    semantics (``src/tree/model.h:555-566``).
    """
    if s.size == 0:
        return np.zeros(0, dtype=np.float32)
    total = s.total_weight
    n_cut = max_bin - 1
    if s.size <= n_cut:
        # few distinct values: every distinct value is a cut.  The cut AT the
        # minimum matters for sparse/one-hot features: "v < min" routes all
        # present values right while missing follows the learned default —
        # the split shape the reference finds on agaricus-style indicator
        # features (colmaker's missing-default enumeration,
        # updater_colmaker-inl.hpp:362-414).
        return np.unique(s.value.astype(np.float32))
    ranks = np.arange(1, n_cut + 1) * (total / (n_cut + 1))
    mid = (s.rmin + s.rmax) * 0.5
    idx = np.searchsorted(mid, ranks, side="left")
    idx = np.clip(idx, 1, s.size - 1)  # never cut below the min value
    cuts = np.unique(s.value[idx]).astype(np.float32)
    return cuts

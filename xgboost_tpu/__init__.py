"""xgboost_tpu — a TPU-native gradient boosting framework.

A brand-new JAX/XLA/Pallas implementation of the capabilities of early
XGBoost (reference: mu-bu/xgboost): gbtree + gblinear boosters, the full
objective/metric set, histogram tree learning driven by a distributed
weighted quantile sketch, and row-sharded data-parallel training where
the reference's rabit TCP allreduce becomes ``psum`` over an ICI mesh.

Design stance (see SURVEY.md §7): not a port.  Data is pre-binned into
dense device arrays (uint8 bin ids) instead of CSR/CSC scans; trees are
struct-of-arrays tensors grown level-by-level inside ``jit``; the one
custom kernels are the Pallas histogram/node-stat kernels
(:mod:`xgboost_tpu.ops.pallas_hist`); everything else is XLA.
"""

from xgboost_tpu.config import TrainParam
from xgboost_tpu.data import DMatrix
from xgboost_tpu.external import ExtMemDMatrix
from xgboost_tpu.learner import (Booster, CVPack, aggcv, cv, mknfold,
                                 train)
from xgboost_tpu.parallel.sharded import ShardedDMatrix
from xgboost_tpu.sklearn import XGBModel, XGBClassifier, XGBRegressor

__version__ = "0.1.0"

__all__ = [
    "TrainParam",
    "DMatrix",
    "ExtMemDMatrix",
    "ShardedDMatrix",
    "Booster",
    "train",
    "cv",
    "CVPack",
    "mknfold",
    "aggcv",
    "XGBModel",
    "XGBClassifier",
    "XGBRegressor",
    "__version__",
]

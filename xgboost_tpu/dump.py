"""Model text dump + feature importance.

Follows the reference dump format (``src/tree/model.h:403-458``):
``nid:[fX<cond] yes=L,no=R,missing=M`` with tab indentation per depth,
optional ``,gain=..,cover=..`` stats, and feature-map typed names
(``src/utils/fmap.h``: i=indicator, q=quantitative, int=integer).
Node ids here are heap-order (children of g are 2g+1/2g+2) rather than
the reference's allocation order; structure and semantics match.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def load_fmap(path: str) -> Dict[int, tuple]:
    """Parse a featmap.txt: ``<fid>\\t<name>\\t<type>`` per line."""
    out: Dict[int, tuple] = {}
    if not path:
        return out
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 3:
                out[int(parts[0])] = (parts[1], parts[2])
    return out


def dump_trees(booster, fmap: str = "", with_stats: bool = False) -> List[str]:
    if booster.param.booster == "gblinear":
        return [booster.gbtree.dump_text()]
    fmap_d = load_fmap(fmap)
    out = []
    for tree in booster.gbtree.trees:
        feature = np.asarray(tree.feature)
        thr = np.asarray(tree.threshold)
        default_left = np.asarray(tree.default_left)
        is_leaf = np.asarray(tree.is_leaf)
        leaf_value = np.asarray(tree.leaf_value)
        gain = np.asarray(tree.gain)
        cover = np.asarray(tree.sum_hess)
        lines: List[str] = []

        def rec(nid: int, depth: int):
            indent = "\t" * depth
            f = feature[nid]
            if is_leaf[nid] or f < 0:
                s = f"{indent}{nid}:leaf={leaf_value[nid]:g}"
                if with_stats:
                    s += f",cover={cover[nid]:g}"
                lines.append(s)
                return
            left, right = 2 * nid + 1, 2 * nid + 2
            miss = left if default_left[nid] else right
            if f in fmap_d:
                name, ftype = fmap_d[f]
                if ftype == "i":
                    cond = f"{name}"
                    # indicator: split is presence/absence; missing side is 'no'
                    yes, no = (right, left) if default_left[nid] else (left, right)
                    s = (f"{indent}{nid}:[{cond}] yes={yes},no={no},"
                         f"missing={miss}")
                elif ftype == "int":
                    s = (f"{indent}{nid}:[{name}<{int(np.ceil(thr[nid]))}] "
                         f"yes={left},no={right},missing={miss}")
                else:
                    s = (f"{indent}{nid}:[{name}<{thr[nid]:g}] "
                         f"yes={left},no={right},missing={miss}")
            else:
                s = (f"{indent}{nid}:[f{f}<{thr[nid]:g}] "
                     f"yes={left},no={right},missing={miss}")
            if with_stats:
                s += f",gain={gain[nid]:g},cover={cover[nid]:g}"
            lines.append(s)
            rec(left, depth + 1)
            rec(right, depth + 1)

        # multi-root trees dump each root's subtree (the reference dumps
        # every root, model.h:403-458 over param.num_roots)
        from xgboost_tpu.models.tree import root_level
        n_roots = max(1, getattr(booster.param, "num_roots", 1))
        first = (1 << root_level(n_roots)) - 1
        for r in range(n_roots):
            rec(first + r, 0)
        out.append("\n".join(lines) + "\n")
    return out


def feature_importance(booster, fmap: str = "") -> Dict[str, int]:
    """Split-count importance (reference get_fscore, wrapper/xgboost.py:512-530)."""
    fmap_d = load_fmap(fmap)
    counts: Dict[str, int] = {}
    for tree in booster.gbtree.trees:
        feature = np.asarray(tree.feature)
        is_leaf = np.asarray(tree.is_leaf)
        sum_hess = np.asarray(tree.sum_hess)
        for nid in range(len(feature)):
            f = feature[nid]
            # a real (reachable) split node: has a feature and mass
            if f >= 0 and not is_leaf[nid] and sum_hess[nid] > 0:
                name = fmap_d.get(f, (f"f{f}", "q"))[0]
                counts[name] = counts.get(name, 0) + 1
    return counts

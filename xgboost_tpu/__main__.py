"""Entry point: ``python -m xgboost_tpu <config> [name=value ...]``."""

import sys

from xgboost_tpu.cli import main

sys.exit(main())

"""Alias: ``python -m xgboost_tpu.launch`` → the multi-host launcher
(:mod:`xgboost_tpu.parallel.launch`)."""

import sys

from xgboost_tpu.parallel.launch import main

if __name__ == "__main__":
    sys.exit(main())

"""Objective functions: gradient/hessian computation.

Re-implements the reference objective registry
(``src/learner/objective.h:69-82``, 9 names) with elementwise gradients
as jitted device functions.  Math follows
``src/learner/objective-inl.hpp``:
  - LossType transforms and grads (:22-114)
  - RegLossObj incl. scale_pos_weight (:117-174)
  - SoftmaxMultiClassObj (:177-271) — h = 2 p (1-p)
  - LambdaRank family (:274-570) — pair sampling is host-side per round,
    pair gradients are device-side (see :mod:`xgboost_tpu.rank_obj`).

Margins are (N, K) with K = num output groups (1 unless multiclass).
Gradients returned as (N, K, 2).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-16


def _sigmoid(x):
    return 1.0 / (1.0 + jnp.exp(-x))


class Objective:
    """Base objective (reference IObjFunction, src/learner/objective.h:13-59)."""

    name: str = ""
    default_metric: str = "rmse"
    output_group_count: int = 1

    def set_param(self, name, value):
        pass

    def get_gradient(self, margin, info, iteration, n_rows):
        """margin: (N, K) jnp; info: MetaInfo; returns (N, K, 2) jnp."""
        raise NotImplementedError

    def pred_transform(self, margin, output_margin=False):
        return margin

    def eval_transform(self, margin):
        """Transform used before metric evaluation (softprob for multiclass)."""
        return self.pred_transform(margin)

    def fused_eval_transform(self):
        """:meth:`eval_transform` as a pure function with STABLE
        identity (jit static arg of the fused scan's device-resident
        eval; same contract as :meth:`fused_grad` — a bound method
        would hash by objective instance and recompile the scan for
        every new booster)."""
        return _identity_transform

    def prob_to_margin(self, base_score: float) -> float:
        return base_score

    def fused_grad(self, info=None):
        """A pure ``(margin, label, weight, iteration) -> (N, K, 2)``
        gradient for the fused multi-round scan (GBTree.do_boost_fused),
        or None when the objective needs host-side work per round
        (custom objectives, host-impl rank).  ``info`` lets objectives
        with static per-dataset structure (device LambdaRank's group
        tables) close over it.  Must return a STABLE function identity
        per (hyperparameters, dataset) so the scan's jit cache hits
        across boosters."""
        return None

    def validate_labels(self, info) -> None:
        """Host-side label validation (once per info); shared by
        get_gradient and the fused path which bypasses it."""


def _identity_transform(margin):
    return margin


def _softmax_transform(margin):
    return jax.nn.softmax(margin, axis=1)


@functools.lru_cache(maxsize=None)
def _regloss_fused(loss: str, spw: float):
    def f(margin, label, weight, iteration):
        return _regloss_grad(margin, label, weight, loss, spw)
    return f


def _softmax_fused(margin, label, weight, iteration):
    return _softmax_grad(margin, label, weight)


@functools.partial(jax.jit, static_argnames=("loss", "spw"))
def _regloss_grad(margin, label, weight, loss: str, spw: float):
    x = margin[:, 0]
    if loss == "linear":
        p = x
        g, h = p - label, jnp.ones_like(p)
    else:  # all logistic variants share gradient math on transformed pred
        p = _sigmoid(x)
        g = p - label
        h = jnp.maximum(p * (1.0 - p), _EPS)
    w = jnp.where(label == 1.0, weight * spw, weight)
    return jnp.stack([g * w, h * w], axis=-1)[:, None, :]


class RegLossObj(Objective):
    """reg:linear, reg:logistic, binary:logistic, binary:logitraw
    (reference RegLossObj, objective-inl.hpp:117-174)."""

    def __init__(self, name: str):
        self.name = name
        self.scale_pos_weight = 1.0
        self.loss = "linear" if name == "reg:linear" else "logistic"
        self.transform_pred = name in ("reg:logistic", "binary:logistic")
        self.default_metric = {"reg:linear": "rmse", "reg:logistic": "rmse",
                               "binary:logistic": "error",
                               "binary:logitraw": "auc"}[name]

    def set_param(self, name, value):
        if name == "scale_pos_weight":
            self.scale_pos_weight = float(value)

    def validate_labels(self, info) -> None:
        if self.loss != "linear":
            def _check():
                lab = np.asarray(info.label)
                # negated-containment form so NaN labels fail too (the
                # reference's CheckLabel is !(l >= 0 && l <= 1))
                if (~((lab >= 0) & (lab <= 1))).any():
                    raise ValueError(
                        "label must be in [0,1] for logistic regression")
            info.check_once("logistic_label_ok", _check)

    def get_gradient(self, margin, info, iteration, n_rows):
        self.validate_labels(info)
        return _regloss_grad(margin, info.label_dev(),
                             info.weight_dev(n_rows), self.loss,
                             float(self.scale_pos_weight))

    def pred_transform(self, margin, output_margin=False):
        if output_margin or not self.transform_pred:
            return margin
        return _sigmoid(margin)

    def eval_transform(self, margin):
        # metrics see transformed predictions except for logitraw's margin
        # (reference EvalTransform == PredTransform for RegLossObj)
        return self.pred_transform(margin)

    def prob_to_margin(self, base_score: float) -> float:
        if self.name != "reg:linear":
            assert 0.0 < base_score < 1.0, \
                "base_score must be in (0,1) for logistic loss"
            return -np.log(1.0 / base_score - 1.0)
        return base_score

    def fused_grad(self, info=None):
        return _regloss_fused(self.loss, float(self.scale_pos_weight))

    def fused_eval_transform(self):
        return _sigmoid if self.transform_pred else _identity_transform


@jax.jit
def _softmax_grad(margin, label, weight):
    p = jax.nn.softmax(margin, axis=1)          # (N, K)
    K = margin.shape[1]
    y = jax.nn.one_hot(label.astype(jnp.int32), K, dtype=p.dtype)
    g = (p - y) * weight[:, None]
    h = 2.0 * p * (1.0 - p) * weight[:, None]
    return jnp.stack([g, h], axis=-1)


class SoftmaxMultiClassObj(Objective):
    """multi:softmax / multi:softprob (reference objective-inl.hpp:177-271)."""

    def __init__(self, output_prob: bool):
        self.name = "multi:softprob" if output_prob else "multi:softmax"
        self.output_prob = output_prob
        self.nclass = 0
        self.default_metric = "merror"

    @property
    def output_group_count(self):
        return max(1, self.nclass)

    def set_param(self, name, value):
        if name == "num_class":
            self.nclass = int(value)

    def validate_labels(self, info) -> None:
        assert self.nclass > 0, "must set num_class to use softmax"
        def _check():
            lab = np.asarray(info.label)
            # negated-containment form so NaN labels fail too
            if (~((lab >= 0) & (lab < self.nclass))).any():
                raise ValueError(
                    f"SoftmaxMultiClassObj: label must be in [0, {self.nclass})")
        info.check_once(f"softmax_label_ok_{self.nclass}", _check)

    def get_gradient(self, margin, info, iteration, n_rows):
        self.validate_labels(info)
        return _softmax_grad(margin, info.label_dev(),
                             info.weight_dev(n_rows))

    def pred_transform(self, margin, output_margin=False):
        if output_margin:
            return margin
        if self.output_prob:
            return jax.nn.softmax(margin, axis=1)
        return jnp.argmax(margin, axis=1).astype(jnp.float32)[:, None]

    def eval_transform(self, margin):
        return jax.nn.softmax(margin, axis=1)

    def fused_grad(self, info=None):
        return _softmax_fused

    def fused_eval_transform(self):
        return _softmax_transform


def create_objective(name: str) -> Objective:
    """Objective factory (reference CreateObjFunction, objective.h:69-82)."""
    if name in ("reg:linear", "reg:logistic", "binary:logistic",
                "binary:logitraw"):
        return RegLossObj(name)
    if name == "multi:softmax":
        return SoftmaxMultiClassObj(False)
    if name == "multi:softprob":
        return SoftmaxMultiClassObj(True)
    if name in ("rank:pairwise", "rank:ndcg", "rank:map"):
        from xgboost_tpu.rank_obj import LambdaRankObj
        return LambdaRankObj(name)
    raise ValueError(f"unknown objective function type: {name}")

"""DMatrix: data container for xgboost_tpu.

Covers the reference's data layer (SURVEY.md §2.1 L2):
  - ``MetaInfo`` — labels/weights/groups/base_margin/root_index/fold_index
    (reference ``src/learner/dmatrix.h:18-145``), including sidecar file
    loading (``train.txt.group`` etc., ``dmatrix.h:108-137``).
  - CSR storage + libsvm text parsing with optional rank/npart split
    loading for distributed training (reference
    ``src/io/simple_dmatrix-inl.hpp:69-117``).
  - binary save/load cache (reference magic 0xffffab01,
    ``simple_dmatrix-inl.hpp:154-251``) — here an ``.npz`` container, with
    the same ``path#cachefile`` / auto ``.buffer`` conventions handled in
    :mod:`xgboost_tpu.io.dispatch`.
  - ``slice``/``mknfold`` support (reference ``wrapper/xgboost_wrapper.cpp:200-245``).

TPU-native difference: downstream training never iterates CSR — the
matrix is quantized once into a dense (n_rows, n_features) bin-id array
(:mod:`xgboost_tpu.binning`), the analog of the reference's decision to
route all distributed/external training through histogram updaters
(``learner-inl.hpp:91-97,263-267``).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional, Sequence

import numpy as np


class MetaInfo:
    """Per-row (and per-group) metadata (reference src/learner/dmatrix.h:18-145)."""

    __slots__ = ("label", "weight", "group_ptr", "base_margin",
                 "root_index", "fold_index", "_dev_cache", "version")

    def __init__(self):
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.group_ptr: Optional[np.ndarray] = None  # (n_groups+1,) int
        self.base_margin: Optional[np.ndarray] = None
        self.root_index: Optional[np.ndarray] = None
        self.fold_index: Optional[np.ndarray] = None
        # device copies + validation marks, reused across boosting rounds
        # (re-uploading label/weight every round costs more host<->device
        # time than the gradient computation itself)
        self._dev_cache: dict = {}
        self.version = 0  # bumped on set_field: snapshot invalidation

    def get_weight(self, n_rows: int) -> np.ndarray:
        if self.weight is None:
            return np.ones(n_rows, dtype=np.float32)
        return self.weight

    def label_dev(self):
        """Device-resident label, cached until the field changes."""
        if "label" not in self._dev_cache:
            import jax.numpy as jnp
            self._dev_cache["label"] = jnp.asarray(self.label)
        return self._dev_cache["label"]

    def weight_dev(self, n_rows: int):
        """Device-resident per-row weight (ones when unset), cached."""
        key = ("weight", n_rows)
        if key not in self._dev_cache:
            import jax.numpy as jnp
            self._dev_cache[key] = jnp.asarray(self.get_weight(n_rows))
        return self._dev_cache[key]

    def check_once(self, mark: str, fn) -> None:
        """Run a host-side validation once per (info, mark); cleared when
        any field is re-set."""
        if mark not in self._dev_cache:
            fn()
            self._dev_cache[mark] = True

    def set_field(self, name: str, value) -> None:
        self._dev_cache.clear()
        self.version += 1
        if value is None:
            setattr(self, name if name != "group" else "group_ptr", None)
            return
        arr = np.asarray(value)
        if name == "group":
            # group sizes -> cumulative pointer (reference MetaInfo::SetInfo)
            self.group_ptr = np.concatenate(
                [[0], np.cumsum(arr.astype(np.int64))])
        elif name in ("label", "weight", "base_margin"):
            setattr(self, name, arr.astype(np.float32).ravel())
        elif name in ("root_index", "fold_index"):
            # uint32: full reference XGDMatrixSetUIntInfo range
            setattr(self, name, arr.astype(np.uint32).ravel())
        else:
            raise ValueError(f"unknown meta field {name!r}")

    def get_field(self, name: str):
        if name == "group":
            return self.group_ptr
        return getattr(self, name)

    def slice(self, rindex: np.ndarray) -> "MetaInfo":
        out = MetaInfo()
        for f in ("label", "weight", "base_margin", "root_index", "fold_index"):
            v = getattr(self, f)
            if v is not None:
                setattr(out, f, v[rindex])
        # group structure does not survive arbitrary row slicing (same as
        # reference XGDMatrixSliceDMatrix, which drops group_ptr)
        return out


class DMatrix:
    """Sparse (CSR) data matrix with metadata.

    Accepts: libsvm text path, dense numpy array (with ``missing`` marker),
    scipy CSR/CSC, or a (indptr, indices, values, num_col) CSR tuple.

    Dense ndarray input is held by REFERENCE and CSR is built lazily on
    first ``values``/``indices``/``indptr`` access (a one-off predict
    never builds it — the fused path uploads views of the caller's
    buffer).  Consequence: mutating the source array between
    construction and first use changes what this matrix sees — and only
    for float32 input (``np.asarray`` copies while converting any other
    dtype); snapshot with ``DMatrix(arr.copy())`` when the buffer will
    be reused.
    """

    def __new__(cls, data: Any = None, *args, **kwargs):
        # "ext:path" / "!path#cache" URIs construct the paged matrix
        # (reference io.cpp routes paged magics and the '!' HalfRAM
        # prefix the same way, io.cpp:36-81); ExtMemDMatrix is not a
        # subclass, so __init__ below is skipped for it.  The '!' prefix
        # is only honored TOGETHER with a '#cache' suffix, matching the
        # reference's routing (io.cpp:70-73 checks '!' inside the
        # cache-file branch only; a bare '!file' is a plain file load).
        if cls is DMatrix and isinstance(data, str) and (
                data.startswith("ext:")
                or (data.startswith("!") and "#" in data)):
            from xgboost_tpu.external import ExtMemDMatrix
            path = data[4:] if data.startswith("ext:") else data
            names = ("label", "weight", "missing", "base_margin", "group",
                     "num_col", "silent", "feature_names")
            for name, val in zip(names, args):
                kwargs.setdefault(name, val)
            unsupported = [k for k in ("base_margin", "group", "num_col",
                                       "feature_names")
                           if kwargs.get(k) is not None]
            if unsupported:
                raise ValueError(
                    f"DMatrix({data!r}): {unsupported} not supported on "
                    "external-memory matrices; construct ExtMemDMatrix and "
                    "use set_base_margin/set_group instead")
            return ExtMemDMatrix(
                path, label=kwargs.get("label"),
                weight=kwargs.get("weight"),
                missing=kwargs.get("missing", np.nan),
                silent=kwargs.get("silent", True))
        return super().__new__(cls)

    def __init__(self, data: Any, label=None, weight=None, missing: float = np.nan,
                 base_margin=None, group=None, num_col: Optional[int] = None,
                 silent: bool = True, feature_names: Optional[Sequence[str]] = None):
        self.info = MetaInfo()
        self.feature_names = list(feature_names) if feature_names else None
        self._col_cache = None
        # CSR storage is LAZY for dense ndarray input: a one-off
        # ``DMatrix(arr)`` predict never touches values/indices/indptr
        # (the fused path uploads views of ``arr`` itself and the
        # density gate reads num_nonmissing()), so the ~2x host copy is
        # only built when something actually iterates CSR (training,
        # sparse binning, slicing...).  The properties below
        # materialize on first access — transparent to every consumer.
        self._indptr = self._indices = self._values = None
        self._lazy_dense: Optional[tuple] = None  # (arr, missing)
        self._lazy_lock = threading.Lock()
        self._nnz: Optional[int] = None

        if isinstance(data, str):
            from xgboost_tpu.io.dispatch import load_dmatrix_into
            load_dmatrix_into(self, data, silent=silent)
        elif isinstance(data, tuple) and len(data) == 4:
            self.indptr, self.indices, self.values, self._num_col = data
            self.indptr = np.asarray(self.indptr, dtype=np.int64)
            self.indices = np.asarray(self.indices, dtype=np.int32)
            self.values = np.asarray(self.values, dtype=np.float32)
        elif _is_scipy_sparse(data):
            csr = data.tocsr()
            self.indptr = csr.indptr.astype(np.int64)
            self.indices = csr.indices.astype(np.int32)
            self.values = csr.data.astype(np.float32)
            self._num_col = csr.shape[1]
        else:
            arr = np.asarray(data, dtype=np.float32)
            if arr.ndim != 2:
                raise ValueError("expected 2D array")
            self._lazy_dense = (arr, missing)
            self._num_col = arr.shape[1]

        if num_col is not None:
            self._num_col = max(num_col, getattr(self, "_num_col", 0))
        elif not hasattr(self, "_num_col") or self._num_col is None:
            self._num_col = int(self.indices.max()) + 1 if len(self.indices) else 0

        if label is not None:
            self.info.set_field("label", label)
        if weight is not None:
            self.info.set_field("weight", weight)
        if base_margin is not None:
            self.info.set_field("base_margin", base_margin)
        if group is not None:
            self.info.set_field("group", group)

    # ------------------------------------------------------------------
    def _from_dense_locked(self, arr: np.ndarray, missing: float) -> None:
        # called with _lazy_lock held (lazy materialization) — the one
        # CSR-building path since dense __init__ went lazy
        if np.isnan(missing):
            present = ~np.isnan(arr)
        else:
            present = arr != missing
        counts = present.sum(axis=1)
        self.indptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        rows, cols = np.nonzero(present)
        self.indices = cols.astype(np.int32)
        self.values = arr[rows, cols].astype(np.float32)
        self._num_col = arr.shape[1]

    # ------------------------------------------------------- lazy CSR
    def _materialize(self) -> None:
        """Build CSR from the pending dense source, once, thread-safely
        (an eagerly-built DMatrix was always shareable across predict
        threads; lazy construction must not regress that).  Writes land
        in order — arrays first, the ``_lazy_dense = None`` "done" mark
        last — so a lock-free property read that sees the mark cleared
        also sees complete arrays (GIL ordering)."""
        with self._lazy_lock:
            if self._lazy_dense is None:
                return  # another thread won the race (or nothing lazy)
            arr, missing = self._lazy_dense
            nc = self._num_col  # num_col= widening must survive rebuild
            self._from_dense_locked(arr, missing)
            self._num_col = max(nc, self._num_col)
            self._lazy_dense = None

    @property
    def indptr(self) -> np.ndarray:
        if self._indptr is None:
            self._materialize()
        return self._indptr

    @indptr.setter
    def indptr(self, v) -> None:
        self._indptr = v

    @property
    def indices(self) -> np.ndarray:
        if self._indices is None:
            self._materialize()
        return self._indices

    @indices.setter
    def indices(self, v) -> None:
        self._indices = v

    @property
    def values(self) -> np.ndarray:
        if self._values is None:
            self._materialize()
        return self._values

    @values.setter
    def values(self, v) -> None:
        self._values = v

    def num_nonmissing(self) -> int:
        """Count of stored (non-missing) entries — ``len(values)``
        without forcing a lazy dense matrix to materialize CSR: the
        predict-path density gate (learner.py) reads ONLY this, so a
        dense one-off ``DMatrix(arr)`` routes straight to the fused
        upload of ``arr`` itself.  Counted in bounded row blocks (the
        boolean temp stays ~16 MB however large the matrix is);
        bit-identical to ``len(self.values)`` by construction."""
        src = self._lazy_dense  # one read: may be cleared concurrently
        if self._values is not None or src is None:
            return len(self.values)
        if self._nnz is None:
            arr, missing = src
            block = max(1, (1 << 24) // max(arr.shape[1], 1))
            total = 0
            for s in range(0, arr.shape[0], block):
                chunk = arr[s:s + block]
                if np.isnan(missing):
                    total += int(np.count_nonzero(~np.isnan(chunk)))
                else:
                    total += int(np.count_nonzero(chunk != missing))
            self._nnz = total
        return self._nnz

    def predict_dense_src(self) -> Optional[np.ndarray]:
        """The dense f32 NaN-missing buffer this matrix wraps, when CSR
        is still pending — the zero-copy upload source for the fused
        predict path (learner._dense_block_fn).  None once CSR exists
        or when the missing marker / dtype / layout would change the
        uploaded values."""
        src = self._lazy_dense  # one read: may be cleared concurrently
        if src is None:
            return None
        arr, missing = src
        if (np.isnan(missing) and arr.dtype == np.float32
                and arr.flags.c_contiguous):
            return arr
        return None

    # ------------------------------------------------------------------
    @property
    def num_row(self) -> int:
        src = self._lazy_dense  # one read: may be cleared concurrently
        if self._indptr is None and src is not None:
            return int(src[0].shape[0])
        return len(self.indptr) - 1

    @property
    def num_col(self) -> int:
        return self._num_col

    def set_label(self, label):
        self.info.set_field("label", label)

    def set_weight(self, weight):
        self.info.set_field("weight", weight)

    def set_group(self, group):
        self.info.set_field("group", group)

    def set_base_margin(self, margin):
        self.info.set_field("base_margin", margin)

    # generic typed field accessors (reference wrapper/xgboost.py:166-183:
    # get/set_float_info for label/weight/base_margin; get/set_uint_info
    # for root_index/fold_index, plus read-only group_ptr)
    _FLOAT_FIELDS = ("label", "weight", "base_margin")
    _UINT_FIELDS = ("root_index", "fold_index")

    def set_float_info(self, field: str, data) -> None:
        if field not in self._FLOAT_FIELDS:
            raise ValueError(f"unknown float field {field!r}")
        self.info.set_field(field, np.asarray(data, dtype=np.float32))

    def get_float_info(self, field: str) -> np.ndarray:
        """Unset fields return an EMPTY array (reference parity: callers
        detect unset weights via size == 0 — unlike get_weight(), which
        materializes the implicit all-ones weights)."""
        if field not in self._FLOAT_FIELDS:
            raise ValueError(f"unknown float field {field!r}")
        v = self.info.get_field(field)
        return (np.zeros(0, np.float32) if v is None
                else np.asarray(v, np.float32).copy())

    def set_uint_info(self, field: str, data) -> None:
        if field not in self._UINT_FIELDS:
            raise ValueError(f"unknown uint field {field!r}")
        arr = np.asarray(data)
        if arr.size and (not np.issubdtype(arr.dtype, np.integer)
                         or int(arr.min()) < 0
                         or int(arr.max()) > np.iinfo(np.uint32).max):
            raise ValueError(
                f"set_uint_info({field!r}): values must fit uint32 "
                "(reference XGDMatrixSetUIntInfo range)")
        self.info.set_field(field, arr)

    def get_uint_info(self, field: str) -> np.ndarray:
        if field == "group_ptr":  # read-only: set via set_group (sizes)
            v = self.info.group_ptr
        elif field in self._UINT_FIELDS:
            v = self.info.get_field(field)
        else:
            raise ValueError(f"unknown uint field {field!r}")
        return (np.zeros(0, np.uint32) if v is None
                else np.asarray(v, np.uint32).copy())

    def get_label(self):
        # a copy: in-place mutation of the returned array would bypass
        # MetaInfo's device-cache invalidation (set via set_field only)
        return None if self.info.label is None else self.info.label.copy()

    def get_weight(self):
        w = self.info.get_weight(self.num_row)
        # copy only stored arrays: the unset case is already a fresh ones()
        return w.copy() if self.info.weight is not None else w

    def get_base_margin(self):
        return (None if self.info.base_margin is None
                else self.info.base_margin.copy())

    # ------------------------------------------------------------------
    def column_values(self, col: int):
        """(row_ids, values) of one column — used by sketch/binning and
        gblinear (the reference's ColBatch access, src/data.h:92-118)."""
        if self._col_cache is None:
            order = np.argsort(self.indices, kind="stable")
            sorted_cols = self.indices[order]
            starts = np.searchsorted(sorted_cols, np.arange(self._num_col + 1))
            row_of_entry = np.repeat(np.arange(self.num_row, dtype=np.int64),
                                     np.diff(self.indptr))
            self._col_cache = (order, starts, row_of_entry)
        order, starts, row_of_entry = self._col_cache
        sel = order[starts[col]:starts[col + 1]]
        return row_of_entry[sel], self.values[sel]

    def to_dense(self, missing: float = np.nan) -> np.ndarray:
        out = np.full((self.num_row, self._num_col), missing, dtype=np.float32)
        rows = np.repeat(np.arange(self.num_row), np.diff(self.indptr))
        out[rows, self.indices] = self.values
        return out

    def slice(self, rindex) -> "DMatrix":
        """Row-slice (reference XGDMatrixSliceDMatrix, xgboost_wrapper.cpp:200-245)."""
        rindex = np.asarray(rindex, dtype=np.int64)
        counts = np.diff(self.indptr)[rindex]
        new_indptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        sel = np.concatenate(
            [np.arange(self.indptr[r], self.indptr[r + 1]) for r in rindex]
        ) if len(rindex) else np.zeros(0, dtype=np.int64)
        out = DMatrix((new_indptr, self.indices[sel], self.values[sel],
                       self._num_col))
        out.info = self.info.slice(rindex)
        out.feature_names = self.feature_names
        return out

    # ------------------------------------------------------------------
    def save_binary(self, path: str, silent: bool = True) -> None:
        """Binary cache (the reference's 0xffffab01 .buffer format,
        simple_dmatrix-inl.hpp:154-251 — here an npz container)."""
        fields = {"indptr": self.indptr, "indices": self.indices,
                  "values": self.values,
                  "num_col": np.int64(self._num_col)}
        for f in ("label", "weight", "base_margin", "root_index", "fold_index"):
            v = getattr(self.info, f)
            if v is not None:
                fields["meta_" + f] = v
        if self.info.group_ptr is not None:
            fields["meta_group_ptr"] = self.info.group_ptr
        # write through a file object: np.savez(str) appends ".npz",
        # which would break the reference's name.buffer convention.
        # Streamed into the tmp+rename staging file (XGT003): a crash
        # mid-save must not leave a torn cache that every later run
        # trusts blindly — and the cache can be the biggest file this
        # process writes, so no in-memory copy of the archive either
        from xgboost_tpu.reliability.integrity import atomic_writer
        with atomic_writer(path) as f:
            np.savez(f, **fields)

    @classmethod
    def load_binary(cls, path: str) -> "DMatrix":
        with np.load(path) as z:
            dm = cls((z["indptr"], z["indices"], z["values"],
                      int(z["num_col"])))
            for f in ("label", "weight", "base_margin", "root_index",
                      "fold_index"):
                if "meta_" + f in z:
                    setattr(dm.info, f, z["meta_" + f])
            if "meta_group_ptr" in z:
                dm.info.group_ptr = z["meta_group_ptr"]
        return dm


def _is_scipy_sparse(data) -> bool:
    try:
        import scipy.sparse as sp  # noqa: deferred optional dependency
        return sp.issparse(data)
    except ImportError:
        return False


# ----------------------------------------------------------------------
def parse_libsvm(path: str, rank: int = 0, nparts: int = 1):
    """Parse libsvm text into CSR; optional round-robin row sharding.

    The reference splits a text source across workers at load time
    (``simple_dmatrix-inl.hpp:89-96``); here ``rank``/``nparts`` select a
    row shard (row i kept iff i % nparts == rank).
    Returns (indptr, indices, values, labels).

    Uses the native multithreaded parser (native/xgtpu_io.cpp — the
    reference's OMP chunk parser, ``src/io/libsvm_parser.h``) when
    available; the pure-Python path below is the fallback.
    """
    from xgboost_tpu.native import parse_libsvm_native
    out = parse_libsvm_native(path, rank, nparts)
    if out is not None:
        return out
    return parse_libsvm_python(path, rank, nparts)


def iter_libsvm_chunks(path: str, chunk_rows: int, rank: int = 0,
                       nparts: int = 1):
    """Stream a libsvm text file as bounded CSR chunks.

    Yields (indptr, indices, values, labels) per ``chunk_rows`` rows —
    host memory stays at one chunk regardless of file size (the
    reference's ThreadedParser streaming, ``src/io/libsvm_parser.h``).
    Shared by the whole-file parser below and external-memory ingest.
    """
    labels: list = []
    indptr: list = [0]
    indices: list = []
    values: list = []

    def emit():
        out = (np.asarray(indptr, dtype=np.int64),
               np.asarray(indices, dtype=np.int32),
               np.asarray(values, dtype=np.float32),
               np.asarray(labels, dtype=np.float32))
        labels.clear(), indices.clear(), values.clear()
        indptr.clear(), indptr.append(0)
        return out

    with open(path, "rb") as f:
        for i, raw in enumerate(f):
            if nparts > 1 and i % nparts != rank:
                continue
            parts = raw.split()
            if not parts:
                continue
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                k, _, v = tok.partition(b":")
                indices.append(int(k))
                values.append(float(v))
            indptr.append(len(indices))
            if len(labels) >= chunk_rows:
                yield emit()
    if labels:
        yield emit()


def parse_libsvm_python(path: str, rank: int = 0, nparts: int = 1):
    """Pure-Python libsvm parser (fallback + parity oracle for the
    native parser's tests)."""
    chunks = list(iter_libsvm_chunks(path, 1 << 62, rank, nparts))
    if not chunks:
        return (np.zeros(1, np.int64), np.zeros(0, np.int32),
                np.zeros(0, np.float32), np.zeros(0, np.float32))
    return chunks[0]


def load_meta_sidecars(dmat: DMatrix, path: str) -> None:
    """Load ``path.group`` / ``path.weight`` / ``path.base_margin`` sidecar
    files if present (reference MetaInfo::TryLoadGroup/TryLoadFloatInfo,
    src/learner/dmatrix.h:108-137)."""
    if os.path.exists(path + ".group"):
        dmat.info.set_field(
            "group", np.loadtxt(path + ".group", dtype=np.int64, ndmin=1))
    for name in ("weight", "base_margin"):
        if os.path.exists(path + "." + name):
            dmat.info.set_field(
                name, np.loadtxt(path + "." + name, dtype=np.float32, ndmin=1))

"""xgboost_tpu.catalog — the multi-tenant model catalog (CATALOG.md
section of SERVING.md).

The fleet and pipeline historically spoke exactly ONE model; production
serves many.  This package holds the pieces that multiplex N named
models over the same replica set without giving up any single-model
guarantee:

- :class:`ModelCatalog` — N named models per replica, each an
  independent :class:`~xgboost_tpu.serving.registry.ModelRegistry`
  (own AOT bucket set, own hot-reload poll, own optional feature
  store), admitted under ONE shared device-memory budget with
  LRU-evict + hysteresis for cold models' engines;
- :class:`TenantQuotas` — per-model admission control at the router
  (in-flight cap -> 503, token-bucket rate limit -> 429), so one
  tenant's overload never touches its neighbors;
- :func:`parse_manifest` — the ``catalog=`` knob's ``name=path``
  manifest format (inline comma-separated or a file).

Per-tenant TRAINING lanes need no new machinery: one
:class:`~xgboost_tpu.pipeline.ContinuousTrainer` per tenant, each with
its own workdir + publish path (``xgboost_tpu.pipeline.
run_tenant_lanes``), gives every tenant its own fsync'd gated-hash
ledger — the "zero ungated models served" chaos contract holds per
tenant by construction (tools/chaos_loop.py --catalog proves it).
"""

from xgboost_tpu.catalog.catalog import (CatalogEntry,  # noqa: F401
                                         ModelCatalog, UnknownModel,
                                         parse_manifest)
from xgboost_tpu.catalog.quota import TenantQuotas  # noqa: F401

__all__ = ["ModelCatalog", "CatalogEntry", "UnknownModel",
           "parse_manifest", "TenantQuotas"]

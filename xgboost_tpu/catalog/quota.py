"""Per-tenant admission control at the fleet router.

Two independent guards per model, both O(1) per request:

- an **in-flight cap** (``tenant_inflight``): a tenant may hold at most
  N requests inside the router at once; past it, THAT tenant sheds 503
  (retryable — capacity returns when its own responses drain);
- a **token-bucket rate limit** (``tenant_rate`` req/s with
  ``tenant_burst`` depth): sustained overload sheds 429 (the client is
  asking faster than its contract; backing off is the fix).

The point is isolation: both guards are keyed by model name, so tenant
A's overload consumes A's tokens and A's in-flight slots and nothing
else — B's requests never queue behind A's storm (asserted in
tests/test_catalog.py and tools/chaos_loop.py --catalog).  Clocks are
monotonic (XGT006): token refill measures durations, not wall time.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class _TenantState:
    __slots__ = ("tokens", "last", "inflight")

    def __init__(self, burst: float):
        self.tokens = burst
        self.last = time.monotonic()
        self.inflight = 0


class TenantQuotas:
    """Per-model in-flight + rate admission.  ``try_admit`` returns
    None (admitted; pair with ``release``) or the shed reason:
    ``"rate"`` (-> 429) / ``"inflight"`` (-> 503)."""

    def __init__(self, inflight_limit: int = 0, rate: float = 0.0,
                 burst: float = 8.0):
        self.inflight_limit = int(inflight_limit)
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._state: Dict[str, _TenantState] = {}
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.inflight_limit > 0 or self.rate > 0

    def try_admit(self, model: str) -> Optional[str]:
        with self._lock:
            st = self._state.get(model)
            if st is None:
                st = self._state[model] = _TenantState(self.burst)
            if self.rate > 0:
                now = time.monotonic()
                st.tokens = min(self.burst,
                                st.tokens + (now - st.last) * self.rate)
                st.last = now
                if st.tokens < 1.0:
                    return "rate"
            if (self.inflight_limit > 0
                    and st.inflight >= self.inflight_limit):
                # checked BEFORE spending a token: an inflight-shed
                # request must not also drain the tenant's rate budget
                return "inflight"
            if self.rate > 0:
                st.tokens -= 1.0
            st.inflight += 1
            return None

    def release(self, model: str) -> None:
        with self._lock:
            st = self._state.get(model)
            if st is not None and st.inflight > 0:
                st.inflight -= 1

    def inflight(self, model: str) -> int:
        with self._lock:
            st = self._state.get(model)
            return st.inflight if st is not None else 0

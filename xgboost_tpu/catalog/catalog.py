"""ModelCatalog: N named models per replica under one device budget.

Each entry is an independent :class:`~xgboost_tpu.serving.registry.
ModelRegistry` — its own AOT bucket executables, its own hot-reload
poll on its own published path, its own micro-batcher and optional
feature store — so per-model behavior (bitwise parity, zero
steady-state recompile, instant rollback) is exactly the single-model
serving stack's.  What the catalog adds is the SHARED part:

- **one device-memory budget** (``serve_catalog_mb``) across all
  resident engines.  Admitting a model past the budget LRU-evicts the
  coldest resident entries' engines (registry poller stopped, batcher
  closed, references dropped); a later request re-admits on demand
  (rebuild + warm off the serving path, like any reload).  Eviction
  respects a **hysteresis** window: an entry used within the last
  ``hysteresis_sec`` is never evicted, so hot models keep their
  compiled executables — the recompile-free steady state survives a
  churning cold tail (recompile_guard-pinned in tests/test_catalog.py);
- **one resolve surface** (``/predict?model=``): requests name a model,
  the bare path resolves to the configured default — the catalog-of-one
  path IS the old single-model path.

Admission builds happen OUTSIDE the catalog lock (an engine warmup is
seconds of compile; requests for other models must not queue behind
it) under a per-entry admit lock — the same staged-commit discipline
as the feature store's ``put``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

from xgboost_tpu.obs import event, span


class UnknownModel(KeyError):
    """The request named a model the catalog does not hold (HTTP 404)."""

    def __init__(self, name: str, known):
        super().__init__(name)
        self.model = name
        self.known = sorted(known)

    def __str__(self):
        return (f"unknown model {self.model!r} (catalog holds: "
                f"{', '.join(self.known) or '<empty>'})")


def parse_manifest(spec: str) -> Dict[str, str]:
    """Parse the ``catalog=`` knob: ``name=path`` entries, either
    inline comma-separated (``a=./a.model,b=./b.model``) or one per
    line in a manifest file (``#`` comments allowed — the same grammar
    as ``parse_config_file``).  Entry order is preserved; the first
    entry is the default model unless ``catalog_default`` overrides."""
    out: Dict[str, str] = {}
    if "=" in spec:
        pairs = [p for p in spec.split(",") if p.strip()]
    else:
        from xgboost_tpu.config import parse_config_file
        return dict(parse_config_file(spec))
    for p in pairs:
        name, path = p.split("=", 1)
        name, path = name.strip(), path.strip()
        if not name or not path:
            raise ValueError(f"bad catalog manifest entry {p!r} "
                             "(want name=path)")
        out[name] = path
    if not out:
        raise ValueError(f"empty catalog manifest {spec!r}")
    return out


class CatalogEntry:
    """One named model's slot: path + (when resident) its registry,
    batcher and feature store.  ``last_hash`` outlives eviction so
    /healthz and the heartbeat advertisement keep naming the content
    this entry would serve."""

    def __init__(self, name: str, path: str, featurestore_mb: float = 0.0):
        self.name = name
        self.path = os.fspath(path)
        self.featurestore_mb = float(featurestore_mb)
        self.registry = None            # ModelRegistry when resident
        self.batcher = None             # MicroBatcher when resident
        self._featurestore = None
        self._fs_lock = threading.Lock()
        self._admit_lock = threading.Lock()
        self.last_used = 0.0            # monotonic; 0 = never touched
        self.last_hash: Optional[str] = None
        self.admissions = 0
        self.evictions = 0
        self._file_hash_cache = None    # ((mtime_ns, size), sha256)

    @property
    def resident(self) -> bool:
        return self.registry is not None

    def device_bytes(self) -> int:
        reg = self.registry
        return reg.device_bytes() if reg is not None else 0

    def content_hash(self) -> Optional[str]:
        """The hash of what this entry serves (resident) or WOULD serve
        on admission: its last served content, else the manifest file's
        bytes (cached by mtime+size — healthz and every heartbeat read
        this, and a cold model's file rarely changes)."""
        reg = self.registry
        if reg is not None:
            return reg.content_hash
        if self.last_hash is not None:
            return self.last_hash
        import hashlib
        try:
            st = os.stat(self.path)
            key = (st.st_mtime_ns, st.st_size)
            cached = self._file_hash_cache
            if cached is not None and cached[0] == key:
                return cached[1]
            with open(self.path, "rb") as f:
                h = hashlib.sha256(f.read()).hexdigest()
        except OSError:
            return None
        self._file_hash_cache = (key, h)
        return h

    def featurestore_for(self):
        """The entry's feature store, swapped when the model's feature
        width changes across a reload (same width-swap discipline as
        the single-model server's ``featurestore_for``)."""
        if self.featurestore_mb <= 0 or self.registry is None:
            return None
        engine = self.registry.engine
        with self._fs_lock:
            fs = self._featurestore
            if fs is None or fs.num_feature != engine.num_feature:
                from xgboost_tpu.serving.featurestore import FeatureStore
                fs = FeatureStore(engine.num_feature,
                                  budget_mb=self.featurestore_mb)
                self._featurestore = fs
            return fs

    def describe(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        d = {"path": self.path, "resident": self.resident,
             "model_hash": self.content_hash(),
             "evictions": self.evictions,
             "last_used_sec": (round(now - self.last_used, 3)
                               if self.last_used else None)}
        reg = self.registry
        if reg is not None:
            d["model_version"] = reg.version
            d["model_hash"] = reg.content_hash
            d["buckets_compiled"] = reg.engine.num_compiled
            d["device_bytes"] = reg.device_bytes()
            d["poisoned"] = reg.poisoned
        fs = self._featurestore
        if fs is not None:
            d["featurestore_rows"] = len(fs)
        return d


class ModelCatalog:
    """Named models -> independent serving stacks, one shared budget.

    Args:
      budget_mb: shared device byte budget across all resident engines
        (0 = unlimited; the catalog-of-one default).
      hysteresis_sec: entries used within this window are never
        evicted (anti-thrash; keeps hot models' executables pinned).
      default: model name bare requests resolve to (default: the first
        added entry).
      registry_factory: ``path -> ModelRegistry`` — how an admitted
        entry builds (run_server closes this over its engine kwargs).
      batcher_factory: optional ``registry -> MicroBatcher`` for the
        HTTP tier; direct API users skip it and predict on
        ``entry.registry`` themselves.
    """

    def __init__(self, budget_mb: float = 0.0, hysteresis_sec: float = 3.0,
                 default: str = "",
                 registry_factory: Optional[Callable] = None,
                 batcher_factory: Optional[Callable] = None):
        self.budget_bytes = int(budget_mb * 1e6) if budget_mb > 0 else 0
        self.hysteresis_sec = float(hysteresis_sec)
        self.default = default
        self._registry_factory = registry_factory
        self._batcher_factory = batcher_factory
        self._entries: Dict[str, CatalogEntry] = {}  # insertion-ordered
        self._lock = threading.Lock()
        from xgboost_tpu.obs.metrics import catalog_metrics
        self.metrics = catalog_metrics()

    # ------------------------------------------------------------- build
    def add_model(self, name: str, path: str, registry=None, batcher=None,
                  featurestore=None,
                  featurestore_mb: float = 0.0) -> CatalogEntry:
        """Register a named model.  With ``registry`` the entry starts
        resident (run_server's eagerly-built default model); without,
        it is admitted lazily on first resolve."""
        entry = CatalogEntry(name, path, featurestore_mb=featurestore_mb)
        if registry is not None:
            entry.registry = registry
            entry.batcher = batcher
            entry._featurestore = featurestore
            entry.last_hash = registry.content_hash
            entry.last_used = time.monotonic()
            entry.admissions += 1
        with self._lock:
            if name in self._entries:
                raise ValueError(f"catalog already holds model {name!r}")
            self._entries[name] = entry
            if not self.default:
                self.default = name
            self.metrics.models_configured.set(len(self._entries))
            self._note_gauges_locked()
        return entry

    def remove_model(self, name: str) -> bool:
        """Detach a named model (the placer's manifest-delta remove
        path): evict its engine if resident, drop the entry, stop
        advertising it on the next heartbeat.  The default model is
        pinned (the HTTP tier's single-model attributes alias it) —
        removing it raises.  Returns False for a name the catalog does
        not hold (detach is idempotent)."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                return False
            if name == self.default:
                raise ValueError(
                    f"model {name!r} is the catalog default and cannot "
                    "be detached")
            if entry.resident:
                self._evict_locked(entry)
            del self._entries[name]
            self.metrics.models_configured.set(len(self._entries))
            self._note_gauges_locked()
        event("catalog.remove", model=name)
        return True

    @classmethod
    def from_manifest(cls, manifest: Dict[str, str], **kwargs
                      ) -> "ModelCatalog":
        cat = cls(**kwargs)
        for name, path in manifest.items():
            cat.add_model(name, path)
        return cat

    # ----------------------------------------------------------- resolve
    def resolve(self, name: str = "") -> CatalogEntry:
        """The serving entry for ``name`` (default model when empty),
        admitted on demand.  Touches the LRU clock."""
        name = name or self.default
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise UnknownModel(name, self._entries)
            entry.last_used = time.monotonic()
            if entry.resident:
                if (self.budget_bytes
                        and self._bytes_used_locked() > self.budget_bytes):
                    # an eagerly-warmed catalog can START over budget
                    # with every entry inside the hysteresis window;
                    # the cold tail sheds here once it ages out
                    self._enforce_budget_locked(keep=name)
                    self._note_gauges_locked()
                self.metrics.requests.inc(name)
                return entry
        self._admit(entry)
        self.metrics.requests.inc(name)
        return entry

    def get(self, name: str = "") -> CatalogEntry:
        """Peek without admitting or touching the LRU clock."""
        name = name or self.default
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise UnknownModel(name, self.names())
        return entry

    def _admit(self, entry: CatalogEntry) -> None:
        """Build + warm an evicted entry OFF the catalog lock (per-entry
        admit lock serializes concurrent resolves of the same model),
        then install and enforce the budget."""
        if self._registry_factory is None:
            raise RuntimeError(
                f"model {entry.name!r} is not resident and the catalog "
                "has no registry_factory to admit it")
        with entry._admit_lock:
            if entry.resident:
                return
            with span("catalog.admit", model=entry.name, path=entry.path):
                registry = self._registry_factory(entry.path)
                batcher = (self._batcher_factory(registry)
                           if self._batcher_factory is not None else None)
            with self._lock:
                entry.registry = registry
                entry.batcher = batcher
                entry.last_hash = registry.content_hash
                entry.last_used = time.monotonic()
                entry.admissions += 1
                self.metrics.admissions.inc()
                self._enforce_budget_locked(keep=entry.name)
                self._note_gauges_locked()
            registry.start()
            event("catalog.admit", model=entry.name,
                  model_hash=registry.content_hash)

    # ------------------------------------------------------------ budget
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes_used_locked()

    def _bytes_used_locked(self) -> int:
        return sum(e.device_bytes() for e in self._entries.values())

    def _enforce_budget_locked(self, keep: str = "") -> None:
        """LRU-evict cold residents until the budget holds.  Entries
        inside the hysteresis window (and ``keep``, the entry being
        admitted) are exempt — a fully-hot catalog is allowed to sit
        over budget rather than thrash its own working set."""
        if not self.budget_bytes:
            return
        now = time.monotonic()
        while self._bytes_used_locked() > self.budget_bytes:
            # the default entry is pinned: the HTTP tier's registry/
            # batcher attributes alias it (single-model back-compat), so
            # evicting it would leave the server pointing at a stopped
            # registry while resolve() rebuilds a fresh one
            victims = [e for e in self._entries.values()
                       if e.resident and e.name != keep
                       and e.name != self.default
                       and now - e.last_used >= self.hysteresis_sec]
            if not victims:
                break
            self._evict_locked(min(victims, key=lambda e: e.last_used))

    def _evict_locked(self, entry: CatalogEntry) -> None:
        registry, batcher = entry.registry, entry.batcher
        entry.last_hash = registry.content_hash
        entry.registry = None
        entry.batcher = None
        entry._featurestore = None
        entry.evictions += 1
        self.metrics.evictions.inc()
        registry.stop()
        if batcher is not None:
            batcher.close()
        event("catalog.evict", model=entry.name,
              model_hash=entry.last_hash)

    def _note_gauges_locked(self) -> None:
        self.metrics.models_resident.set(
            sum(1 for e in self._entries.values() if e.resident))
        self.metrics.bytes_used.set(self._bytes_used_locked())
        self.metrics.bytes_budget.set(self.budget_bytes)

    # ------------------------------------------------------------- state
    def names(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def entries(self) -> List[CatalogEntry]:
        with self._lock:
            return list(self._entries.values())

    def __len__(self) -> int:
        return len(self._entries)

    def models(self) -> Dict[str, Dict[str, Optional[str]]]:
        """The advertisement the replica's heartbeat carries: every
        configured model (resident or not — an evicted model is still
        SERVABLE, it just re-admits on first hit) with the content hash
        it would serve and its current device-byte footprint (0 while
        evicted — the placer falls back to manifest file size for
        cost)."""
        with self._lock:
            return {e.name: {"path": e.path, "hash": e.content_hash(),
                             "bytes": e.device_bytes()}
                    for e in self._entries.values()}

    def describe(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "default": self.default,
                "configured": len(self._entries),
                "resident": sum(1 for e in self._entries.values()
                                if e.resident),
                "bytes_used": self._bytes_used_locked(),
                "bytes_budget": self.budget_bytes,
                "models": {e.name: e.describe(now)
                           for e in self._entries.values()},
            }

    # --------------------------------------------------------- lifecycle
    def start(self) -> None:
        for e in self.entries():
            if e.registry is not None:
                e.registry.start()

    def stop(self) -> None:
        for e in self.entries():
            reg, batcher = e.registry, e.batcher
            if reg is not None:
                reg.stop()
            if batcher is not None:
                batcher.close()

"""Per-round timing breakdown + jax.profiler trace capture, plus the
Prometheus-style serving metrics (:class:`ServingMetrics`) consumed by
``xgboost_tpu.serving``'s ``GET /metrics`` endpoint.

The analog of the reference's ``report_stats`` accounting
(``subtree/rabit/src/allreduce_mock.h:52-56,87-95``: per-version
allreduce time and checkpoint cost) and of SURVEY.md §5.1's "keep the
report_stats idea".  Two levels:

- ``profile=1`` — host-side phase timing per boosting round (predict /
  gradient / grow / eval), printed per round and summarized at the end.
  Phases force a true device barrier at their boundaries so async
  dispatch doesn't smear costs across phases.  On remote-attached
  backends (tunnels) a barrier costs a full round-trip, so per-phase
  numbers are inflated by that constant — see PROFILE.md; off by
  default.
- ``profile=2`` — additionally captures a ``jax.profiler`` trace into
  ``profile_dir`` (default ``./xgtpu_profile``) for XProf/TensorBoard —
  the device-side view of kernel time.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict
from typing import Dict, Optional, Sequence, Tuple


class RoundProfiler:
    """Collects per-phase wall time per boosting round."""

    def __init__(self, level: int = 1, trace_dir: Optional[str] = None,
                 out=None):
        import sys
        self.level = level
        self.trace_dir = trace_dir or "./xgtpu_profile"
        self.out = out if out is not None else sys.stderr
        self.rounds = []
        self._current = None
        self._tracing = False

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self.level >= 2 and not self._tracing:
            import jax
            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True

    def stop(self):
        if self._tracing:
            import jax
            jax.profiler.stop_trace()
            self._tracing = False
            print(f"[prof] jax.profiler trace written to {self.trace_dir}",
                  file=self.out)

    # ---------------------------------------------------------- round phases
    def begin_round(self, iteration: int):
        self._current = {"round": iteration, "phases": {}, "t0": None}

    def phase(self, name: str):
        """Context manager timing one phase of the current round.  Call
        ``.block(x)`` inside (or rely on the caller's own sync) to pin
        async device work to this phase."""
        return _Phase(self, name)

    def end_round(self):
        if self._current is None:
            return
        c = self._current
        total = sum(c["phases"].values())
        parts = " ".join(f"{k}={v * 1e3:.1f}ms"
                         for k, v in c["phases"].items())
        print(f"[prof] round {c['round']}: total={total * 1e3:.1f}ms "
              f"{parts}", file=self.out)
        self.rounds.append(c)
        self._current = None

    # ------------------------------------------------------------- summary
    def summary(self) -> str:
        if not self.rounds:
            return "[prof] no rounds recorded"
        agg = defaultdict(float)
        for r in self.rounds:
            for k, v in r["phases"].items():
                agg[k] += v
        total = sum(agg.values())
        n = len(self.rounds)
        lines = [f"[prof] {n} rounds, {total:.3f}s total, "
                 f"{total / n * 1e3:.1f}ms/round"]
        for k, v in sorted(agg.items(), key=lambda kv: -kv[1]):
            lines.append(f"[prof]   {k:<10s} {v:8.3f}s  "
                         f"{v / total * 100:5.1f}%  {v / n * 1e3:8.1f}ms/round")
        return "\n".join(lines)

    def print_summary(self):
        print(self.summary(), file=self.out)


class _Phase:
    def __init__(self, prof: RoundProfiler, name: str):
        self.prof = prof
        self.name = name
        self._blocked = None

    def block(self, x):
        """Record device arrays whose completion closes this phase."""
        self._blocked = x
        return x

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._blocked is not None and exc[0] is None:
            import jax
            jax.block_until_ready(self._blocked)
            # block_until_ready is advisory on some remote-attached
            # backends (axon tunnel); one single-element host pull is a
            # true barrier on the in-order stream (last leaf suffices)
            leaves = [x for x in jax.tree.leaves(self._blocked)
                      if hasattr(x, "ravel")
                      and getattr(x, "is_fully_addressable", True)]
            if leaves:
                jax.device_get(leaves[-1].ravel()[:1])
        cur = self.prof._current
        if cur is None and self.prof.rounds:
            # outside begin/end (e.g. eval after end_round): fold into
            # the most recent round
            cur = self.prof.rounds[-1]
        if cur is not None:
            cur["phases"][self.name] = (
                cur["phases"].get(self.name, 0.0)
                + time.perf_counter() - self.t0)
        return False


# --------------------------------------------------------------- serving
# Prometheus-style metric primitives for the serving subsystem.  These
# follow the RoundProfiler conventions — named per-phase accounting,
# render() as the print_summary analog — but expose the text exposition
# format a scraper expects instead of stderr lines.

# latency buckets in seconds: 0.5ms .. 5s, roughly x2 per step
_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5, 5.0)
# batch-size buckets in rows: powers of two
_ROWS_BUCKETS = tuple(float(1 << i) for i in range(15))


class Counter:
    """Monotonic counter (Prometheus ``counter``)."""

    def __init__(self, name: str, help_text: str = ""):
        self.name, self.help = name, help_text
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._v += v

    @property
    def value(self) -> float:
        return self._v

    def render(self) -> str:
        return (f"# HELP {self.name} {self.help}\n"
                f"# TYPE {self.name} counter\n"
                f"{self.name} {_fmt(self._v)}\n")


class Gauge:
    """Settable value (Prometheus ``gauge``)."""

    def __init__(self, name: str, help_text: str = ""):
        self.name, self.help = name, help_text
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._v += v

    @property
    def value(self) -> float:
        return self._v

    def render(self) -> str:
        return (f"# HELP {self.name} {self.help}\n"
                f"# TYPE {self.name} gauge\n"
                f"{self.name} {_fmt(self._v)}\n")


class Histogram:
    """Fixed-bucket histogram (Prometheus ``histogram``) with quantile
    estimation by linear interpolation within the winning bucket —
    enough resolution for p50/p99 gauges on the metrics page."""

    def __init__(self, name: str, help_text: str = "",
                 buckets: Sequence[float] = _LATENCY_BUCKETS):
        self.name, self.help = name, help_text
        self.bounds = tuple(sorted(buckets))
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, x: float) -> None:
        i = bisect.bisect_left(self.bounds, x)
        with self._lock:
            self._counts[i] += 1
            self._sum += x
            self._n += 1

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Approximate q-quantile (0..1) from the bucket counts."""
        with self._lock:
            n = self._n
            counts = list(self._counts)
        if n == 0:
            return 0.0
        target = q * n
        cum = 0.0
        for i, c in enumerate(counts):
            prev = cum
            cum += c
            if cum >= target:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else lo
                if c == 0 or hi <= lo:
                    return hi
                return lo + (hi - lo) * (target - prev) / c
        return self.bounds[-1]

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        cum = 0
        with self._lock:
            counts = list(self._counts)
            total, s = self._n, self._sum
        for bound, c in zip(self.bounds, counts):
            cum += c
            lines.append(f'{self.name}_bucket{{le="{_fmt(bound)}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        lines.append(f"{self.name}_sum {_fmt(s)}")
        lines.append(f"{self.name}_count {total}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    return f"{int(v)}" if float(v).is_integer() else repr(float(v))


class ReliabilityMetrics:
    """Process-wide failure-path accounting (RELIABILITY.md): how often
    the crash-safety machinery actually engaged.  One instance per
    process (:func:`reliability_metrics`), shared by the learner's
    model I/O, the CLI checkpoint ring, and the serving stack; rendered
    into the serving ``GET /metrics`` body alongside ServingMetrics."""

    def __init__(self, prefix: str = "xgbtpu_reliability"):
        p = prefix
        self.integrity_failures = Counter(
            f"{p}_integrity_failures_total",
            "persisted files that failed CRC/footer verification")
        self.ring_fallbacks = Counter(
            f"{p}_ckpt_ring_fallbacks_total",
            "checkpoint loads that fell back past a corrupt ring member")
        self.quarantines = Counter(
            f"{p}_quarantined_files_total",
            "corrupt files moved aside as *.corrupt")
        self.poisoned_reloads = Counter(
            f"{p}_poisoned_reload_skips_total",
            "reload polls skipped because the file content is known-bad")
        self.shed_requests = Counter(
            f"{p}_shed_requests_total",
            "abandoned (caller timed out) requests shed before dispatch")
        self.faults_injected = Counter(
            f"{p}_faults_injected_total",
            "chaos faults fired by the injection registry")
        self.drain_seconds = Gauge(
            f"{p}_drain_seconds",
            "duration of the last HTTP drain (SIGTERM to stopped)")
        self._all = (self.integrity_failures, self.ring_fallbacks,
                     self.quarantines, self.poisoned_reloads,
                     self.shed_requests, self.faults_injected,
                     self.drain_seconds)

    def render(self) -> str:
        return "".join(m.render() for m in self._all)


_RELIABILITY: Optional[ReliabilityMetrics] = None
_RELIABILITY_LOCK = threading.Lock()


def reliability_metrics() -> ReliabilityMetrics:
    """The process-wide ReliabilityMetrics singleton.  Counters are
    cumulative for the process lifetime; tests read deltas."""
    global _RELIABILITY
    if _RELIABILITY is None:
        with _RELIABILITY_LOCK:
            if _RELIABILITY is None:
                _RELIABILITY = ReliabilityMetrics()
    return _RELIABILITY


class ServingMetrics:
    """Metric registry for the serving subsystem (see SERVING.md for the
    full schema).  One instance is shared by engine + batcher + registry
    + HTTP front end; :meth:`render` produces the ``GET /metrics`` body.
    """

    def __init__(self, prefix: str = "xgbtpu_serving"):
        self.prefix = prefix
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()
        p = prefix
        self.requests = self.counter(
            f"{p}_requests_total", "prediction requests received")
        self.rows = self.counter(
            f"{p}_rows_total", "real (caller-supplied) rows predicted")
        self.padded_rows = self.counter(
            f"{p}_padded_rows_total",
            "padding rows added to reach the shape bucket")
        self.rejected = self.counter(
            f"{p}_rejected_total", "requests rejected with QueueFull (503)")
        self.errors = self.counter(
            f"{p}_errors_total", "requests that raised during prediction")
        self.batches = self.counter(
            f"{p}_batches_total", "coalesced device batches executed")
        self.compiles = self.counter(
            f"{p}_compiles_total", "predict executables compiled")
        self.reloads = self.counter(
            f"{p}_reloads_total", "successful model hot-reloads")
        self.reload_errors = self.counter(
            f"{p}_reload_errors_total", "failed model reload attempts")
        self.queue_rows = self.gauge(
            f"{p}_queue_rows", "rows currently waiting in the batch queue")
        self.model_version = self.gauge(
            f"{p}_model_version", "monotonic version of the served model")
        self.batch_rows = self.histogram(
            f"{p}_batch_rows", "rows per coalesced device batch",
            _ROWS_BUCKETS)
        self.latency = self.histogram(
            f"{p}_latency_seconds",
            "request latency, submit to result (includes queueing)")

    # ------------------------------------------------------- constructors
    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._register(Counter(name, help_text))

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._register(Gauge(name, help_text))

    def histogram(self, name: str, help_text: str = "",
                  buckets: Sequence[float] = _LATENCY_BUCKETS) -> Histogram:
        return self._register(Histogram(name, help_text, buckets))

    def _register(self, m):
        with self._lock:
            if m.name in self._metrics:
                return self._metrics[m.name]
            self._metrics[m.name] = m
            return m

    # ------------------------------------------------------------- render
    def quantiles(self, qs: Tuple[float, ...] = (0.5, 0.99)
                  ) -> Dict[float, float]:
        return {q: self.latency.quantile(q) for q in qs}

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        parts = [m.render() for m in metrics]
        # p50/p99 latency as plain gauges (scrapers that don't do
        # histogram_quantile still get the headline numbers)
        for q, label in ((0.5, "p50"), (0.99, "p99")):
            v = self.latency.quantile(q)
            name = f"{self.prefix}_latency_{label}_seconds"
            parts.append(f"# HELP {name} {label} request latency\n"
                         f"# TYPE {name} gauge\n{name} {_fmt(v)}\n")
        # the process-wide reliability counters ride along so one scrape
        # covers both steady-state and failure-path behavior
        parts.append(reliability_metrics().render())
        return "".join(parts)

"""Compatibility shim: the profiling/metrics layer moved to
:mod:`xgboost_tpu.obs` (OBSERVABILITY.md).

Everything that used to live here — :class:`RoundProfiler` (``profile=1/2``
per-round phase timing), the Prometheus-style primitives
(:class:`Counter`/:class:`Gauge`/:class:`Histogram`) and the
:class:`ServingMetrics`/:class:`ReliabilityMetrics` groups — is
re-exported unchanged, so ``from xgboost_tpu.profiling import ...``
keeps working.  New code should import from ``xgboost_tpu.obs``
directly, which also carries the pieces that never existed here:
tracing spans, the structured event log, :class:`TrainingMetrics`, the
``metrics_port=`` scrape server, and per-worker collective stats.
"""

from __future__ import annotations

from xgboost_tpu.obs.metrics import (_LATENCY_BUCKETS,  # noqa: F401
                                     _ROWS_BUCKETS, Counter, Gauge,
                                     Histogram, LabeledCounter,
                                     LabeledGauge, MetricsRegistry,
                                     ReliabilityMetrics, ServingMetrics,
                                     TrainingMetrics, _fmt, registry,
                                     reliability_metrics,
                                     training_metrics)
from xgboost_tpu.obs.profiler import RoundProfiler, _Phase  # noqa: F401

__all__ = [
    "RoundProfiler",
    "Counter", "Gauge", "Histogram", "LabeledCounter", "LabeledGauge",
    "MetricsRegistry", "registry",
    "ServingMetrics", "ReliabilityMetrics", "TrainingMetrics",
    "reliability_metrics", "training_metrics",
]

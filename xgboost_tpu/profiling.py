"""Per-round timing breakdown + jax.profiler trace capture.

The analog of the reference's ``report_stats`` accounting
(``subtree/rabit/src/allreduce_mock.h:52-56,87-95``: per-version
allreduce time and checkpoint cost) and of SURVEY.md §5.1's "keep the
report_stats idea".  Two levels:

- ``profile=1`` — host-side phase timing per boosting round (predict /
  gradient / grow / eval), printed per round and summarized at the end.
  Phases force a true device barrier at their boundaries so async
  dispatch doesn't smear costs across phases.  On remote-attached
  backends (tunnels) a barrier costs a full round-trip, so per-phase
  numbers are inflated by that constant — see PROFILE.md; off by
  default.
- ``profile=2`` — additionally captures a ``jax.profiler`` trace into
  ``profile_dir`` (default ``./xgtpu_profile``) for XProf/TensorBoard —
  the device-side view of kernel time.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Optional


class RoundProfiler:
    """Collects per-phase wall time per boosting round."""

    def __init__(self, level: int = 1, trace_dir: Optional[str] = None,
                 out=None):
        import sys
        self.level = level
        self.trace_dir = trace_dir or "./xgtpu_profile"
        self.out = out if out is not None else sys.stderr
        self.rounds = []
        self._current = None
        self._tracing = False

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self.level >= 2 and not self._tracing:
            import jax
            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True

    def stop(self):
        if self._tracing:
            import jax
            jax.profiler.stop_trace()
            self._tracing = False
            print(f"[prof] jax.profiler trace written to {self.trace_dir}",
                  file=self.out)

    # ---------------------------------------------------------- round phases
    def begin_round(self, iteration: int):
        self._current = {"round": iteration, "phases": {}, "t0": None}

    def phase(self, name: str):
        """Context manager timing one phase of the current round.  Call
        ``.block(x)`` inside (or rely on the caller's own sync) to pin
        async device work to this phase."""
        return _Phase(self, name)

    def end_round(self):
        if self._current is None:
            return
        c = self._current
        total = sum(c["phases"].values())
        parts = " ".join(f"{k}={v * 1e3:.1f}ms"
                         for k, v in c["phases"].items())
        print(f"[prof] round {c['round']}: total={total * 1e3:.1f}ms "
              f"{parts}", file=self.out)
        self.rounds.append(c)
        self._current = None

    # ------------------------------------------------------------- summary
    def summary(self) -> str:
        if not self.rounds:
            return "[prof] no rounds recorded"
        agg = defaultdict(float)
        for r in self.rounds:
            for k, v in r["phases"].items():
                agg[k] += v
        total = sum(agg.values())
        n = len(self.rounds)
        lines = [f"[prof] {n} rounds, {total:.3f}s total, "
                 f"{total / n * 1e3:.1f}ms/round"]
        for k, v in sorted(agg.items(), key=lambda kv: -kv[1]):
            lines.append(f"[prof]   {k:<10s} {v:8.3f}s  "
                         f"{v / total * 100:5.1f}%  {v / n * 1e3:8.1f}ms/round")
        return "\n".join(lines)

    def print_summary(self):
        print(self.summary(), file=self.out)


class _Phase:
    def __init__(self, prof: RoundProfiler, name: str):
        self.prof = prof
        self.name = name
        self._blocked = None

    def block(self, x):
        """Record device arrays whose completion closes this phase."""
        self._blocked = x
        return x

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._blocked is not None and exc[0] is None:
            import jax
            jax.block_until_ready(self._blocked)
            # block_until_ready is advisory on some remote-attached
            # backends (axon tunnel); one single-element host pull is a
            # true barrier on the in-order stream (last leaf suffices)
            leaves = [x for x in jax.tree.leaves(self._blocked)
                      if hasattr(x, "ravel")
                      and getattr(x, "is_fully_addressable", True)]
            if leaves:
                jax.device_get(leaves[-1].ravel()[:1])
        cur = self.prof._current
        if cur is None and self.prof.rounds:
            # outside begin/end (e.g. eval after end_round): fold into
            # the most recent round
            cur = self.prof.rounds[-1]
        if cur is not None:
            cur["phases"][self.name] = (
                cur["phases"].get(self.name, 0.0)
                + time.perf_counter() - self.t0)
        return False

"""CLI entry point: ``python -m xgboost_tpu.analysis [paths...]``.

Exit-code contract (what CI keys off):

  0  clean (no unsuppressed, non-baselined findings)
  1  findings
  2  usage / internal error

``tools/xgtpu_lint.py`` is a thin wrapper around this module.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from xgboost_tpu.analysis import core
from xgboost_tpu.analysis.rules import all_rules, rules_by_code


def _default_paths() -> List[str]:
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m xgboost_tpu.analysis",
        description="xgtpu-lint: JAX-aware static analysis for the "
                    "xgboost_tpu tree (rule catalog: ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint "
                         "(default: the xgboost_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--rules", default=None, metavar="XGT00x[,..]",
                    help="run only the named rules")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="baseline file (default: ANALYSIS_BASELINE.json "
                         "at the repo root, when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline (report full debt)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept every current finding into the "
                         "baseline file and exit 0")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print baselined findings")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 2

    try:
        rules = (rules_by_code(args.rules.split(","))
                 if args.rules else all_rules())
    except ValueError as e:
        print(f"xgtpu-lint: {e}", file=sys.stderr)
        return 2

    if args.list_rules:
        for r in rules:
            doc = (r.__class__.__doc__ or "").strip().splitlines()[0]
            print(f"{r.code}  {r.name:<28s} {doc}")
        return 0

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"xgtpu-lint: no such path: {p}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or core.default_baseline_path()
    baseline = None
    if not args.no_baseline and not args.write_baseline:
        if os.path.exists(baseline_path):
            try:
                baseline = core.Baseline.load(baseline_path)
            except (OSError, ValueError, json.JSONDecodeError) as e:
                print(f"xgtpu-lint: bad baseline {baseline_path}: {e}",
                      file=sys.stderr)
                return 2
        elif args.baseline:
            print(f"xgtpu-lint: baseline not found: {baseline_path}",
                  file=sys.stderr)
            return 2

    result = core.run(paths, baseline=baseline, rules=rules)

    if args.write_baseline:
        if args.rules:
            print("xgtpu-lint: --write-baseline cannot be combined with "
                  "--rules (a partial-rule scan would drop every other "
                  "rule's accepted debt from the baseline)",
                  file=sys.stderr)
            return 2
        # merge, don't clobber: entries outside the scanned paths are
        # kept, so a subdirectory scan cannot erase the rest of the
        # accepted-debt ledger
        try:
            old = (core.Baseline.load(baseline_path)
                   if os.path.exists(baseline_path) else core.Baseline())
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"xgtpu-lint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        merged = old.rescoped(result.findings, paths)
        merged.dump(baseline_path)
        print(f"xgtpu-lint: accepted {len(result.findings)} finding(s) "
              f"for the scanned paths ({sum(merged.counts.values())} "
              f"total baselined) -> {baseline_path}", file=sys.stderr)
        return 0

    if args.as_json:
        print(json.dumps(result.to_json(), indent=2))
    else:
        core.render_report(result, verbose=args.verbose)
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())

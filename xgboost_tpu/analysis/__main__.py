"""CLI entry point: ``python -m xgboost_tpu.analysis [paths...]``.

Exit-code contract (what CI keys off):

  0  clean (no unsuppressed, non-baselined findings)
  1  findings
  2  usage / internal error

Cross-file contract rules (XGT008-XGT012 + XGT016/XGT017,
analysis/contracts.py) run alongside the per-file rules by default:
facts are collected from the whole repo (package + ``tools/``)
regardless of which subset of paths was scanned, because a contract is
only checkable whole.  ``--changed [REF]`` narrows REPORTING to files
touched vs. a git ref (the fast pre-commit loop); ``--write-contracts``
regenerates the committed ``ANALYSIS_CONTRACTS.json`` inventory;
``--sarif`` renders the report as SARIF 2.1.0 (one run per rule code)
for editor/CI ingestion — same findings, same exit contract.

``tools/xgtpu_lint.py`` is a thin wrapper around this module.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Set

from xgboost_tpu.analysis import core
from xgboost_tpu.analysis.contracts import (CONTRACT_CODES,
                                            CONTRACT_RULE_DOCS,
                                            default_engine, repo_root)
from xgboost_tpu.analysis.rules import all_rules, rules_by_code


def _default_paths() -> List[str]:
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def _split_rule_codes(spec: str):
    """-> (per-file rule list, contract code set).  Raises ValueError
    on unknown codes (matching rules_by_code's contract)."""
    wanted = {c.strip().upper() for c in spec.split(",") if c.strip()}
    contract = {c for c in wanted if c in CONTRACT_CODES}
    per_file_codes = wanted - contract
    per_file = rules_by_code(per_file_codes) if per_file_codes else []
    return per_file, contract


def _changed_files(ref: str) -> Set[str]:
    """Absolute paths of files changed vs. ``ref`` (diff + untracked).
    Raises CalledProcessError when git/ref is unusable."""
    root = repo_root()
    out: Set[str] = set()
    for cmd in (["git", "-C", root, "diff", "--name-only", ref, "--"],
                ["git", "-C", root, "ls-files", "--others",
                 "--exclude-standard"]):
        res = subprocess.run(cmd, capture_output=True, text=True,
                             check=True)
        for line in res.stdout.splitlines():
            line = line.strip()
            if line:
                out.add(os.path.abspath(os.path.join(root, line)))
    return out


def _rule_catalog():
    """code -> (short name, one-line description), per-file + contract."""
    cat = {}
    for r in all_rules():
        doc = (r.__class__.__doc__ or "").strip().splitlines()[0]
        cat[r.code] = (r.name, doc)
    for code, (name, doc) in CONTRACT_RULE_DOCS.items():
        cat[code] = (name, doc)
    return cat


def _sarif_report(result) -> dict:
    """SARIF 2.1.0 view of one lint result: one run per rule code that
    produced findings (so per-family triage tools group naturally), or
    a single empty-results run carrying the full rule catalog when the
    tree is clean (consumers distinguish "ran clean" from "didn't
    run").  Artifact URIs are repo-root-relative; columns are 1-based
    per the SARIF region contract."""
    root = repo_root()
    cat = _rule_catalog()

    def rel(p: str) -> str:
        try:
            r = os.path.relpath(os.path.abspath(p), root)
        except ValueError:
            r = p
        return r.replace(os.sep, "/")

    def rule_obj(code: str) -> dict:
        name, doc = cat.get(code, (code.lower(), ""))
        return {"id": code, "name": name,
                "shortDescription": {"text": doc}}

    def run_obj(rules: List[dict], results: List[dict]) -> dict:
        return {"tool": {"driver": {"name": "xgtpu-lint",
                                    "informationUri":
                                        "https://github.com/xgboost-tpu",
                                    "rules": rules}},
                "results": results}

    by_rule: dict = {}
    for f in result.findings:
        by_rule.setdefault(f.rule, []).append(f)
    runs = []
    for code in sorted(by_rule):
        results = []
        for f in by_rule[code]:
            region: dict = {"startLine": max(f.line, 1)}
            if f.col:
                region["startColumn"] = f.col + 1
            if f.snippet:
                region["snippet"] = {"text": f.snippet}
            results.append({
                "ruleId": code,
                "level": "warning",
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": rel(f.path)},
                    "region": region}}]})
        runs.append(run_obj([rule_obj(code)], results))
    if not runs:
        runs = [run_obj([rule_obj(c) for c in sorted(cat)], [])]
    return {"$schema": "https://json.schemastore.org/sarif-2.1.0.json",
            "version": "2.1.0", "runs": runs}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m xgboost_tpu.analysis",
        description="xgtpu-lint: JAX-aware static analysis for the "
                    "xgboost_tpu tree (rule catalog: ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint "
                         "(default: the xgboost_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--sarif", action="store_true",
                    help="SARIF 2.1.0 report on stdout (one run per "
                         "rule code; exit contract unchanged)")
    ap.add_argument("--rules", default=None, metavar="XGT00x[,..]",
                    help="run only the named rules")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="baseline file (default: ANALYSIS_BASELINE.json "
                         "at the repo root, when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline (report full debt)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept every current finding into the "
                         "baseline file and exit 0")
    ap.add_argument("--no-contracts", action="store_true",
                    help="skip the cross-file contract rules "
                         "(XGT008-XGT012, XGT016, XGT017)")
    ap.add_argument("--write-contracts", action="store_true",
                    help="regenerate ANALYSIS_CONTRACTS.json from the "
                         "extracted route/metric/knob/lock inventories "
                         "and exit")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="report only findings anchored in files "
                         "changed vs. REF (default HEAD); cross-file "
                         "facts still collect repo-wide")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print baselined findings")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 2

    if args.as_json and args.sarif:
        print("xgtpu-lint: --json and --sarif are two renderings of "
              "one report — pick one", file=sys.stderr)
        return 2

    contract_codes = set(CONTRACT_CODES)
    try:
        if args.rules:
            rules, contract_codes = _split_rule_codes(args.rules)
        else:
            rules = all_rules()
    except ValueError as e:
        print(f"xgtpu-lint: {e}", file=sys.stderr)
        return 2
    if args.no_contracts:
        contract_codes = set()

    if args.list_rules:
        for r in rules:
            doc = (r.__class__.__doc__ or "").strip().splitlines()[0]
            print(f"{r.code}  {r.name:<28s} {doc}")
        for code in sorted(contract_codes):
            name, doc = CONTRACT_RULE_DOCS[code]
            print(f"{code}  {name:<28s} {doc} [cross-file]")
        return 0

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"xgtpu-lint: no such path: {p}", file=sys.stderr)
            return 2

    engine = (default_engine(paths, codes=contract_codes)
              if contract_codes else None)

    if args.write_contracts:
        if engine is None:
            print("xgtpu-lint: --write-contracts needs the contract "
                  "rules enabled", file=sys.stderr)
            return 2
        out = engine.write_inventory()
        inv = engine.inventory()
        print(f"xgtpu-lint: wrote {out} "
              f"({len(inv['http_routes'])} routes, "
              f"{len(inv['metric_families'])} metric families, "
              f"{len(inv['env_knobs'])} env knobs, "
              f"{len(inv['lock_edges'])} lock edges, "
              f"{len(inv['exit_codes'])} exit codes, "
              f"{len(inv['events'])} events)", file=sys.stderr)
        return 0

    anchor_filter = None
    if args.changed is not None:
        if args.write_baseline:
            print("xgtpu-lint: --write-baseline cannot be combined "
                  "with --changed (a narrowed-reporting scan must not "
                  "rewrite the accepted-debt ledger)", file=sys.stderr)
            return 2
        try:
            changed = _changed_files(args.changed)
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            print(f"xgtpu-lint: --changed failed: {detail.strip()}",
                  file=sys.stderr)
            return 2
        # per-file rules only parse the changed .py files under the
        # scanned scope; contract facts still collect repo-wide and
        # the anchor filter narrows what gets REPORTED.  Contract
        # findings anchored in the doc/inventory surfaces always pass
        # the filter: drift CAUSED by a changed .py file anchors there
        # (a stale OBSERVABILITY.md row, a stale ANALYSIS_CONTRACTS
        # section), and dropping those would make the pre-commit loop
        # pass on exactly the cross-file drift the change introduced
        scope = [os.path.abspath(p) for p in paths]
        paths = sorted(
            f for f in changed
            if f.endswith(".py") and os.path.exists(f)
            and any(f == s or f.startswith(s.rstrip(os.sep) + os.sep)
                    for s in scope))
        doc_anchors = (set(engine.doc_surfaces())
                       if engine is not None else set())
        anchor_filter = (
            lambda f: os.path.abspath(f.path) in changed
            or (f.rule in CONTRACT_CODES
                and os.path.abspath(f.path) in doc_anchors))

    baseline_path = args.baseline or core.default_baseline_path()
    baseline = None
    if not args.no_baseline and not args.write_baseline:
        if os.path.exists(baseline_path):
            try:
                baseline = core.Baseline.load(baseline_path)
            except (OSError, ValueError, json.JSONDecodeError) as e:
                print(f"xgtpu-lint: bad baseline {baseline_path}: {e}",
                      file=sys.stderr)
                return 2
        elif args.baseline:
            print(f"xgtpu-lint: baseline not found: {baseline_path}",
                  file=sys.stderr)
            return 2

    result = core.run(paths, baseline=baseline, rules=rules,
                      contracts=engine, anchor_filter=anchor_filter)

    if args.write_baseline:
        if args.rules:
            print("xgtpu-lint: --write-baseline cannot be combined with "
                  "--rules (a partial-rule scan would drop every other "
                  "rule's accepted debt from the baseline)",
                  file=sys.stderr)
            return 2
        # merge, don't clobber: entries outside the scanned paths are
        # kept, so a subdirectory scan cannot erase the rest of the
        # accepted-debt ledger
        try:
            old = (core.Baseline.load(baseline_path)
                   if os.path.exists(baseline_path) else core.Baseline())
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"xgtpu-lint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        # rescope PER RULE CLASS (baseline keys lead with the rule
        # code, so the two ledgers partition cleanly): per-file
        # findings were only re-collected from the scanned paths —
        # entries elsewhere must survive a subdirectory scan — while
        # contract findings were re-collected from the engine's
        # repo-wide fact scope + doc/inventory surfaces, and THAT is
        # their coverage; one rule-blind union either erases per-file
        # debt outside the scanned subset or keeps-and-re-adds contract
        # findings anchored outside it, inflating counts every run
        contract = set(CONTRACT_CODES)

        def split_counts(b):
            return (core.Baseline({k: v for k, v in b.counts.items()
                                   if k.split("|", 1)[0] not in contract}),
                    core.Baseline({k: v for k, v in b.counts.items()
                                   if k.split("|", 1)[0] in contract}))

        old_pf, old_ct = split_counts(old)
        pf = [f for f in result.findings if f.rule not in contract]
        ct = [f for f in result.findings if f.rule in contract]
        merged = old_pf.rescoped(pf, paths)
        ct_cov = (list(engine.fact_paths) + engine.doc_surfaces()
                  if engine is not None else [])
        merged.counts.update(old_ct.rescoped(ct, ct_cov).counts)
        merged.dump(baseline_path)
        print(f"xgtpu-lint: accepted {len(result.findings)} finding(s) "
              f"for the scanned paths ({sum(merged.counts.values())} "
              f"total baselined) -> {baseline_path}", file=sys.stderr)
        return 0

    if args.as_json:
        print(json.dumps(result.to_json(), indent=2))
    elif args.sarif:
        print(json.dumps(_sarif_report(result), indent=2))
    else:
        core.render_report(result, verbose=args.verbose)
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())

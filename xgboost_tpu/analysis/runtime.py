"""Dynamic checkers — the runtime half of xgtpu-lint (ANALYSIS.md).

Static rules catch patterns; these catch the behaviors the patterns
cause, in real executions under pytest:

- :class:`RecompileGuard` counts XLA ``backend_compile`` events via
  ``jax.monitoring``, generalizing the serving subsystem's
  zero-steady-state-recompile test so ANY test can assert a compile
  budget over a code region (``with guard.expect(0): ...``).
- :class:`LockRaceChecker` wraps an object's locks in instrumented
  shims that record per-thread held-lock sets, then watches writes to
  lock-guarded attributes: a write with the guarding lock not held is
  recorded as a violation (the dynamic twin of the static XGT005
  rule), and acquiring two instrumented locks in opposite orders on
  different call paths is recorded as a lock-order inversion (a latent
  deadlock no single run deadlocks on).  The static complement is
  XGT011 (analysis/contracts.py): the whole-repo nested-acquisition
  graph sees every LEXICAL order, not just the ones a test executed;
  tests/test_analysis_contracts.py cross-checks that runtime
  observations are a subset of that graph.
- :class:`DonationGuard` is the runtime twin of the static XGT013
  use-after-donate rule: it wraps a ``donate_argnums`` jitted callable
  and, after each call, DELETES the device buffers the caller handed
  over at donated positions — which is exactly what donation does on
  TPU but what CPU silently skips (JAX warns and copies).  A caller
  that touches a donated buffer post-call then raises loudly under
  test on any backend, instead of reading garbage only on device.

All record violations instead of raising at the fault site, so a
stress test collects everything and fails once with the full report
(``checker.assert_clean()``).
"""

from __future__ import annotations

import dataclasses
import threading
import traceback
from contextlib import contextmanager
from typing import Dict, List, Sequence, Set, Tuple

# ---------------------------------------------------------------- compiles
# jax.monitoring offers no listener unregistration, so one process-wide
# counter is installed once and consumers read deltas of it.  A plain
# int (not an event list): a long-lived process compiles indefinitely,
# and every consumer only ever needs the count.
_compile_count = 0
_LISTENER_LOCK = threading.Lock()
_listener_installed = False


def _ensure_listener() -> None:
    global _listener_installed
    with _LISTENER_LOCK:
        if _listener_installed:
            return
        import jax

        def _on_event(*args, **kwargs):
            global _compile_count
            if args and "backend_compile" in str(args[0]):
                with _LISTENER_LOCK:
                    _compile_count += 1

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _listener_installed = True


class RecompileGuard:
    """Assert steady-state compile counts from XLA's own telemetry.

    ``backend_compile`` monitoring events are the ground truth the
    serving zero-recompile acceptance test pins (a Python-side cache
    counter can lie; the XLA event cannot).  Usage::

        def test_hot_path_is_compile_free(recompile_guard):
            f(x)                              # warmup compiles here
            with recompile_guard.expect(0):   # steady state
                for _ in range(100):
                    f(x)
    """

    def __init__(self):
        _ensure_listener()

    def count(self) -> int:
        """Total backend compiles observed process-wide so far."""
        return _compile_count

    def new_since(self, baseline: int) -> int:
        return _compile_count - baseline

    @contextmanager
    def expect(self, max_compiles: int = 0):
        """Fail if the region compiles more than ``max_compiles``
        XLA programs."""
        before = self.count()
        yield self
        new = self.count() - before
        if new > max_compiles:
            raise AssertionError(
                f"recompile_guard: region compiled {new} XLA program(s), "
                f"budget was {max_compiles} — a steady-state path is "
                "re-tracing (shape-varying args? Python scalars burned "
                "into the trace? see ANALYSIS.md XGT001)")


# ------------------------------------------------------------------- locks
@dataclasses.dataclass
class Violation:
    """One observed locking violation."""

    kind: str          # "unguarded-write" | "lock-order-inversion"
    detail: str
    thread: str
    stack: str

    def render(self) -> str:
        return (f"[{self.kind}] {self.detail} (thread {self.thread})\n"
                f"{self.stack}")


class InstrumentedLock:
    """Drop-in wrapper over a ``threading.Lock``/``RLock`` that reports
    acquire/release to its :class:`LockRaceChecker`."""

    def __init__(self, checker: "LockRaceChecker", name: str, inner=None):
        self._checker = checker
        self.name = name
        self._inner = inner if inner is not None else threading.Lock()

    def acquire(self, *args, **kwargs) -> bool:
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._checker._note_acquire(self.name)
        return got

    def release(self) -> None:
        self._checker._note_release(self.name)
        self._inner.release()

    def held_by_current_thread(self) -> bool:
        return self.name in self._checker._held()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class LockRaceChecker:
    """Instrumented-lock race/deadlock observer.

    :meth:`instrument` rewires one object: each named lock attribute is
    wrapped in an :class:`InstrumentedLock` (same underlying primitive,
    so real mutual exclusion is unchanged) and the object's class is
    subclassed with a ``__setattr__`` that records a violation whenever
    a guarded attribute is WRITTEN without any of the object's
    instrumented locks held.  Reads are not traced — the invariant this
    codebase documents (OBSERVABILITY.md, serving/) is writer-side
    locking with benign racy reads.

    Lock-order inversions are tracked globally across every lock the
    checker wrapped: first ``A then B`` on one path and ``B then A`` on
    another is recorded even though no single run deadlocks.
    """

    def __init__(self):
        self.violations: List[Violation] = []
        self._tls = threading.local()
        self._mu = threading.Lock()
        self._edges: Set[Tuple[str, str]] = set()
        self._inverted: Set[Tuple[str, str]] = set()
        self._n_instrumented = 0

    # ------------------------------------------------------------ held set
    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _note_acquire(self, name: str) -> None:
        held = self._held()
        with self._mu:
            for h in held:
                if h == name:
                    continue
                self._edges.add((h, name))
                pair = tuple(sorted((h, name)))
                if (name, h) in self._edges and pair not in self._inverted:
                    self._inverted.add(pair)
                    self._record(
                        "lock-order-inversion",
                        f"{h} -> {name} here, but {name} -> {h} was "
                        "also observed — latent deadlock")
        held.append(name)

    def _note_release(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):  # innermost acquisition
            if held[i] == name:
                del held[i]
                break

    def _record(self, kind: str, detail: str) -> None:
        stack = "".join(traceback.format_stack(limit=8)[:-2])
        self.violations.append(Violation(
            kind=kind, detail=detail,
            thread=threading.current_thread().name, stack=stack))

    # ---------------------------------------------------------- instrument
    def wrap_lock(self, name: str, inner=None) -> InstrumentedLock:
        """A standalone instrumented lock (for code that takes a lock
        as a dependency)."""
        return InstrumentedLock(self, name, inner)

    def instrument(self, obj, locks: Sequence[str],
                   guarded: Sequence[str]):
        """Instrument ``obj`` in place and return it.

        Args:
          locks: attribute names of the object's lock(s) to wrap
            (e.g. ``("_lock",)``).
          guarded: attribute names whose WRITES must happen with one of
            those locks held.
        """
        checker = self
        wrapped: Dict[str, InstrumentedLock] = {}
        with self._mu:
            self._n_instrumented += 1
            seq = self._n_instrumented
        for lock_attr in locks:
            inner = getattr(obj, lock_attr)
            # per-INSTANCE lock names: two instances of one class must
            # not satisfy each other's guard check (holding b1._lock
            # while writing b2.attr is exactly the race to catch)
            ilock = InstrumentedLock(
                self, f"{type(obj).__name__}#{seq}.{lock_attr}", inner)
            object.__setattr__(obj, lock_attr, ilock)
            wrapped[lock_attr] = ilock
        guarded_set = frozenset(guarded)
        base = type(obj)

        class _Watched(base):
            def __setattr__(self, key, value):
                if key in guarded_set and not any(
                        il.held_by_current_thread()
                        for il in wrapped.values()):
                    checker._record(
                        "unguarded-write",
                        f"{base.__name__}.{key} written without "
                        f"{'/'.join(il.name for il in wrapped.values())} "
                        "held")
                super().__setattr__(key, value)

        _Watched.__name__ = base.__name__ + "+lockcheck"
        _Watched.__qualname__ = _Watched.__name__
        obj.__class__ = _Watched
        return obj

    # -------------------------------------------------------------- report
    def assert_clean(self) -> None:
        if self.violations:
            report = "\n".join(v.render() for v in self.violations)
            raise AssertionError(
                f"LockRaceChecker: {len(self.violations)} violation(s)\n"
                + report)


# ---------------------------------------------------------------- donation
class DonationGuard:
    """Runtime use-after-donate detector (dynamic twin of XGT013).

    ``donate_argnums`` donation is a no-op on CPU — JAX warns once and
    copies — so the whole tier-1 suite can pass while every donated
    dispatch reads freed memory on TPU.  This guard makes CPU behave
    like the device: :meth:`wrap` returns a shim that, after each call
    completes, ``delete()``-s every jax-array leaf the caller passed at
    a donated position.  From then on any caller-side touch of that
    buffer raises JAX's own "Array has been deleted" — the runtime
    observation of exactly the reads XGT013 flags statically.

    Two hazards are RECORDED rather than raised, so a multi-dispatch
    test collects everything and fails once via :meth:`assert_clean`:

    - ``donated-reuse``: an argument arriving at a donated position is
      already deleted — the caller re-passed a donated buffer instead
      of rebinding the carry (the loop form of use-after-donate);
    - ``non-donatable``: a donated position held a non-empty value
      with no deletable device array in it (donation silently
      pointless — e.g. a Python scalar burned into the trace).  An
      EMPTY pytree at a donated position is vacuously fine — gbtree
      donates ``tuple(eval_margins)`` unconditionally, and training
      without evals passes ``()`` there.

    Usage (the integration test drives the REAL fused dispatch)::

        guard = DonationGuard(donate_argnums=(1, 11))
        monkeypatch.setattr(gbtree, "_scan_rounds_donated",
                            guard.wrap(gbtree._scan_rounds_donated))
        ... run update_many with XGBTPU_FUSED_DONATE=1 ...
        assert guard.calls > 0
        guard.assert_clean()
    """

    def __init__(self, donate_argnums: Sequence[int]):
        self.donate_argnums = tuple(donate_argnums)
        self.calls = 0
        self.violations: List[Violation] = []

    def _record(self, kind: str, detail: str) -> None:
        stack = "".join(traceback.format_stack(limit=8)[:-2])
        self.violations.append(Violation(
            kind=kind, detail=detail,
            thread=threading.current_thread().name, stack=stack))

    @staticmethod
    def _array_leaves(value):
        import jax
        return [leaf for leaf in jax.tree_util.tree_leaves(value)
                if isinstance(leaf, jax.Array)]

    def wrap(self, fn):
        """``fn`` with device-faithful donation semantics appended."""
        import functools

        import jax

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            donated = []
            for i in self.donate_argnums:
                if i >= len(args):
                    continue
                leaves = self._array_leaves(args[i])
                if not leaves:
                    if jax.tree_util.tree_leaves(args[i]):
                        self._record(
                            "non-donatable",
                            f"donated position {i} of {fn.__name__} "
                            "holds no device array — donation is "
                            "silently a no-op there")
                    continue
                for leaf in leaves:
                    if leaf.is_deleted():
                        self._record(
                            "donated-reuse",
                            f"argument at donated position {i} of "
                            f"{fn.__name__} was ALREADY donated by an "
                            "earlier call — rebind the carry instead "
                            "of re-passing the dead buffer")
                    else:
                        donated.append(leaf)
            out = fn(*args, **kwargs)
            # the computation must have consumed its inputs before the
            # host frees them out from under an async dispatch
            jax.block_until_ready(out)
            for leaf in donated:
                leaf.delete()
            self.calls += 1
            return out

        return wrapper

    def assert_clean(self) -> None:
        if self.violations:
            report = "\n".join(v.render() for v in self.violations)
            raise AssertionError(
                f"DonationGuard: {len(self.violations)} violation(s)\n"
                + report)

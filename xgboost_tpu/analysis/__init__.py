"""xgboost_tpu.analysis — xgtpu-lint, the project-specific correctness
tooling (ANALYSIS.md).

Static half: an AST lint engine with rules tuned to this codebase's
hazards — recompile traps (XGT001), host<->device sync in hot loops
(XGT002), non-atomic persistence (XGT003), swallowed exceptions
(XGT004), lock discipline (XGT005), wall-clock durations (XGT006), and
collectives under rank-dependent control flow (XGT007).  Run it with
``python -m xgboost_tpu.analysis`` or ``tools/xgtpu_lint.py``; tier-1
enforces a clean tree via ``tests/test_analysis.py``.

Cross-file half (:mod:`~xgboost_tpu.analysis.contracts`): a two-phase
engine — per-file fact collectors feeding whole-repo checkers — for the
contracts that drift *between* files: HTTP route/client parity
(XGT008), metric-family drift against OBSERVABILITY.md (XGT009), env
knob + CLI param-table drift (XGT010), and the static lock-order graph
(XGT011).  The extracted inventories are committed as
``ANALYSIS_CONTRACTS.json`` so contract changes land as reviewed diffs.

Dynamic half (:mod:`~xgboost_tpu.analysis.runtime`): the
``RecompileGuard`` (XLA backend-compile counting, the generalized
serving zero-steady-state-compile assertion) and the
``LockRaceChecker`` (instrumented locks that flag guarded-attribute
writes without the lock and lock-order inversions), both exposed as
pytest fixtures in ``tests/conftest.py``.
"""

from xgboost_tpu.analysis.contracts import (CONTRACT_CODES,  # noqa: F401
                                            ContractEngine,
                                            default_engine)
from xgboost_tpu.analysis.core import (Baseline, Finding,  # noqa: F401
                                       Result, analyze_source,
                                       default_baseline_path, run)
from xgboost_tpu.analysis.rules import all_rules, rules_by_code  # noqa: F401

__all__ = ["Baseline", "Finding", "Result", "analyze_source", "run",
           "default_baseline_path", "all_rules", "rules_by_code",
           "CONTRACT_CODES", "ContractEngine", "default_engine"]

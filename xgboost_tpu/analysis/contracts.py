"""xgtpu-lint v2: whole-repo contract analysis (ANALYSIS.md §v2).

The PR-4 rules are single-file AST passes; the surfaces PRs 5-7 grew
drift *between* files: three stdlib HTTP servers spoken to by a
half-dozen hand-rolled clients, dozens of ``xgbtpu_*`` metric families
documented by hand in OBSERVABILITY.md, ``XGBTPU_*`` env knobs mirrored
into README tables, and 20+ lock acquisition sites guarded only by the
*runtime* LockRaceChecker.  This module turns those conventions into
enforced cross-file invariants with a two-phase engine:

1. **fact collection** — one AST pass per file extracts route tables
   (``do_GET``/``do_POST`` path dispatch), HTTP client calls, metric
   family constructions (names resolved through f-strings, prefix
   defaults and constant loops), ``XGBTPU_*`` env reads, the
   ``SERVE_PARAMS``/``FLEET_PARAMS`` tables, and nested
   ``with self.<lock>`` acquisition pairs;
2. **whole-repo checking** — the collected facts are judged against
   each other and against the committed docs:

   - **XGT008** HTTP contract parity: every client call targets a route
     some handler defines, with the right method;
   - **XGT009** metric-family drift: every constructed ``xgbtpu_*``
     family appears in OBSERVABILITY.md's inventory table and vice
     versa, with consistent label sets;
   - **XGT010** knob drift: every ``XGBTPU_*`` env read is documented
     in README.md, every documented knob is read somewhere, and every
     ``SERVE_PARAMS``/``FLEET_PARAMS`` key is consumed outside its
     table (the "one table, two surfaces" discipline, mechanized);
   - **XGT011** static lock-order graph: nested lock acquisitions
     keyed by ``(class, lock attr)`` form a global digraph that must be
     acyclic — the static complement of the runtime LockRaceChecker,
     which only sees orders a test happens to execute;
   - **XGT012** HTTP timeout discipline: every outbound HTTP call
     (``urlopen``, ``http.client.HTTPConnection``) must pass an
     explicit ``timeout`` — a timeout-less client blocked on a wedged
     peer is a latent hang, exactly the stall failure the deadline /
     watchdog / ejection machinery exists to bound (RELIABILITY.md
     stall matrix);
   - **XGT016** exit-code registry (v3): process exit codes are
     defined ONCE, in ``reliability/rc.py`` (``*_RC`` constants), and
     referenced symbolically everywhere — a ``sys.exit``/``os._exit``
     with a bare int literal (other than the POSIX-generic 0/1/2), a
     comparison of a returncode against a literal matching a
     registered code, or a ``*_RC`` constant defined outside the
     registry are findings.  The launcher keys recovery decisions off
     these codes (``HOST_LOSS_RC`` -> re-plan, ``FENCE_RC`` ->
     readmit), so a drifted literal silently reroutes recovery;
   - **XGT017** obs event-name drift (v3): every event name emitted
     via ``trace.event(...)``/``self._event(...)`` (and literal
     ``{"kind": "event"}`` dicts handed to ``events.emit``) must
     appear in OBSERVABILITY.md's "Event inventory" table and vice
     versa — the chaos selftests and obs_report grep these names, so
     an undocumented rename breaks tooling without failing a test.

The extracted inventories are committed as ``ANALYSIS_CONTRACTS.json``
(:meth:`ContractEngine.inventory`) so reviewers see contract diffs in
PRs; a stale committed inventory is itself a finding (regenerate with
``--write-contracts``).

Findings ride the PR-4 machinery unchanged: inline
``# xgtpu: disable=XGT00x`` suppressions work at the anchored line of
``.py``-anchored findings, baseline keys are content-addressed, and the
CLI/exit contract is shared (``python -m xgboost_tpu.analysis``).
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from xgboost_tpu.analysis.core import (FileContext, Finding, Suppressions,
                                       const_str, default_baseline_path,
                                       iter_py_files, terminal_name)

#: the cross-file rule codes this engine owns
CONTRACT_CODES = ("XGT008", "XGT009", "XGT010", "XGT011", "XGT012",
                  "XGT016", "XGT017")

#: one-line catalog entries (``--list-rules``)
CONTRACT_RULE_DOCS = {
    "XGT008": ("http-contract-parity",
               "HTTP client calls must match a handler route table "
               "entry (endpoint + method)"),
    "XGT009": ("metric-family-drift",
               "xgbtpu_* families in code <-> OBSERVABILITY.md "
               "inventory, labels consistent"),
    "XGT010": ("knob-drift",
               "XGBTPU_* env reads <-> README knob docs; "
               "SERVE_PARAMS/FLEET_PARAMS keys consumed"),
    "XGT011": ("lock-order-cycle",
               "global nested-lock acquisition graph must be acyclic"),
    "XGT012": ("http-timeout-discipline",
               "every outbound HTTP call (urlopen / HTTPConnection) "
               "must pass an explicit timeout"),
    "XGT016": ("exit-code-registry",
               "*_RC exit codes defined once in reliability/rc.py, "
               "referenced symbolically (no magic exit literals)"),
    "XGT017": ("event-name-drift",
               "trace.event names in code <-> OBSERVABILITY.md event "
               "inventory table"),
}

_HTTP_METHODS = frozenset({"GET", "POST", "PUT", "DELETE", "HEAD",
                           "PATCH"})
_FAMILY_RE = re.compile(r"^xgbtpu_[a-z0-9_]+$")
_KNOB_RE = re.compile(r"XGBTPU_[A-Z0-9_]+")
#: the event-name grammar: dotted lowercase (``gang.fence``) — the
#: forcing function toward namespaced names, same as the metric grammar
_EVENT_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
#: exit-code constant naming convention (``FENCE_RC``)
_RC_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*_RC$")
#: where the one true exit-code registry lives (XGT016)
_RC_REGISTRY_SUFFIX = "reliability/rc.py"
#: POSIX-generic exit codes every CLI uses freely: success, generic
#: failure, usage error — below the registered-protocol range
_GENERIC_RCS = frozenset({0, 1, 2})
_METRIC_CTORS = frozenset({"Counter", "Gauge", "Histogram",
                           "LabeledCounter", "LabeledGauge",
                           "counter", "gauge", "histogram"})
_LABELED_CTORS = frozenset({"LabeledCounter", "LabeledGauge"})

#: doc files, looked up at the engine root
OBSERVABILITY_DOC = "OBSERVABILITY.md"
README_DOC = "README.md"
CONTRACTS_FILE = "ANALYSIS_CONTRACTS.json"


def _lockish(attr: str) -> bool:
    """The lock-attribute heuristic shared with XGT005, widened to the
    condition-variable and mutex spellings this tree uses."""
    a = attr.lower()
    return "lock" in a or a.endswith("_cv") or a == "_mu"


# ------------------------------------------------------------------ facts
class Facts:
    """Everything phase 1 extracted, across every scanned file."""

    def __init__(self):
        # (file, handler_class, method, path, line)
        self.routes: List[Tuple[str, str, str, str, int]] = []
        # (file, method, path, line)
        self.clients: List[Tuple[str, str, str, int]] = []
        # (file, family, label_or_None, line)
        self.families: List[Tuple[str, str, Optional[str], int]] = []
        # (file, knob, line)
        self.knobs: List[Tuple[str, str, int]] = []
        # (file, table 'serve'|'fleet', key, line)
        self.params: List[Tuple[str, str, str, int]] = []
        # (file, outer 'Class.attr', inner 'Class.attr', line)
        self.lock_edges: List[Tuple[str, str, str, int]] = []
        # (file, call 'urlopen'|'HTTPConnection'|..., line, has_timeout)
        self.http_calls: List[Tuple[str, str, int, bool]] = []
        # (file, NAME_RC, value, line) from the registry file itself
        self.rc_defs: List[Tuple[str, str, int, int]] = []
        # (file, NAME_RC, value, line) defined OUTSIDE the registry
        self.rc_assigns: List[Tuple[str, str, int, int]] = []
        # (file, 'exit'|'_exit', literal value, line)
        self.exit_calls: List[Tuple[str, str, int, int]] = []
        # (file, compared-name, literal value, line): returncode-ish
        # names compared against bare int literals
        self.rc_compares: List[Tuple[str, str, int, int]] = []
        # (file, event name, line): trace.event()/_event()/emit() sites
        self.events: List[Tuple[str, str, int]] = []
        # file -> every string constant in it (param-consumption check)
        self.str_consts: Dict[str, Set[str]] = {}
        # file -> Suppressions (inline disables apply to contract
        # findings anchored there, same as the per-file rules)
        self.suppressions: Dict[str, Suppressions] = {}
        # file -> source lines (snippet lookups re-use the phase-1
        # read instead of reopening the file)
        self.lines: Dict[str, List[str]] = {}
        self.files: List[str] = []


# --------------------------------------------------------------- resolver
class _FileResolver:
    """Best-effort constant resolution for strings: literals,
    f-strings over parameter defaults / simple local assignments /
    module constants, and loop variables ranging over constant string
    tuples (``for op in OPS:``).  Returns the LIST of possible values,
    or None when the expression is not statically resolvable —
    precision over recall, like every rule here."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.module_consts: Dict[str, str] = {}
        self.module_seqs: Dict[str, Tuple[str, ...]] = {}
        for node in ctx.tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            s = const_str(node.value)
            if s is not None:
                self.module_consts[name] = s
            elif isinstance(node.value, (ast.Tuple, ast.List)):
                vals = [const_str(e) for e in node.value.elts]
                if vals and all(v is not None for v in vals):
                    self.module_seqs[name] = tuple(vals)

    def resolve(self, node: ast.AST,
                seen: frozenset = frozenset()) -> Optional[List[str]]:
        s = const_str(node)
        if s is not None:
            return [s]
        if isinstance(node, ast.JoinedStr):
            outs = [""]
            for part in node.values:
                if isinstance(part, ast.Constant):
                    vals = [str(part.value)]
                elif isinstance(part, ast.FormattedValue):
                    r = self.resolve(part.value, seen)
                    if r is None:
                        return None
                    vals = r
                else:
                    return None
                outs = [o + v for o in outs for v in vals]
            return outs
        if isinstance(node, ast.Name):
            return self._resolve_name(node, seen)
        return None

    def _resolve_name(self, node: ast.Name,
                      seen: frozenset) -> Optional[List[str]]:
        name = node.id
        if name in seen:
            return None
        seen = seen | {name}
        func = None
        for anc in self.ctx.ancestors(node):
            if isinstance(anc, ast.For) and func is None:
                tgt = anc.target
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return self._resolve_iter(anc.iter, seen)
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if func is None:
                    func = anc
        if func is not None:
            for sub in ast.walk(func):
                if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Name)
                        and sub.targets[0].id == name):
                    r = self.resolve(sub.value, seen)
                    if r is not None:
                        return r
            d = self._param_default(func, name)
            if d is not None:
                return [d]
        if name in self.module_consts:
            return [self.module_consts[name]]
        if name in self.module_seqs:
            return list(self.module_seqs[name])
        return None

    def _resolve_iter(self, it: ast.AST,
                      seen: frozenset) -> Optional[List[str]]:
        if isinstance(it, (ast.Tuple, ast.List)):
            vals = [const_str(e) for e in it.elts]
            if vals and all(v is not None for v in vals):
                return vals
            return None
        if isinstance(it, ast.Name):
            return list(self.module_seqs.get(it.id, ())) or None
        return None

    @staticmethod
    def _param_default(fn, name: str) -> Optional[str]:
        pos = fn.args.posonlyargs + fn.args.args
        defaults = fn.args.defaults
        for i, a in enumerate(pos):
            if a.arg != name:
                continue
            j = i - (len(pos) - len(defaults))
            if 0 <= j < len(defaults):
                return const_str(defaults[j])
            return None
        for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if a.arg == name and d is not None:
                return const_str(d)
        return None


# ------------------------------------------------------------- collectors
def _with_lock_attrs(node: ast.With) -> List[str]:
    """Lock attrs entered by one ``with``, in item order (the
    XGT005 helper widened by :func:`_lockish`)."""
    out = []
    for item in node.items:
        e = item.context_expr
        if (isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Name)
                and e.value.id == "self" and _lockish(e.attr)):
            out.append(e.attr)
    return out


def _norm_path(p: str) -> str:
    return p.split("?", 1)[0]


def collect_file(ctx: FileContext, facts: Facts) -> None:
    """Phase 1 for one parsed file: extract every fact the phase-2
    checkers consume."""
    res = _FileResolver(ctx)
    facts.files.append(ctx.path)
    facts.suppressions[ctx.path] = Suppressions(ctx.source)
    facts.lines[ctx.path] = ctx.lines
    consts = facts.str_consts.setdefault(ctx.path, set())
    seen_clients: Set[Tuple[str, str, int]] = set()

    def add_client(method: str, path: str, line: int) -> None:
        path = _norm_path(path)
        if not path.startswith("/"):
            return
        key = (method, path, line)
        if key not in seen_clients:
            seen_clients.add(key)
            facts.clients.append((ctx.path, method, path, line))

    _collect_rc_defs(ctx, facts)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            consts.add(node.value)
        if isinstance(node, ast.ClassDef):
            _collect_routes(ctx, node, facts)
            _collect_lock_edges(ctx, node, facts)
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            _collect_param_table(ctx, node, facts)
        if isinstance(node, ast.Subscript):
            _collect_env_subscript(ctx, node, res, facts)
        if isinstance(node, ast.Compare):
            _collect_rc_compare(ctx, node, facts)
        if not isinstance(node, ast.Call):
            continue
        _collect_metric_ctor(ctx, node, res, facts)
        _collect_env_call(ctx, node, res, facts)
        _collect_client_call(node, add_client)
        _collect_http_timeout(ctx, node, facts)
        _collect_exit_call(ctx, node, facts)
        _collect_event(ctx, node, res, facts)


def _collect_routes(ctx: FileContext, cls: ast.ClassDef,
                    facts: Facts) -> None:
    """Route tables from ``do_GET``/``do_POST`` path dispatch: every
    comparison of something against a ``"/"``-leading string constant
    inside those methods is a route this handler serves."""
    for fn in cls.body:
        if not isinstance(fn, ast.FunctionDef):
            continue
        if fn.name not in ("do_GET", "do_POST"):
            continue
        method = fn.name[3:]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare):
                continue
            if not all(isinstance(op, (ast.Eq, ast.In))
                       for op in node.ops):
                continue
            for comp in node.comparators:
                elts = (comp.elts if isinstance(comp, (ast.Tuple, ast.List))
                        else [comp])
                for e in elts:
                    s = const_str(e)
                    if s and s.startswith("/"):
                        facts.routes.append(
                            (ctx.path, cls.name, method, s, node.lineno))


def _collect_lock_edges(ctx: FileContext, cls: ast.ClassDef,
                        facts: Facts) -> None:
    """Nested ``with self.<lock>`` acquisition pairs, keyed
    ``Class.attr``: multi-item ``with a, b:`` orders a before b, and a
    ``with`` lexically inside another (same function) orders outer
    before inner.  Cross-function nesting (a method called with a lock
    held) is the runtime checker's domain."""
    for node in ast.walk(cls):
        if not isinstance(node, ast.With):
            continue
        attrs = _with_lock_attrs(node)
        if not attrs:
            continue
        for i, a in enumerate(attrs):
            for b in attrs[i + 1:]:
                facts.lock_edges.append(
                    (ctx.path, f"{cls.name}.{a}", f"{cls.name}.{b}",
                     node.lineno))
        outer_attrs: List[str] = []
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
                break
            if isinstance(anc, ast.With):
                outer_attrs.extend(_with_lock_attrs(anc))
        for outer in outer_attrs:
            for inner in attrs:
                facts.lock_edges.append(
                    (ctx.path, f"{cls.name}.{outer}",
                     f"{cls.name}.{inner}", node.lineno))


def _collect_param_table(ctx: FileContext, node, facts: Facts) -> None:
    if isinstance(node, ast.Assign):
        if (len(node.targets) != 1
                or not isinstance(node.targets[0], ast.Name)):
            return
        name = node.targets[0].id
    elif isinstance(node, ast.AnnAssign):  # SERVE_PARAMS: Dict[...] = {..}
        if not isinstance(node.target, ast.Name):
            return
        name = node.target.id
    else:
        return
    table = {"SERVE_PARAMS": "serve", "FLEET_PARAMS": "fleet",
             "PIPELINE_PARAMS": "pipeline",
             "STREAM_PARAMS": "stream",
             "CATALOG_PARAMS": "catalog",
             "PLACER_PARAMS": "placer"}.get(name)
    if table is None or not isinstance(node.value, ast.Dict):
        return
    for k in node.value.keys:
        s = const_str(k) if k is not None else None
        if s:
            facts.params.append((ctx.path, table, s, k.lineno))


def _collect_metric_ctor(ctx: FileContext, node: ast.Call,
                         res: _FileResolver, facts: Facts) -> None:
    fname = terminal_name(node.func)
    if fname not in _METRIC_CTORS or not node.args:
        return
    names = res.resolve(node.args[0])
    if not names:
        return
    label: Optional[str] = None
    if fname in _LABELED_CTORS and len(node.args) >= 2:
        lab = res.resolve(node.args[1])
        if lab and len(lab) == 1:
            label = lab[0]
    for fam in names:
        if _FAMILY_RE.match(fam):
            facts.families.append((ctx.path, fam, label, node.lineno))


def _is_environ(node: ast.AST) -> bool:
    return ((isinstance(node, ast.Attribute) and node.attr == "environ")
            or (isinstance(node, ast.Name) and node.id == "environ"))


def _collect_env_call(ctx: FileContext, node: ast.Call,
                      res: _FileResolver, facts: Facts) -> None:
    fname = terminal_name(node.func)
    if fname in ("get", "setdefault"):
        if not (isinstance(node.func, ast.Attribute)
                and _is_environ(node.func.value)):
            return
    elif fname != "getenv":
        return
    if not node.args:
        return
    for knob in (res.resolve(node.args[0]) or ()):
        if _KNOB_RE.fullmatch(knob) and knob != "XGBTPU_":
            facts.knobs.append((ctx.path, knob, node.lineno))


def _collect_env_subscript(ctx: FileContext, node: ast.Subscript,
                           res: _FileResolver, facts: Facts) -> None:
    if not (_is_environ(node.value)
            and isinstance(node.ctx, ast.Load)):
        return
    for knob in (res.resolve(node.slice) or ()):
        if _KNOB_RE.fullmatch(knob) and knob != "XGBTPU_":
            facts.knobs.append((ctx.path, knob, node.lineno))


def _collect_client_call(node: ast.Call, add_client) -> None:
    """HTTP client call extraction — every hand-rolled client shape in
    this tree:

    - ``conn.request("POST", "/predict", ...)``
    - ``urlopen(url + "/healthz")`` (GET)
    - ``self._post("/fleet/register", payload)`` (POST by convention)
    - adjacent constants ``("GET", "/metrics")`` anywhere in a call's
      positionals (the rollout controller's ``_call``/``forward``
      plumbing)
    """
    fname = terminal_name(node.func)
    if fname == "request" and len(node.args) >= 2:
        m, p = const_str(node.args[0]), const_str(node.args[1])
        if m in _HTTP_METHODS and p and p.startswith("/"):
            add_client(m, p, node.lineno)
            return
    if fname == "urlopen" and node.args:
        arg0 = node.args[0]
        if (isinstance(arg0, ast.BinOp) and isinstance(arg0.op, ast.Add)):
            p = const_str(arg0.right)
            if p and p.startswith("/"):
                add_client("GET", p, node.lineno)
                return
    if fname == "_post" and node.args:
        p = const_str(node.args[0])
        if p and p.startswith("/"):
            add_client("POST", p, node.lineno)
            return
    args = node.args
    for i in range(len(args) - 1):
        m, p = const_str(args[i]), const_str(args[i + 1])
        if m in _HTTP_METHODS and p and p.startswith("/"):
            add_client(m, p, node.lineno)
            return


# --------------------------------------------------- XGT016/XGT017 facts
def _collect_rc_defs(ctx: FileContext, facts: Facts) -> None:
    """Module-level ``NAME_RC = <int>`` assignments: registry entries
    when the file IS ``reliability/rc.py``, out-of-registry definitions
    (an XGT016 finding) anywhere else."""
    is_registry = ctx.path.replace("\\", "/").endswith(_RC_REGISTRY_SUFFIX)
    for node in ctx.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if not _RC_NAME_RE.match(name):
            continue
        if not (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)
                and not isinstance(node.value.value, bool)):
            continue
        dest = facts.rc_defs if is_registry else facts.rc_assigns
        dest.append((ctx.path, name, node.value.value, node.lineno))


def _collect_exit_call(ctx: FileContext, node: ast.Call,
                       facts: Facts) -> None:
    """``sys.exit`` / ``os._exit`` with a bare int literal."""
    fname = terminal_name(node.func)
    if fname not in ("exit", "_exit") or len(node.args) != 1:
        return
    arg = node.args[0]
    if (isinstance(arg, ast.Constant) and isinstance(arg.value, int)
            and not isinstance(arg.value, bool)):
        facts.exit_calls.append((ctx.path, fname, arg.value, node.lineno))


def _collect_rc_compare(ctx: FileContext, node: ast.Compare,
                        facts: Facts) -> None:
    """``p.returncode == 143``-style comparisons: a returncode-ish name
    (contains ``rc`` or ``returncode``) against a bare int literal.
    The checker only flags literals matching a REGISTERED code —
    ``rc == 0`` and arbitrary small ints stay out of scope."""
    if not all(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
        return
    operands = [node.left] + list(node.comparators)
    for a, b in zip(operands, operands[1:]):
        for name_node, lit_node in ((a, b), (b, a)):
            t = terminal_name(name_node)
            if t is None or not ("rc" in t.lower()
                                 or "returncode" in t.lower()):
                continue
            if (isinstance(lit_node, ast.Constant)
                    and isinstance(lit_node.value, int)
                    and not isinstance(lit_node.value, bool)):
                facts.rc_compares.append(
                    (ctx.path, t, lit_node.value, node.lineno))


def _collect_event(ctx: FileContext, node: ast.Call,
                   res: _FileResolver, facts: Facts) -> None:
    """Event-name emission sites: ``trace.event(name, ...)`` and the
    trainers' ``self._event(name, ...)`` wrappers (resolved through
    the constant resolver), plus literal ``{"kind": "event"}`` dicts
    handed straight to ``events.emit`` — the profiler's span-record
    emits carry ``"kind": "span"`` and are excluded by that key."""
    fname = terminal_name(node.func)
    if fname in ("event", "_event") and node.args:
        for name in (res.resolve(node.args[0]) or ()):
            if _EVENT_RE.match(name):
                facts.events.append((ctx.path, name, node.lineno))
        return
    if fname != "emit" or not node.args:
        return
    d = node.args[0]
    if not isinstance(d, ast.Dict):
        return
    fields: Dict[str, ast.AST] = {}
    for k, v in zip(d.keys, d.values):
        ks = const_str(k) if k is not None else None
        if ks:
            fields[ks] = v
    if "kind" in fields and const_str(fields["kind"]) == "event":
        name = (const_str(fields["name"])
                if "name" in fields else None)
        if name and _EVENT_RE.match(name):
            facts.events.append((ctx.path, name, node.lineno))


#: outbound-HTTP constructors that take a ``timeout`` (XGT012).
#: ``urlopen`` hangs forever without one; the two connection classes
#: default to the GLOBAL socket timeout, which is None in practice.
_HTTP_TIMEOUT_CALLS = frozenset({"urlopen", "HTTPConnection",
                                 "HTTPSConnection"})


def _collect_http_timeout(ctx: FileContext, node: ast.Call,
                          facts: Facts) -> None:
    """XGT012 facts: every outbound-HTTP constructor call, with
    whether it passes an explicit timeout — the ``timeout=`` keyword,
    or the 3rd positional (``urlopen(url, data, timeout)`` /
    ``HTTPConnection(host, port, timeout)``)."""
    fname = terminal_name(node.func)
    if fname not in _HTTP_TIMEOUT_CALLS:
        return
    has_timeout = (any(kw.arg == "timeout" for kw in node.keywords)
                   or len(node.args) >= 3)
    facts.http_calls.append((ctx.path, fname, node.lineno, has_timeout))


# ------------------------------------------------------------ doc parsing
def _doc_metric_table(text: str) -> Dict[str, Tuple[Optional[str], int]]:
    """Parse OBSERVABILITY.md's metric inventory: backticked tokens in
    the first cell of table rows.  ``{a,b}`` groups expand to
    alternatives; a trailing ``{label=}`` names the family's single
    label dimension.  Tokens not matching the family grammar (prose,
    shorthand) are ignored — which is the forcing function toward
    explicit full names."""
    out: Dict[str, Tuple[Optional[str], int]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.lstrip().startswith("|"):
            continue
        first_cell = line.lstrip().lstrip("|").split("|", 1)[0]
        for tok in re.findall(r"`([^`]+)`", first_cell):
            for fam, label in _expand_doc_token(tok.strip()):
                out.setdefault(fam, (label, lineno))
    return out


def _expand_braces(tok: str) -> List[str]:
    """``a.{b,c}.d`` -> ``["a.b.d", "a.c.d"]`` (the doc tables' row
    compression; shared by the metric and event inventories)."""
    names = [tok]
    while True:
        expanded: List[str] = []
        changed = False
        for n in names:
            m = re.search(r"\{([^{}=]+)\}", n)
            if m and "," in m.group(1):
                changed = True
                for alt in m.group(1).split(","):
                    expanded.append(n[:m.start()] + alt.strip()
                                    + n[m.end():])
            else:
                expanded.append(n)
        names = expanded
        if not changed:
            return names


def _expand_doc_token(tok: str) -> List[Tuple[str, Optional[str]]]:
    label = None
    m = re.search(r"\{([a-z_]+)=\}$", tok)
    if m:
        label = m.group(1)
        tok = tok[:m.start()]
    return [(n, label) for n in _expand_braces(tok)
            if _FAMILY_RE.match(n)]


def _doc_event_table(text: str) -> Dict[str, int]:
    """Parse OBSERVABILITY.md's EVENT inventory: backticked tokens in
    the first cell of table rows under the "Event inventory" heading
    (and only there — the span table also uses dotted names, so the
    parse is heading-scoped).  ``{a,b}`` groups expand; tokens not
    matching the event grammar are ignored."""
    out: Dict[str, int] = {}
    in_section = False
    for lineno, line in enumerate(text.splitlines(), 1):
        if line.startswith("#"):
            in_section = "event inventory" in line.lower()
            continue
        if not in_section or not line.lstrip().startswith("|"):
            continue
        first_cell = line.lstrip().lstrip("|").split("|", 1)[0]
        for tok in re.findall(r"`([^`]+)`", first_cell):
            for name in _expand_braces(tok.strip()):
                if _EVENT_RE.match(name):
                    out.setdefault(name, lineno)
    return out


def _doc_knobs(text: str) -> Dict[str, int]:
    """Every backticked ``XGBTPU_*`` token in README, with its first
    line.  Table rows and prose both count as documentation — the
    contract is that the name is findable at all."""
    out: Dict[str, int] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        for span_text in re.findall(r"`([^`]+)`", line):
            for knob in _KNOB_RE.findall(span_text):
                if knob != "XGBTPU_":
                    out.setdefault(knob, lineno)
    return out


# ---------------------------------------------------------------- engine
class ContractEngine:
    """Phase-1 + phase-2 driver for one tree.

    ``root`` is where the docs (OBSERVABILITY.md, README.md) and the
    committed inventory (ANALYSIS_CONTRACTS.json) are looked up;
    ``fact_paths`` are the directories/files facts are collected from.
    For the real repo use :func:`default_engine`, which pins the fact
    scope to the package + ``tools/`` regardless of what subset the CLI
    was pointed at — contracts are whole-repo by nature.
    """

    def __init__(self, root: str,
                 fact_paths: Optional[Sequence[str]] = None,
                 codes: Optional[Iterable[str]] = None):
        self.root = os.path.abspath(root)
        if fact_paths is None:
            fact_paths = [self.root]
        self.fact_paths = [os.path.abspath(p) for p in fact_paths]
        self.codes = set(codes if codes is not None else CONTRACT_CODES)
        self._facts: Optional[Facts] = None

    # ----------------------------------------------------------- phase 1
    def facts(self) -> Facts:
        if self._facts is not None:
            return self._facts
        facts = Facts()
        for path in iter_py_files(self.fact_paths):
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=path)
            except (OSError, SyntaxError):
                continue  # per-file rules already report XGT000 there
            collect_file(FileContext(path, source, tree), facts)
        self._facts = facts
        return facts

    def _doc(self, name: str) -> Tuple[Optional[str], str]:
        path = os.path.join(self.root, name)
        try:
            with open(path, encoding="utf-8") as f:
                return f.read(), path
        except OSError:
            return None, path

    def _rel(self, path: str) -> str:
        try:
            rel = os.path.relpath(os.path.abspath(path), self.root)
        except ValueError:
            rel = path
        return rel.replace(os.sep, "/")

    # ----------------------------------------------------------- phase 2
    def run(self) -> Tuple[List[Finding], List[Finding]]:
        """-> (active findings, suppressed findings)."""
        facts = self.facts()
        findings: List[Finding] = []
        if "XGT008" in self.codes:
            findings += self._check_routes(facts)
        if "XGT009" in self.codes:
            findings += self._check_metrics(facts)
        if "XGT010" in self.codes:
            findings += self._check_knobs(facts)
        if "XGT011" in self.codes:
            findings += self._check_locks(facts)
        if "XGT012" in self.codes:
            findings += self._check_timeouts(facts)
        if "XGT016" in self.codes:
            findings += self._check_exit_codes(facts)
        if "XGT017" in self.codes:
            findings += self._check_events(facts)
        findings += self._check_inventory_drift(facts)
        active: List[Finding] = []
        suppressed: List[Finding] = []
        for f in findings:
            sup = facts.suppressions.get(f.path)
            (suppressed if sup is not None and sup.is_suppressed(f)
             else active).append(f)
        active.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return active, suppressed

    def _finding(self, rule: str, path: str, line: int, message: str,
                 snippet: str = "") -> Finding:
        if not snippet:
            lines = (self._facts.lines.get(path)
                     if self._facts is not None else None)
            if lines is None:
                try:
                    with open(path, encoding="utf-8") as f:
                        lines = f.read().splitlines()
                except OSError:
                    lines = []
            if 1 <= line <= len(lines):
                snippet = lines[line - 1]
        return Finding(rule=rule, path=path, line=line, col=0,
                       message=message, snippet=snippet)

    # ------------------------------------------------------------ XGT008
    def _check_routes(self, facts: Facts) -> List[Finding]:
        if not facts.routes:
            return []  # no handlers in scope: nothing to hold clients to
        table: Dict[str, Set[str]] = {}
        for _, _, method, path, _ in facts.routes:
            table.setdefault(path, set()).add(method)
        out = []
        for file, method, path, line in facts.clients:
            methods = table.get(path)
            if methods is None:
                out.append(self._finding(
                    "XGT008", file, line,
                    f"HTTP client calls {method} {path}, but no handler "
                    "route table (do_GET/do_POST dispatch) defines that "
                    "endpoint — typo, or the route was removed without "
                    "its callers"))
            elif method not in methods:
                out.append(self._finding(
                    "XGT008", file, line,
                    f"HTTP method mismatch: client sends {method} "
                    f"{path}, handlers serve it only via "
                    f"{'/'.join(sorted(methods))}"))
        return out

    # ------------------------------------------------------------ XGT009
    def _check_metrics(self, facts: Facts) -> List[Finding]:
        out: List[Finding] = []
        by_family: Dict[str, List[Tuple[str, Optional[str], int]]] = {}
        for file, fam, label, line in facts.families:
            by_family.setdefault(fam, []).append((file, label, line))
        for fam, sites in sorted(by_family.items()):
            labels = {lab for _, lab, _ in sites}
            if len(labels) > 1:
                file, _, line = sites[-1]
                out.append(self._finding(
                    "XGT009", file, line,
                    f"metric family {fam} is constructed with "
                    "INCONSISTENT label sets across sites "
                    f"({sorted(str(x) for x in labels)}) — scrapers see "
                    "one family, it must have one label schema"))
        doc_text, doc_path = self._doc(OBSERVABILITY_DOC)
        if doc_text is None or not facts.families:
            return out
        documented = _doc_metric_table(doc_text)
        for fam, sites in sorted(by_family.items()):
            file, label, line = sites[0]
            if fam not in documented:
                out.append(self._finding(
                    "XGT009", file, line,
                    f"metric family {fam} is constructed here but "
                    f"missing from {OBSERVABILITY_DOC}'s metric "
                    "inventory table — add a row (full family name in "
                    "backticks)"))
                continue
            doc_label, _ = documented[fam]
            if doc_label != label:
                out.append(self._finding(
                    "XGT009", file, line,
                    f"label drift on {fam}: code constructs label "
                    f"{label!r}, {OBSERVABILITY_DOC} documents "
                    f"{doc_label!r}"))
        for fam, (label, lineno) in sorted(documented.items()):
            if fam not in by_family:
                out.append(self._finding(
                    "XGT009", doc_path, lineno,
                    f"{OBSERVABILITY_DOC} documents metric family "
                    f"{fam}, which no code constructs — stale row or "
                    "renamed family"))
        return out

    # ------------------------------------------------------------ XGT010
    def _check_knobs(self, facts: Facts) -> List[Finding]:
        out: List[Finding] = []
        readme, readme_path = self._doc(README_DOC)
        reads: Dict[str, Tuple[str, int]] = {}
        for file, knob, line in facts.knobs:
            reads.setdefault(knob, (file, line))
        if readme is not None and facts.knobs:
            documented = _doc_knobs(readme)
            for knob, (file, line) in sorted(reads.items()):
                if knob not in documented:
                    out.append(self._finding(
                        "XGT010", file, line,
                        f"env knob {knob} is read here but undocumented "
                        f"in {README_DOC} — add it to the knob table"))
            for knob, lineno in sorted(documented.items()):
                if knob not in reads:
                    out.append(self._finding(
                        "XGT010", readme_path, lineno,
                        f"{README_DOC} documents env knob {knob}, which "
                        "nothing reads — stale doc or renamed knob"))
        # every SERVE_PARAMS/FLEET_PARAMS key must be consumed somewhere
        # outside its defining table (the CLI surface references each
        # key explicitly: sp["serve_x"] / fp["fleet_x"])
        for file, table, key, line in facts.params:
            used = any(key in consts
                       for path, consts in facts.str_consts.items()
                       if path != file)
            if not used:
                out.append(self._finding(
                    "XGT010", file, line,
                    f"{table.upper()}_PARAMS key {key!r} is never "
                    "referenced outside its table — the knob is "
                    "documented but not wired to any surface"))
        return out

    # ------------------------------------------------------------ XGT011
    def _check_locks(self, facts: Facts) -> List[Finding]:
        graph: Dict[str, Set[str]] = {}
        sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for file, outer, inner, line in facts.lock_edges:
            graph.setdefault(outer, set()).add(inner)
            graph.setdefault(inner, set())
            sites.setdefault((outer, inner), (file, line))
        out = []
        for cycle in _find_cycles(graph):
            # anchor on a REAL edge inside the cycle's node set — the
            # sorted node list is a set, not a walk, so zipping it
            # would fabricate edges the graph does not have
            members = set(cycle)
            real = sorted((a, b) for (a, b) in sites
                          if a in members and b in members
                          and b in graph.get(a, ()))
            anchor = min(sites[e] for e in real) if real else ("", 0)
            edge_s = ", ".join(f"{a} -> {b}" for a, b in real)
            out.append(self._finding(
                "XGT011", anchor[0], anchor[1],
                f"lock-order cycle among {{{', '.join(cycle)}}} "
                f"(acquisition edges: {edge_s}) — two call paths "
                "acquiring these locks concurrently can deadlock; "
                "pick one global order (the runtime LockRaceChecker "
                "only sees orders a test executes; this graph sees "
                "them all)"))
        return out

    # ------------------------------------------------------------ XGT012
    def _check_timeouts(self, facts: Facts) -> List[Finding]:
        out = []
        for file, call, line, has_timeout in facts.http_calls:
            if has_timeout:
                continue
            out.append(self._finding(
                "XGT012", file, line,
                f"outbound HTTP call {call}(...) passes no explicit "
                "timeout — blocked on a wedged peer it hangs this "
                "thread forever (the stall the deadline/watchdog "
                "machinery exists to bound); pass timeout="))
        return out

    # ------------------------------------------------------------ XGT016
    def _check_exit_codes(self, facts: Facts) -> List[Finding]:
        out: List[Finding] = []
        registry: Dict[int, str] = {}
        for file, name, value, line in sorted(
                facts.rc_defs, key=lambda t: (t[0], t[3])):
            if value in registry:
                out.append(self._finding(
                    "XGT016", file, line,
                    f"exit code {value} registered twice "
                    f"({registry[value]} and {name}) — the launcher "
                    "dispatches recovery on the VALUE, two names for "
                    "one code is a routing ambiguity"))
            else:
                registry[value] = name
        for file, name, value, line in facts.rc_assigns:
            hint = (f" (collides with registered {registry[value]})"
                    if value in registry else "")
            out.append(self._finding(
                "XGT016", file, line,
                f"exit-code constant {name} = {value} defined outside "
                f"the registry{hint} — reliability/rc.py is the single "
                "home; define it there and import it"))
        for file, call, value, line in facts.exit_calls:
            if value in registry:
                out.append(self._finding(
                    "XGT016", file, line,
                    f"{call}({value}) spells registered exit code "
                    f"{registry[value]} as a magic literal — import it "
                    "from reliability.rc so the registry stays the "
                    "single source of truth"))
            elif value not in _GENERIC_RCS:
                out.append(self._finding(
                    "XGT016", file, line,
                    f"{call}({value}): unregistered protocol exit code "
                    "— register a *_RC constant in reliability/rc.py "
                    "(0/1/2 are POSIX-generic and exempt); the "
                    "launcher cannot dispatch recovery on a code it "
                    "has no name for"))
        for file, name, value, line in facts.rc_compares:
            if value in registry:
                out.append(self._finding(
                    "XGT016", file, line,
                    f"comparison of {name} against magic literal "
                    f"{value} — that is registered exit code "
                    f"{registry[value]}; compare against the constant "
                    "so a registry renumber cannot desynchronize "
                    "dispatch"))
        return out

    # ------------------------------------------------------------ XGT017
    def _check_events(self, facts: Facts) -> List[Finding]:
        out: List[Finding] = []
        if not facts.events:
            return out
        doc_text, doc_path = self._doc(OBSERVABILITY_DOC)
        if doc_text is None:
            return out
        documented = _doc_event_table(doc_text)
        emitted: Dict[str, Tuple[str, int]] = {}
        for file, name, line in sorted(
                facts.events, key=lambda t: (t[0], t[2])):
            emitted.setdefault(name, (file, line))
        for name, (file, line) in sorted(emitted.items()):
            if name not in documented:
                out.append(self._finding(
                    "XGT017", file, line,
                    f"event {name!r} is emitted here but missing from "
                    f"{OBSERVABILITY_DOC}'s event inventory table — "
                    "add a row (full dotted name in backticks); "
                    "obs_report and the chaos selftests grep event "
                    "names, an undocumented one is invisible tooling "
                    "surface"))
        for name, lineno in sorted(documented.items()):
            if name not in emitted:
                out.append(self._finding(
                    "XGT017", doc_path, lineno,
                    f"{OBSERVABILITY_DOC} documents event {name!r}, "
                    "which nothing emits — stale row or renamed "
                    "event"))
        return out

    # -------------------------------------------------------- inventory
    def inventory(self) -> dict:
        """The committed-contract view of the extracted facts: stable,
        line-number-free, repo-root-relative — the thing reviewers diff
        in PRs (ANALYSIS_CONTRACTS.json)."""
        facts = self.facts()
        routes = sorted({(self._rel(f), cls, m, p)
                         for f, cls, m, p, _ in facts.routes})
        families: Dict[str, Optional[str]] = {}
        # sort on hashable columns only: a family constructed both with
        # and without a label (itself an XGT009 finding) must not crash
        # the inventory on a None-vs-str comparison
        for _, fam, label, _ in sorted(
                facts.families, key=lambda t: (t[0], t[1], t[3])):
            families.setdefault(fam, label)
        params: Dict[str, List[str]] = {"serve": [], "fleet": [],
                                        "pipeline": [], "catalog": [],
                                        "stream": [], "placer": []}
        for _, table, key, _ in facts.params:
            if key not in params[table]:
                params[table].append(key)
        edges = sorted({(o, i) for _, o, i, _ in facts.lock_edges})
        # XGT012 inventory: every outbound-HTTP constructor site, with
        # its timeout discipline (the checker keeps `true` the only
        # value that survives tier-1, so this section is the committed
        # proof the tree has no timeout-less client)
        http_clients = sorted({(self._rel(f), call, has_t)
                               for f, call, _, has_t in facts.http_calls})
        # XGT016/XGT017 inventories: the registered exit-code protocol
        # (name -> value, sorted by value — recovery dispatch order)
        # and every emitted obs event name
        exit_codes = dict(sorted(
            {name: value for _, name, value, _ in facts.rc_defs}.items(),
            key=lambda kv: kv[1]))
        return {
            "version": 2,
            "http_routes": [
                {"file": f, "handler": cls, "method": m, "path": p}
                for f, cls, m, p in routes],
            "metric_families": {
                fam: {"label": families[fam]}
                for fam in sorted(families)},
            "env_knobs": sorted({k for _, k, _ in facts.knobs}),
            "cli_params": {t: sorted(ks) for t, ks in params.items()},
            "lock_edges": [list(e) for e in edges],
            "http_clients": [
                {"file": f, "call": c, "timeout": t}
                for f, c, t in http_clients],
            "exit_codes": exit_codes,
            "events": sorted({n for _, n, _ in facts.events}),
        }

    def contracts_path(self) -> str:
        return os.path.join(self.root, CONTRACTS_FILE)

    def doc_surfaces(self) -> List[str]:
        """Absolute paths of the doc/inventory files contract findings
        may anchor in (existing files only) — the CLI's ``--changed``
        filter and ``--write-baseline`` coverage both key off this, so
        a new checked doc surface automatically rides along."""
        out = []
        for name in (OBSERVABILITY_DOC, README_DOC, CONTRACTS_FILE):
            p = os.path.join(self.root, name)
            if os.path.exists(p):
                out.append(p)
        return out

    def write_inventory(self, path: Optional[str] = None) -> str:
        path = path or self.contracts_path()
        payload = (json.dumps(self.inventory(), indent=2,
                              sort_keys=False) + "\n").encode()
        from xgboost_tpu.reliability.integrity import atomic_write
        atomic_write(path, payload, durable=False)
        return path

    _SECTION_RULE = {"http_routes": "XGT008",
                     "metric_families": "XGT009",
                     "env_knobs": "XGT010",
                     "cli_params": "XGT010",
                     "lock_edges": "XGT011",
                     "http_clients": "XGT012",
                     "exit_codes": "XGT016",
                     "events": "XGT017"}

    def _check_inventory_drift(self, facts: Facts) -> List[Finding]:
        """The committed ANALYSIS_CONTRACTS.json must match what the
        tree extracts NOW — a contract change lands as a reviewed diff
        of the inventory, never silently."""
        path = self.contracts_path()
        if not os.path.exists(path):
            return []
        try:
            with open(path, encoding="utf-8") as f:
                committed = json.load(f)
        except (OSError, ValueError) as e:
            return [self._finding(
                "XGT008", path, 1,
                f"{CONTRACTS_FILE} is unreadable ({e}) — regenerate "
                "with --write-contracts", snippet=CONTRACTS_FILE)]
        current = self.inventory()
        out = []
        for section, rule in sorted(self._SECTION_RULE.items()):
            if rule not in self.codes:
                continue
            if committed.get(section) != current.get(section):
                out.append(self._finding(
                    rule, path, 1,
                    f"committed {CONTRACTS_FILE} section "
                    f"{section!r} is stale (the tree's extracted "
                    "contract changed) — review the diff and "
                    "regenerate with --write-contracts",
                    snippet=f"{CONTRACTS_FILE}:{section}"))
        return out


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles, one per strongly connected component (plus
    self-loops): deterministic, and enough for a lint report — the fix
    (pick one order) collapses the whole SCC anyway."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Dict[str, bool] = {}
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack[v] = True
        for w in sorted(graph.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif on_stack.get(w):
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack[w] = False
                comp.append(w)
                if w == v:
                    break
            sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    cycles = []
    for comp in sccs:
        if len(comp) > 1:
            cycles.append(sorted(comp))
        elif comp[0] in graph.get(comp[0], ()):
            cycles.append(comp)  # self-loop: nested re-acquisition
    return sorted(cycles)


# ------------------------------------------------------------ construction
def repo_root() -> str:
    return os.path.dirname(default_baseline_path())


def default_engine(paths: Sequence[str],
                   codes: Optional[Iterable[str]] = None
                   ) -> ContractEngine:
    """The engine for a CLI invocation: when every scanned path sits
    inside the repo, contracts are whole-repo (root = repo root, facts
    from the package + ``tools/`` — a subset scan must not shrink the
    contract); otherwise (fixture mini-trees) the scanned paths ARE the
    tree and docs are looked up at their common root."""
    root = repo_root()
    abspaths = [os.path.abspath(p) for p in paths]
    if all(os.path.commonpath([root, p]) == root for p in abspaths
           if os.path.splitdrive(p)[0] == os.path.splitdrive(root)[0]):
        pkg = os.path.join(root, "xgboost_tpu")
        tools = os.path.join(root, "tools")
        fact_paths = [p for p in (pkg, tools) if os.path.isdir(p)]
        return ContractEngine(root, fact_paths or [root], codes=codes)
    common = (abspaths[0] if len(abspaths) == 1
              else os.path.commonpath(abspaths))
    if os.path.isfile(common):
        common = os.path.dirname(common)
    return ContractEngine(common, abspaths, codes=codes)

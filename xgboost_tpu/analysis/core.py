"""xgtpu-lint core: findings, suppressions, baseline, and the runner.

The engine is deliberately dependency-free (stdlib ``ast`` only — no
jax import), so ``python -m xgboost_tpu.analysis`` runs anywhere the
source tree exists, including CI hosts with no accelerator runtime.

Three layers of "this finding is accepted":

1. **inline suppression** — ``# xgtpu: disable=XGT003`` on the
   offending line (or on a comment line directly above it) silences the
   named rule(s) for that statement; ``# xgtpu: disable-file=XGT004``
   anywhere in the file silences the rule(s) file-wide.  ``all`` names
   every rule.  Suppressions are for sites where the pattern is
   INTENTIONAL and the comment should say why.
2. **baseline** — a committed JSON ledger of accepted legacy findings
   (``ANALYSIS_BASELINE.json``).  Baselined findings do not fail the
   build but are reported as "baselined" so the debt stays visible.
   Keys are content-addressed (rule + path tail + source line text), so
   unrelated edits that shift line numbers do not invalidate them.
3. everything else fails (exit code 1 / the tier-1 test).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import sys
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_DISABLE_RE = re.compile(
    r"#\s*xgtpu:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<codes>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")

#: rule code used for files the parser itself rejects
PARSE_ERROR_RULE = "XGT000"


def _iter_comments(source: str):
    """Yield ``(lineno, text, is_comment_only_line)`` for every real
    comment token.  Tokenize errors end the scan quietly (the caller
    already ast-parsed the file; a trailing tokenize hiccup must not
    kill suppression handling for the lines before it)."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield (tok.start[0], tok.string,
                       tok.line.lstrip().startswith("#"))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""

    @property
    def baseline_key(self) -> str:
        """Content-addressed identity: stable across line-number drift
        AND across invocation styles (relative vs absolute paths) —
        repo files key on their repo-root-relative path, so a baseline
        written by ``tools/xgtpu_lint.py xgboost_tpu/`` matches a run
        of ``python -m xgboost_tpu.analysis`` (absolute default path)."""
        return f"{self.rule}|{_key_path(self.path)}|{self.snippet.strip()}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message}")

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet.strip()}


class Suppressions:
    """Inline ``# xgtpu: disable=...`` directives for one file.

    Directives are read from REAL comment tokens only (``tokenize``),
    never from string literals or docstrings — prose that merely
    *mentions* the syntax (this module's own docstring, ANALYSIS.md
    excerpts quoted in code) must not disable anything."""

    def __init__(self, source: str):
        self.file_wide: Set[str] = set()
        self.by_line: Dict[int, Set[str]] = {}
        for lineno, text, own_line in _iter_comments(source):
            m = _DISABLE_RE.search(text)
            if not m:
                continue
            codes = {c.strip().upper()
                     for c in m.group("codes").split(",") if c.strip()}
            if m.group("file"):
                self.file_wide |= codes
                continue
            self.by_line.setdefault(lineno, set()).update(codes)
            if own_line:
                # a comment-only suppression line also covers the next
                # source line (the statement it annotates)
                self.by_line.setdefault(lineno + 1, set()).update(codes)

    def is_suppressed(self, finding: Finding) -> bool:
        def hit(codes: Set[str]) -> bool:
            return "ALL" in codes or finding.rule.upper() in codes
        if hit(self.file_wide):
            return True
        codes = self.by_line.get(finding.line, set())
        return hit(codes)


class FileContext:
    """Everything a rule needs to inspect one parsed file."""

    def __init__(self, path: str, source: str, tree: ast.AST):
        self.path = os.path.normpath(path).replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    # ---------------------------------------------------------- tree helpers
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_loop(self, node: ast.AST) -> Optional[ast.AST]:
        """Nearest enclosing STATEMENT loop (for/while; comprehensions
        do not count — they are expression-level and usually cold)."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.For, ast.While)):
                return anc
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
                return None
        return None

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=self.path, line=line, col=col,
                       message=message, snippet=self.line_text(line))


# ------------------------------------------------------------------ helpers
def terminal_name(func: ast.AST) -> Optional[str]:
    """The last identifier of a call target: ``open`` for ``open`` and
    ``io.open``, ``jit`` for ``jax.jit``."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ------------------------------------------------------------------ baseline
class Baseline:
    """Committed ledger of accepted legacy findings (counts per
    content-addressed key)."""

    VERSION = 1

    def __init__(self, counts: Optional[Dict[str, int]] = None):
        self.counts: Dict[str, int] = dict(counts or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as f:
            data = json.load(f)
        if data.get("version") != cls.VERSION:
            raise ValueError(
                f"{path}: unsupported baseline version "
                f"{data.get('version')!r} (expected {cls.VERSION})")
        counts = {str(k): int(v) for k, v in data.get("findings", {}).items()}
        return cls(counts)

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        counts: Dict[str, int] = {}
        for f in findings:
            counts[f.baseline_key] = counts.get(f.baseline_key, 0) + 1
        return cls(counts)

    def dump(self, path: str) -> None:
        data = {"version": self.VERSION,
                "findings": dict(sorted(self.counts.items()))}
        payload = (json.dumps(data, indent=2, sort_keys=False)
                   + "\n").encode()
        from xgboost_tpu.reliability.integrity import atomic_write
        atomic_write(path, payload, durable=False)

    def rescoped(self, findings: Sequence[Finding],
                 scanned_paths: Sequence[str]) -> "Baseline":
        """A new baseline where entries for files UNDER the scanned
        paths are replaced by ``findings`` and entries elsewhere are
        kept — so a partial-scan ``--write-baseline`` cannot silently
        drop the rest of the accepted debt.  Coverage matching works on
        repo-root-relative key paths; scanned paths outside the repo
        replace nothing beyond their own re-found keys (the baseline is
        a repo ledger)."""
        prefixes: List[Tuple[str, bool]] = []
        for p in scanned_paths:
            prefixes.append((_key_path(os.fspath(p)),
                             os.path.isdir(p)))

        def covered(key: str) -> bool:
            kpath = key.split("|", 2)[1]
            for kp, is_dir in prefixes:
                if kp in (".", ""):
                    return True
                if is_dir and kpath.startswith(kp.rstrip("/") + "/"):
                    return True
                if not is_dir and kpath == kp:
                    return True
            return False

        kept = {k: v for k, v in self.counts.items() if not covered(k)}
        merged = Baseline(kept)
        for f in findings:
            merged.counts[f.baseline_key] = (
                merged.counts.get(f.baseline_key, 0) + 1)
        return merged

    def split(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Finding]]:
        """-> (new findings, baselined findings).  Each baseline entry
        absorbs at most its recorded count."""
        budget = dict(self.counts)
        new: List[Finding] = []
        old: List[Finding] = []
        for f in findings:
            k = f.baseline_key
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                old.append(f)
            else:
                new.append(f)
        return new, old


def default_baseline_path() -> str:
    """``ANALYSIS_BASELINE.json`` next to the package (the repo root in
    a source checkout)."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg_dir), "ANALYSIS_BASELINE.json")


def _key_path(path: str) -> str:
    """Baseline-key path form: repo-root-relative for files under the
    repo, the last three components otherwise (tmp fixtures)."""
    root = os.path.dirname(default_baseline_path())
    try:
        rel = os.path.relpath(os.path.abspath(path), root)
    except ValueError:  # different drive (Windows)
        rel = None
    if rel is not None and not rel.startswith(".."):
        return rel.replace(os.sep, "/")
    parts = os.path.normpath(path).replace(os.sep, "/").split("/")
    return "/".join(parts[-3:])


# -------------------------------------------------------------------- runner
@dataclasses.dataclass
class Result:
    """Outcome of one analysis run."""

    findings: List[Finding]            # unsuppressed, non-baselined
    baselined: List[Finding]
    suppressed: List[Finding]
    files_scanned: int

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        return {
            "version": 1,
            "files_scanned": self.files_scanned,
            "findings": [f.to_json() for f in self.findings],
            "baselined": [f.to_json() for f in self.baselined],
            "suppressed_count": len(self.suppressed),
            "counts": self.rule_counts(),
            "clean": self.clean,
        }

    def rule_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return counts


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__"
                             and not d.startswith("."))
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def analyze_source(source: str, path: str = "<string>",
                   rules: Optional[Sequence] = None
                   ) -> Tuple[List[Finding], List[Finding]]:
    """Lint one source string -> (active findings, suppressed findings).
    Parse failures surface as a single XGT000 finding (never an
    exception: the linter must report on a broken tree, not die on it).
    """
    from xgboost_tpu.analysis.rules import all_rules
    if rules is None:
        rules = all_rules()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        f = Finding(rule=PARSE_ERROR_RULE, path=path,
                    line=e.lineno or 1, col=e.offset or 0,
                    message=f"file does not parse: {e.msg}")
        return [f], []
    ctx = FileContext(path, source, tree)
    sup = Suppressions(source)
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for rule in rules:
        if not rule.applies(ctx.path):
            continue
        for f in rule.check(ctx):
            (suppressed if sup.is_suppressed(f) else active).append(f)
    active.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return active, suppressed


def run(paths: Sequence[str], baseline: Optional[Baseline] = None,
        rules: Optional[Sequence] = None, contracts=None,
        anchor_filter=None) -> Result:
    """Lint every ``.py`` file under ``paths``.

    ``contracts`` is an optional
    :class:`~xgboost_tpu.analysis.contracts.ContractEngine`: its
    cross-file findings (XGT008-XGT011) merge into the result and flow
    through the same baseline/exit machinery.  ``anchor_filter`` (a
    ``Finding -> bool``) drops findings outside a file set of interest
    — the ``--changed`` pre-commit loop (facts still collect repo-wide;
    only the REPORTING narrows)."""
    from xgboost_tpu.analysis.rules import all_rules
    if rules is None:
        rules = all_rules()
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    n_files = 0
    for path in iter_py_files(paths):
        n_files += 1
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            findings.append(Finding(
                rule=PARSE_ERROR_RULE, path=path, line=1, col=0,
                message=f"unreadable: {e}"))
            continue
        active, sup = analyze_source(source, path, rules)
        findings.extend(active)
        suppressed.extend(sup)
    if contracts is not None:
        cactive, csup = contracts.run()
        findings.extend(cactive)
        suppressed.extend(csup)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if anchor_filter is not None:
        findings = [f for f in findings if anchor_filter(f)]
    if baseline is not None:
        new, old = baseline.split(findings)
    else:
        new, old = findings, []
    return Result(findings=new, baselined=old, suppressed=suppressed,
                  files_scanned=n_files)


def render_report(result: Result, out=None, verbose: bool = False) -> None:
    out = out if out is not None else sys.stdout
    for f in result.findings:
        print(f.render(), file=out)
    if verbose:
        for f in result.baselined:
            print(f"{f.render()}  [baselined]", file=out)
    counts = result.rule_counts()
    summary = ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
    print(f"xgtpu-lint: {result.files_scanned} files, "
          f"{len(result.findings)} finding(s)"
          + (f" ({summary})" if summary else "")
          + (f", {len(result.baselined)} baselined" if result.baselined
             else "")
          + (f", {len(result.suppressed)} suppressed"
             if result.suppressed else ""),
          file=out)

"""xgtpu-lint v3: dataflow-aware JAX tracing rules (ANALYSIS.md §v3).

The v1 rules are pattern matchers over one AST node at a time; the
hazards this module targets are relations BETWEEN statements — a buffer
donated at line 40 and read at line 55, a side effect inside a function
whose only callers are ``jax.jit``, a ``psum`` whose axis name never
appears in the enclosing ``shard_map``'s specs.  Two shared layers feed
three rules:

- :class:`FunctionFlow` — an intraprocedural def-use view of one
  function: every binding site (assignments, loop targets, ``with
  ... as``, walrus), every ``Name`` load, both in stable source order,
  plus param-rooted taint (a name assigned from a tainted expression is
  tainted, transitively) — reaching-definitions flattened to source
  order, which is exact for the straight-line callers this tree has
  and conservative under branches (both arms count as "after").
- :func:`traced_functions` — the set of function defs whose bodies
  execute under a JAX trace: jit-decorated (directly or via
  ``functools.partial(jax.jit, ...)``), passed to ``jax.jit`` /
  ``shard_map`` / ``lax.scan``-family wrappers by name, or nested
  inside either.

Rules (registered in rules.py alongside XGT001-XGT007):

  XGT013  use-after-donate — an argument at a ``donate_argnums``
          position of a jitted callable is DEAD after the call (XLA
          may have reused the buffer); the carry-rebind idiom
          ``carry = fn(carry, ...)`` is the blessed pattern.
  XGT014  impure traced scope — obs/metrics emission, fault
          injection, ``time.*``, ``print``/``open``, global/nonlocal
          mutation, host pulls, or ``np.asarray`` on traced values
          inside a traced function: the side effect fires once at
          trace time (or never), not per execution.
  XGT015  collective axis discipline — ``psum``/``all_gather`` axis
          names must match an axis the enclosing ``shard_map``'s
          specs/mesh mention, and collectives must not sit under
          Python branches on traced (param-tainted) values.

Like every rule here: precision over recall — an unresolvable name is
skipped, not guessed at.  The runtime twin of XGT013 is
:class:`~xgboost_tpu.analysis.runtime.DonationGuard`.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from xgboost_tpu.analysis.core import (FileContext, Finding, const_str,
                                       dotted_name, terminal_name)

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)


# ------------------------------------------------------------- jit helpers
def _is_jit(node: ast.AST) -> bool:
    """``jax.jit`` / bare ``jit`` (the only spellings in this tree)."""
    return (dotted_name(node) in ("jax.jit", "jit")
            or (isinstance(node, ast.Attribute) and node.attr == "jit"))


def _const_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return tuple(out)
    return None


def _kw_names(call: ast.Call, kw_name: str) -> Set[str]:
    """Constant string(s) of a keyword like ``static_argnames=``."""
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg != kw_name:
            continue
        s = const_str(kw.value)
        if s is not None:
            names.add(s)
        elif isinstance(kw.value, (ast.Tuple, ast.List)):
            for e in kw.value.elts:
                s = const_str(e)
                if s:
                    names.add(s)
    return names


def _donate_argnums(call: ast.Call) -> Optional[Tuple[int, ...]]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _const_int_tuple(kw.value)
    return None


def _jit_call_of(node: ast.AST) -> Optional[ast.Call]:
    """The jit-configuring Call when ``node`` wraps a function in jit:
    ``jax.jit(f, ...)`` -> that call; ``functools.partial(jax.jit,
    ...)(f)`` -> the partial call (which carries the keywords)."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jit(node.func):
        return node
    f = node.func
    if (isinstance(f, ast.Call) and terminal_name(f.func) == "partial"
            and f.args and _is_jit(f.args[0])):
        return f
    return None


def _wrapped_callable(node: ast.Call) -> Optional[str]:
    """The NAME being jit-wrapped by ``node`` (``jax.jit(f)`` /
    ``partial(jax.jit, ...)(f)``), when it is a plain name."""
    cfg = _jit_call_of(node)
    if cfg is None:
        return None
    if cfg is node:                       # jax.jit(f, ...)
        if node.args and isinstance(node.args[0], ast.Name):
            return node.args[0].id
        return None
    if node.args and isinstance(node.args[0], ast.Name):
        return node.args[0].id            # partial(jax.jit, ..)(f)
    return None


# ------------------------------------------------------------ traced scope
#: wrapper callables whose function-valued arguments execute under a
#: JAX trace.  ``scan``/``while_loop``/``cond`` cover the lax control
#: flow family; ``shard_map`` covers both jax.experimental and this
#: tree's parallel/mesh.py compat wrapper (same terminal name).
_TRACING_WRAPPERS = frozenset({
    "jit", "pmap", "vmap", "shard_map", "scan", "while_loop",
    "fori_loop", "cond", "grad", "value_and_grad", "remat",
    "checkpoint", "custom_vjp", "custom_jvp"})


def traced_functions(ctx: FileContext) -> Set[ast.AST]:
    """Every FunctionDef whose body runs under a JAX trace, plus all
    function defs nested inside one.  Also records, per traced root,
    the static argnames its jit wrapping declares (``.xgtpu_static``
    attribute) so taint can skip trace-static params."""
    by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, FunctionNode):
            by_name.setdefault(node.name, []).append(node)

    roots: Dict[ast.AST, Set[str]] = {}

    def add_root(fn: ast.AST, statics: Set[str]) -> None:
        roots.setdefault(fn, set()).update(statics)

    for node in ast.walk(ctx.tree):
        if isinstance(node, FunctionNode):
            for dec in node.decorator_list:
                if _is_jit(dec):
                    add_root(node, set())
                elif isinstance(dec, ast.Call):
                    cfg = dec if _is_jit(dec.func) else _jit_call_of(dec)
                    if cfg is not None:
                        add_root(node, _kw_names(cfg, "static_argnames"))
        if not isinstance(node, ast.Call):
            continue
        cfg = _jit_call_of(node)
        if cfg is not None:
            name = _wrapped_callable(node)
            if name:
                for fn in by_name.get(name, ()):
                    add_root(fn, _kw_names(cfg, "static_argnames"))
            continue
        if terminal_name(node.func) in _TRACING_WRAPPERS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    for fn in by_name.get(arg.id, ()):
                        add_root(fn, set())

    traced: Set[ast.AST] = set()
    for fn, statics in roots.items():
        fn.xgtpu_static = statics  # type: ignore[attr-defined]
        for sub in ast.walk(fn):
            if isinstance(sub, FunctionNode):
                traced.add(sub)
    return traced


def _param_names(fn) -> Set[str]:
    a = fn.args
    names = {p.arg for p in a.posonlyargs + a.args}
    if a.vararg:
        names.add(a.vararg.arg)
    return names


def param_taint(fn) -> Set[str]:
    """Names carrying (possibly) traced values inside ``fn``: its
    positional params minus declared ``static_argnames`` (kw-only
    params are excluded wholesale — every jit wrapper in this tree
    passes statics keyword-only), closed transitively over simple
    assignments whose right-hand side reads a tainted name."""
    statics = getattr(fn, "xgtpu_static", set())
    tainted = _param_names(fn) - set(statics)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if not isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign, ast.NamedExpr)):
                continue
            value = node.value
            if value is None:
                continue
            if not any(isinstance(s, ast.Name) and s.id in tainted
                       and isinstance(s.ctx, ast.Load)
                       for s in ast.walk(value)):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for name in _target_names(t):
                    if name not in tainted:
                        tainted.add(name)
                        changed = True
    return tainted


# ------------------------------------------------------------ FunctionFlow
def _target_names(target: ast.AST) -> Iterator[str]:
    """Every plain name bound by an assignment target."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, ast.Starred):
        yield from _target_names(target.value)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            yield from _target_names(e)


def stmt_bound_names(stmt: ast.AST) -> Set[str]:
    """Names (re)bound by ONE statement's own targets."""
    out: Set[str] = set()
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            out.update(_target_names(t))
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        out.update(_target_names(stmt.target))
    elif isinstance(stmt, ast.For):
        out.update(_target_names(stmt.target))
    elif isinstance(stmt, ast.With):
        for item in stmt.items:
            if item.optional_vars is not None:
                out.update(_target_names(item.optional_vars))
    return out


class FunctionFlow:
    """Source-ordered def/use events for one function body.

    ``defs[name]`` / ``uses[name]`` are lists of ``(lineno, col,
    node)`` sorted by position.  Nested function bodies are EXCLUDED:
    a closure's reads execute at some unrelated time, and guessing
    would trade precision for noise (ANALYSIS.md §v3)."""

    def __init__(self, ctx: FileContext, fn) -> None:
        self.ctx = ctx
        self.fn = fn
        self.defs: Dict[str, List[Tuple[int, int, ast.AST]]] = {}
        self.uses: Dict[str, List[Tuple[int, int, ast.AST]]] = {}
        self.aliases: Dict[str, List[Tuple[int, str, ast.AST]]] = {}
        for node in self._walk_own(fn):
            if isinstance(node, ast.Name):
                rec = (node.lineno, node.col_offset, node)
                if isinstance(node.ctx, ast.Load):
                    self.uses.setdefault(node.id, []).append(rec)
                else:
                    self.defs.setdefault(node.id, []).append(rec)
            elif isinstance(node, ast.Assign):
                # simple alias copy: ``a = b`` (the donated-buffer
                # aliasing hazard XGT013's MUST-FAIL fixture pins)
                if isinstance(node.value, ast.Name):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.aliases.setdefault(
                                node.value.id, []).append(
                                    (node.lineno, t.id, node))
        for events in self.defs.values():
            events.sort(key=lambda r: (r[0], r[1]))
        for events in self.uses.values():
            events.sort(key=lambda r: (r[0], r[1]))

    @staticmethod
    def _walk_own(fn) -> Iterator[ast.AST]:
        """Walk ``fn``'s body without descending into nested function
        defs or lambdas."""
        stack: List[ast.AST] = list(fn.body)
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, FunctionNode + (ast.Lambda,)):
                    continue
                stack.append(child)

    def first_event_after(self, name: str, line: int
                          ) -> Optional[Tuple[str, ast.AST]]:
        """The first def or use of ``name`` strictly after ``line`` ->
        ``("def"|"use", node)`` — the reaching-definitions question
        XGT013 asks, flattened to source order."""
        events: List[Tuple[int, int, str, ast.AST]] = []
        for ln, col, node in self.defs.get(name, ()):
            if ln > line:
                events.append((ln, col, "def", node))
        for ln, col, node in self.uses.get(name, ()):
            if ln > line:
                events.append((ln, col, "use", node))
        if not events:
            return None
        events.sort(key=lambda r: (r[0], r[1]))
        _, _, kind, node = events[0]
        return kind, node

    def live_aliases(self, name: str, line: int) -> List[str]:
        """Names that are plain copies of ``name`` made before
        ``line`` and not rebound again before it."""
        out = []
        for ln, alias, _ in self.aliases.get(name, ()):
            if ln >= line or alias == name:
                continue
            redef = [d for d, _, n in self.defs.get(alias, ())
                     if ln < d < line]
            if not redef:
                out.append(alias)
        return out


def enclosing_stmt(ctx: FileContext, node: ast.AST) -> ast.AST:
    """The nearest enclosing STATEMENT of an expression node."""
    cur = node
    while not isinstance(cur, ast.stmt):
        parent = ctx.parent(cur)
        if parent is None:
            return cur
        cur = parent
    return cur


def enclosing_function(ctx: FileContext, node: ast.AST):
    for anc in ctx.ancestors(node):
        if isinstance(anc, FunctionNode):
            return anc
    return None


# ----------------------------------------------------------------- XGT013
class Rule:
    code = "XGT000"
    name = "base"

    def applies(self, path: str) -> bool:
        return True

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


class UseAfterDonate(Rule):
    """XGT013: a caller reads an argument it passed at a
    ``donate_argnums`` position of a jitted callable, after the call —
    XLA may already have reused (or on CPU will warn and copy) that
    buffer, and on TPU the read returns garbage or raises.  The
    blessed idiom is the carry rebind, ``margin, ... = fn(margin,
    ...)``: the donated name is rebound by the call's own statement,
    so nothing can read the dead buffer.  Donation maps follow simple
    aliases, including the conditional-wrapper selection
    ``fn = donated if donate else plain`` (union of positions), and
    ``tuple(name)`` wrapping of a donated pytree argument.  A donating
    call inside a loop that does NOT rebind its donated argument is
    flagged outright: iteration 2 passes an already-donated buffer."""

    code = "XGT013"
    name = "use-after-donate"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        donated = self._module_donation_map(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, FunctionNode):
                yield from self._check_function(ctx, node, donated)

    # -------------------------------------------------- donation maps
    @staticmethod
    def _module_donation_map(ctx: FileContext
                             ) -> Dict[str, FrozenSet[int]]:
        out: Dict[str, FrozenSet[int]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, FunctionNode):
                for dec in node.decorator_list:
                    cfg = (dec if isinstance(dec, ast.Call)
                           and _is_jit(dec.func) else _jit_call_of(dec))
                    if cfg is None:
                        continue
                    nums = _donate_argnums(cfg)
                    if nums:
                        out[node.name] = frozenset(nums)
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            cfg = _jit_call_of(node.value)
            if cfg is None:
                continue
            nums = _donate_argnums(cfg)
            if nums:
                out[node.targets[0].id] = frozenset(nums)
        return out

    @staticmethod
    def _local_donation_map(fn, donated: Dict[str, FrozenSet[int]]
                            ) -> Dict[str, FrozenSet[int]]:
        """Extend the module map with function-local aliases:
        ``scan = _donated`` and ``scan = _donated if c else _plain``
        (union of referenced donated names' positions)."""
        local = dict(donated)
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                value = node.value
                names: List[str] = []
                if isinstance(value, ast.Name):
                    names = [value.id]
                elif isinstance(value, ast.IfExp):
                    names = [n.id for n in (value.body, value.orelse)
                             if isinstance(n, ast.Name)]
                positions: Set[int] = set()
                for n in names:
                    positions.update(local.get(n, ()))
                if positions:
                    tgt = node.targets[0].id
                    if frozenset(positions) != local.get(tgt):
                        local[tgt] = frozenset(positions)
                        changed = True
        return local

    @staticmethod
    def _donated_arg_names(call: ast.Call,
                           positions: FrozenSet[int]) -> List[str]:
        """Caller-side names whose buffers the call donates: a bare
        ``name`` or ``tuple(name)`` at a donated position."""
        out = []
        for i in sorted(positions):
            if i >= len(call.args):
                continue
            arg = call.args[i]
            if (isinstance(arg, ast.Call)
                    and terminal_name(arg.func) == "tuple" and arg.args):
                arg = arg.args[0]
            if isinstance(arg, ast.Name):
                out.append(arg.id)
        return out

    # ------------------------------------------------------- checking
    def _check_function(self, ctx: FileContext, fn,
                        donated: Dict[str, FrozenSet[int]]
                        ) -> Iterator[Finding]:
        local = self._local_donation_map(fn, donated)
        calls = []
        for node in FunctionFlow._walk_own(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in local):
                calls.append(node)
        if not calls:
            return
        flow = FunctionFlow(ctx, fn)
        for call in calls:
            stmt = enclosing_stmt(ctx, call)
            rebound = stmt_bound_names(stmt)
            positions = local[call.func.id]
            for name in self._donated_arg_names(call, positions):
                in_loop = self._loop_between(ctx, call, fn)
                if name not in rebound and in_loop is not None:
                    yield ctx.finding(
                        self.code, call,
                        f"{call.func.id}() donates {name!r} but the "
                        "enclosing loop never rebinds it — iteration 2 "
                        "passes an already-donated buffer; use the "
                        f"carry rebind ({name} = "
                        f"{call.func.id}({name}, ...))")
                    continue
                # a carry rebind revives the NAME, but any pre-call
                # alias still points at the dead buffer — check those
                # regardless
                dead_names = flow.live_aliases(name, call.lineno)
                if name not in rebound:
                    dead_names = [name] + dead_names
                end = getattr(stmt, "end_lineno", stmt.lineno)
                for dead in dead_names:
                    nxt = flow.first_event_after(dead, end)
                    if nxt is None or nxt[0] == "def":
                        continue
                    _, use = nxt
                    what = (f"{dead!r} (aliasing donated {name!r})"
                            if dead != name else f"{name!r}")
                    yield ctx.finding(
                        self.code, use,
                        f"use-after-donate: {what} was donated to "
                        f"{call.func.id}() on line {call.lineno} "
                        "(donate_argnums) and is read here — the "
                        "buffer may already be reused; rebind the "
                        "result over the donated name (carry rebind) "
                        "or drop the read")

    @staticmethod
    def _loop_between(ctx: FileContext, node: ast.AST, fn):
        for anc in ctx.ancestors(node):
            if anc is fn:
                return None
            if isinstance(anc, (ast.For, ast.While)):
                return anc
            if isinstance(anc, FunctionNode + (ast.Lambda,)):
                return None
        return None


# ----------------------------------------------------------------- XGT014
#: call terminal names that are side effects when traced: obs event /
#: metric emission, fault injection, console/file I/O.  ``jax.debug.*``
#: is the sanctioned escape hatch and is exempted by dotted prefix.
_IMPURE_TERMINALS = frozenset({
    "event", "_event", "emit", "span", "inject", "print", "open"})
#: host pulls: force a device sync (and break under trace)
_HOST_PULL_DOTTED = frozenset({"jax.device_get", "device_get"})
_NP_CAST_DOTTED = frozenset({"np.asarray", "np.array",
                             "numpy.asarray", "numpy.array"})


class ImpureTracedScope(Rule):
    """XGT014: a side effect inside a function that executes under a
    JAX trace (jit-decorated, passed to jit/shard_map/lax.scan, or
    nested in one).  Traced Python runs ONCE at trace time: an obs
    ``event()``/``span()``, ``faults.inject()``, ``time.*`` read,
    ``print``/``open``, or global/nonlocal mutation fires once per
    compile — not per execution — which is exactly the silent
    obs-vs-XLA divergence the ``XGBTPU_OBS_PHASES=0`` fallback existed
    to dodge; ``np.asarray`` on a traced value raises a
    TracerArrayConversionError at best.  Hoist the side effect to the
    host-side caller (the mock.collective replay in do_boost_fused is
    the worked example), or use ``jax.debug.*`` (exempt)."""

    code = "XGT014"
    name = "impure-traced-scope"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        traced = traced_functions(ctx)
        if not traced:
            return
        taint_cache: Dict[ast.AST, Set[str]] = {}
        for fn in traced:
            for node in FunctionFlow._walk_own(fn):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    kind = ("global" if isinstance(node, ast.Global)
                            else "nonlocal")
                    yield ctx.finding(
                        self.code, node,
                        f"{kind} mutation inside traced {fn.name}(): "
                        "runs once at trace time, not per execution — "
                        "thread state through the carry instead")
                if not isinstance(node, ast.Call):
                    continue
                msg = self._impure_call(ctx, fn, node, taint_cache)
                if msg:
                    yield ctx.finding(
                        self.code, node,
                        f"{msg} inside traced {fn.name}(): traced "
                        "Python runs once at trace time (or breaks the "
                        "trace) — hoist it to the host-side caller, or "
                        "route through jax.debug.* if it must observe "
                        "traced values")

    def _impure_call(self, ctx: FileContext, fn, node: ast.Call,
                     taint_cache: Dict[ast.AST, Set[str]]
                     ) -> Optional[str]:
        d = dotted_name(node.func)
        if d is not None and d.startswith("jax.debug."):
            return None
        t = terminal_name(node.func)
        if t in _IMPURE_TERMINALS:
            return f"side-effect call {t}()"
        if d is not None and d.startswith("time."):
            return f"wall-clock read {d}()"
        if d in _HOST_PULL_DOTTED:
            return f"host pull {d}()"
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "item" and not node.args):
            return "host pull .item()"
        if d in _NP_CAST_DOTTED and node.args:
            tainted = taint_cache.setdefault(fn, param_taint(fn))
            if any(isinstance(s, ast.Name) and s.id in tainted
                   and isinstance(s.ctx, ast.Load)
                   for s in ast.walk(node.args[0])):
                return f"numpy cast {d}() of a traced value"
        return None


# ----------------------------------------------------------------- XGT015
_COLLECTIVE_TERMINALS = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "psum_scatter", "axis_index"})
#: attribute reads of a traced name that are trace-STATIC (shape
#: metadata), so branching on them is fine
_STATIC_ATTRS = frozenset({"shape", "ndim", "size", "dtype"})
_STATIC_TEST_CALLS = frozenset({"isinstance", "len", "getattr",
                                "hasattr", "callable"})


def _axis_token(node: ast.AST, consts: Dict[str, str],
                params: Set[str]) -> Optional[str]:
    """Canonical token of an axis-name expression: a resolved string,
    ``$NAME`` for an unresolved (e.g. imported) constant, or None for
    a function parameter / unresolvable expression (config seams are
    skipped, not guessed)."""
    s = const_str(node)
    if s is not None:
        return s
    if isinstance(node, ast.Name):
        if node.id in params:
            return None
        if node.id in consts:
            return consts[node.id]
        return "$" + node.id
    return None


class CollectiveAxisDiscipline(Rule):
    """XGT015: dataflow-powered deepening of XGT007 for ``shard_map``
    programs.

    (a) axis match — a collective lexically inside a function passed
        to ``shard_map`` must name an axis the call site's
        ``P(...)``/``PartitionSpec(...)`` specs (or an in-file mesh
        construction) mention.  Names resolve through in-file
        constants (``DATA_AXIS = "data"``); imported axis constants
        match symbolically (the same NAME on both sides), so a psum
        over a renamed or misspelled axis is a finding while the
        repo's ``DATA_AXIS`` convention passes.
    (b) data-dependent branch — a collective under an ``if``/``while``
        whose test reads a param-tainted (traced) value dynamically:
        the branch is resolved ONCE at trace time, so ranks disagreeing
        at runtime would skip the collective and deadlock the mesh.
        ``is None`` tests, ``isinstance``, and ``.shape``/``.ndim``
        reads are trace-static and exempt.
    """

    code = "XGT015"
    name = "collective-axis-discipline"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        consts = {
            t.id: node.value.value
            for node in ctx.tree.body
            if isinstance(node, ast.Assign) and len(node.targets) == 1
            and isinstance((t := node.targets[0]), ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)}
        yield from self._check_axis_match(ctx, consts)
        yield from self._check_data_branches(ctx)

    # ------------------------------------------------- (a) axis match
    def _check_axis_match(self, ctx: FileContext,
                          consts: Dict[str, str]) -> Iterator[Finding]:
        by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, FunctionNode):
                by_name.setdefault(node.name, []).append(node)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "shard_map"
                    and node.args):
                continue
            inner = node.args[0]
            fns = (by_name.get(inner.id, ())
                   if isinstance(inner, ast.Name) else ())
            if not fns:
                continue
            axes = self._site_axes(ctx, node, consts)
            if not axes:
                continue
            for fn in fns:
                params = _param_names(fn) | {
                    a.arg for a in fn.args.kwonlyargs}
                for sub in ast.walk(fn):
                    if not (isinstance(sub, ast.Call) and
                            terminal_name(sub.func)
                            in _COLLECTIVE_TERMINALS):
                        continue
                    tok = self._collective_axis(sub, consts, params)
                    if tok is not None and tok not in axes:
                        pretty = tok.lstrip("$")
                        yield ctx.finding(
                            self.code, sub,
                            f"collective {terminal_name(sub.func)}() "
                            f"names axis {pretty!r}, but the enclosing "
                            "shard_map's specs/mesh mention only "
                            f"{sorted(a.lstrip('$') for a in axes)} — "
                            "a renamed or misspelled mesh axis fails "
                            "at trace time on device but passes "
                            "single-host tests")

    def _site_axes(self, ctx: FileContext, call: ast.Call,
                   consts: Dict[str, str]) -> Set[str]:
        """Axis tokens the shard_map call site declares: P()/
        PartitionSpec() arguments reachable from the call's specs
        (following simple local assignments like ``D = P(DATA_AXIS)``)
        plus axis names of in-file mesh constructions."""
        axes: Set[str] = set()
        scope = enclosing_function(ctx, call) or ctx.tree
        for node in ast.walk(scope):
            if (isinstance(node, ast.Call)
                    and terminal_name(node.func) in ("P", "PartitionSpec")):
                for arg in node.args:
                    tok = _axis_token(arg, consts, set())
                    if tok:
                        axes.add(tok)
            if (isinstance(node, ast.Call)
                    and terminal_name(node.func) in ("Mesh", "make_mesh",
                                                     "AbstractMesh")):
                for arg in list(node.args) + [
                        kw.value for kw in node.keywords]:
                    if isinstance(arg, (ast.Tuple, ast.List)):
                        for e in arg.elts:
                            tok = _axis_token(e, consts, set())
                            if tok:
                                axes.add(tok)
        return axes

    @staticmethod
    def _collective_axis(call: ast.Call, consts: Dict[str, str],
                         params: Set[str]) -> Optional[str]:
        axis_expr = None
        for kw in call.keywords:
            if kw.arg == "axis_name":
                axis_expr = kw.value
        if axis_expr is None and len(call.args) >= 2:
            axis_expr = call.args[1]
        if axis_expr is None:
            return None
        return _axis_token(axis_expr, consts, params)

    # ----------------------------------------- (b) data-dependent ifs
    def _check_data_branches(self, ctx: FileContext) -> Iterator[Finding]:
        traced = traced_functions(ctx)
        taint_cache: Dict[ast.AST, Set[str]] = {}
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and terminal_name(node.func)
                    in _COLLECTIVE_TERMINALS):
                continue
            fn = enclosing_function(ctx, node)
            if fn is None or fn not in traced:
                continue
            tainted = taint_cache.setdefault(fn, param_taint(fn))
            for anc in ctx.ancestors(node):
                if anc is fn or isinstance(anc, FunctionNode):
                    break
                if not isinstance(anc, (ast.If, ast.While, ast.IfExp)):
                    continue
                ref = self._dynamic_tainted_ref(ctx, anc.test, tainted)
                if ref:
                    yield ctx.finding(
                        self.code, node,
                        f"collective {terminal_name(node.func)}() "
                        "under a Python branch on traced value "
                        f"{ref!r}: the branch resolves once at trace "
                        "time — shards disagreeing at runtime would "
                        "skip the collective and deadlock; use "
                        "jnp.where / lax.cond, or branch on static "
                        "config")
                    break

    @staticmethod
    def _dynamic_tainted_ref(ctx: FileContext, test: ast.AST,
                             tainted: Set[str]) -> Optional[str]:
        for sub in ast.walk(test):
            if not (isinstance(sub, ast.Name) and sub.id in tainted
                    and isinstance(sub.ctx, ast.Load)):
                continue
            parent = ctx.parent(sub)
            if (isinstance(parent, ast.Attribute)
                    and parent.attr in _STATIC_ATTRS):
                continue
            if (isinstance(parent, ast.Call)
                    and terminal_name(parent.func) in _STATIC_TEST_CALLS):
                continue
            if (isinstance(parent, ast.Compare)
                    and all(isinstance(op, (ast.Is, ast.IsNot))
                            for op in parent.ops)):
                continue
            return sub.id
        return None

"""The xgtpu-lint rule catalog (ANALYSIS.md has rationale + fix
recipes per rule).

Each rule encodes one invariant the codebase established in an earlier
PR and that no generic tool checks:

  XGT001  recompile hazards around ``jax.jit``
  XGT002  host<->device synchronization inside hot training loops
  XGT003  durable writes bypassing ``reliability.integrity.atomic_write``
  XGT004  broad exception handlers that swallow errors silently
  XGT005  mutation of lock-guarded attributes outside the lock
  XGT006  wall-clock ``time.time()`` used to measure durations
  XGT007  collectives under rank-dependent control flow

The v3 dataflow-aware rules XGT013 (use-after-donate), XGT014 (impure
traced scope) and XGT015 (collective axis discipline) live in
:mod:`xgboost_tpu.analysis.dataflow` — they need a def-use view of a
whole function, not one node — and are registered in ``_ALL_RULES``
here so the CLI treats them like any per-file rule.

The cross-file contract rules XGT008-XGT012, XGT016 (exit-code
registry) and XGT017 (obs event-name drift) live in
:mod:`xgboost_tpu.analysis.contracts` — they need whole-repo facts, not
one file's AST.

Rules are heuristic by design: they aim at THIS tree's hazards, with
inline ``# xgtpu: disable=`` suppressions (plus the committed baseline)
as the escape hatch for intentional sites.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Iterator, List, Optional, Sequence, Set

from xgboost_tpu.analysis.core import (FileContext, Finding, const_str,
                                       dotted_name, terminal_name)


class Rule:
    """One lint rule: a code, a short name, and a ``check`` generator."""

    code = "XGT000"
    name = "base"

    def applies(self, path: str) -> bool:
        return True

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


def _path_has(path: str, needles: Sequence[str]) -> bool:
    return any(n in path for n in needles)


# ---------------------------------------------------------------- XGT001
def _is_jit_target(node: ast.AST) -> bool:
    """``jax.jit`` / bare ``jit`` (the only way it is imported here)."""
    return (dotted_name(node) in ("jax.jit", "jit")
            or (isinstance(node, ast.Attribute) and node.attr == "jit"))


def _static_argnames(call: ast.Call) -> Set[str]:
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if const_str(v):
                names.add(const_str(v))
            elif isinstance(v, (ast.Tuple, ast.List)):
                for elt in v.elts:
                    s = const_str(elt)
                    if s:
                        names.add(s)
    return names


def _jit_decoration(fn: ast.FunctionDef) -> Optional[Set[str]]:
    """Static argnames when ``fn`` is jit-decorated (directly or via
    ``functools.partial(jax.jit, ...)``), else None."""
    for dec in fn.decorator_list:
        if _is_jit_target(dec):
            return set()
        if isinstance(dec, ast.Call):
            if _is_jit_target(dec.func):
                return _static_argnames(dec)
            if (terminal_name(dec.func) == "partial" and dec.args
                    and _is_jit_target(dec.args[0])):
                return _static_argnames(dec)
    return None


class RecompileHazards(Rule):
    """XGT001: patterns that retrace/recompile per call or per value.

    (a) a ``jax.jit`` wrapper constructed inside a loop — a fresh
        wrapper per iteration; for lambdas/closures a fresh cache key,
        i.e. a recompile every iteration;
    (b) ``jax.jit(f)(...)`` built and invoked in one expression inside a
        function body — re-wrapped on every execution of that line;
    (c) Python ``if``/``while`` branching on a NON-static parameter's
        shape inside a jitted function — every distinct shape traces a
        new program (pad to a bucket, or make the argument static);
    (d) a jitted callable fed a loop-varying slice (``f(x[:i])``) —
        one compile per distinct length.
    """

    code = "XGT001"
    name = "recompile-hazard"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        jitted_names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.FunctionDef)
                    and _jit_decoration(node) is not None):
                jitted_names.add(node.name)
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _is_jit_target(node.value.func)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jitted_names.add(t.id)

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _is_jit_target(node.func):
                if ctx.enclosing_loop(node) is not None:
                    yield ctx.finding(
                        self.code, node,
                        "jax.jit wrapper constructed inside a loop: a "
                        "fresh wrapper (and for lambdas a fresh compile-"
                        "cache key) per iteration — hoist the jitted "
                        "callable out of the loop")
                elif (isinstance(ctx.parent(node), ast.Call)
                      and ctx.parent(node).func is node
                      and node.args
                      and isinstance(node.args[0], ast.Lambda)):
                    yield ctx.finding(
                        self.code, node,
                        "jax.jit(lambda...)(args) built and invoked in "
                        "one expression: the wrapper (and its compile "
                        "cache entry) is rebuilt on every execution — "
                        "bind the jitted function once at module/init "
                        "scope")
            if isinstance(node, ast.FunctionDef):
                statics = _jit_decoration(node)
                if statics is not None:
                    yield from self._shape_branches(ctx, node, statics)
            if isinstance(node, ast.Call):
                fname = terminal_name(node.func)
                if fname in jitted_names:
                    yield from self._loop_varying_args(ctx, node)

    def _shape_branches(self, ctx: FileContext, fn: ast.FunctionDef,
                        statics: Set[str]) -> Iterator[Finding]:
        params = {a.arg for a in (fn.args.args + fn.args.kwonlyargs
                                  + fn.args.posonlyargs)} - statics
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for sub in ast.walk(node.test):
                hit = None
                if (isinstance(sub, ast.Attribute)
                        and sub.attr in ("shape", "ndim", "size")
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id in params):
                    hit = f"{sub.value.id}.{sub.attr}"
                elif (isinstance(sub, ast.Call)
                      and terminal_name(sub.func) == "len"
                      and sub.args
                      and isinstance(sub.args[0], ast.Name)
                      and sub.args[0].id in params):
                    hit = f"len({sub.args[0].id})"
                if hit:
                    yield ctx.finding(
                        self.code, node,
                        f"shape-dependent Python branch on {hit} inside "
                        f"jitted {fn.name}(): each distinct shape traces "
                        "a new program — pad to a fixed bucket or mark "
                        "the argument in static_argnames")
                    break

    def _loop_varying_args(self, ctx: FileContext,
                           call: ast.Call) -> Iterator[Finding]:
        loop = ctx.enclosing_loop(call)
        if not isinstance(loop, ast.For):
            return
        loop_vars = {n.id for n in ast.walk(loop.target)
                     if isinstance(n, ast.Name)}
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for sub in ast.walk(arg):
                if not (isinstance(sub, ast.Subscript)
                        and isinstance(sub.slice, ast.Slice)):
                    continue
                bounds = [b for b in (sub.slice.lower, sub.slice.upper,
                                      sub.slice.step) if b is not None]
                if any(isinstance(n, ast.Name) and n.id in loop_vars
                       for b in bounds for n in ast.walk(b)):
                    yield ctx.finding(
                        self.code, call,
                        "jitted function called with a loop-varying "
                        "slice: one compile per distinct length — pad "
                        "to a fixed shape (or lift the loop into the "
                        "jitted program)")
                    return


# ---------------------------------------------------------------- XGT002
class HostSyncInHotLoop(Rule):
    """XGT002: host<->device synchronization inside the per-round /
    per-node loops of the training hot path.  Each ``.item()`` /
    ``np.asarray`` / ``device_get`` on a device value forces a blocking
    transfer per iteration, serializing the device pipeline (the exact
    cost class arXiv:1806.11248 §4 removes from the GPU hist method).
    Scoped to the hot-path files — including the serving engine, whose
    warmup/chunking loops sit on the request path; cold paths
    (save/load, dump) live elsewhere or use comprehensions, which are
    not flagged.
    """

    code = "XGT002"
    name = "host-sync-in-hot-loop"

    HOT_PATHS = ("models/gbtree.py", "models/updaters.py", "ops/",
                 "serving/engine.py", "serving/featurestore.py",
                 "fleet/", "pipeline/", "catalog/", "stream/",
                 "placer/")

    def applies(self, path: str) -> bool:
        return _path_has(path, self.HOT_PATHS)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_loop(node) is None:
                continue
            msg = self._sync_call(node)
            if msg:
                yield ctx.finding(
                    self.code, node,
                    f"{msg} inside a hot-path loop forces a host<->"
                    "device sync per iteration — batch the transfer "
                    "outside the loop or keep the value on device")

    @staticmethod
    def _sync_call(node: ast.Call) -> Optional[str]:
        d = dotted_name(node.func)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "item" and not node.args):
            return ".item()"
        if d in ("np.asarray", "np.array", "numpy.asarray", "numpy.array"):
            # converting a literal list/tuple/comprehension is pure
            # host work, not a device pull
            if node.args and not isinstance(
                    node.args[0], (ast.List, ast.Tuple, ast.ListComp,
                                   ast.GeneratorExp, ast.Constant)):
                return d + "()"
            return None
        if d in ("jax.device_get", "device_get"):
            return d + "()"
        if (isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int") and node.args
                and isinstance(node.args[0], ast.Subscript)):
            return f"{node.func.id}(array[...])"
        return None


# ---------------------------------------------------------------- XGT003
_WRITE_MODE = frozenset("wx")


def _mode_writes(mode: Optional[str]) -> bool:
    return bool(mode) and any(c in _WRITE_MODE for c in mode)


class NonAtomicPersistence(Rule):
    """XGT003: durable files written with plain ``open(..., 'w')`` (or a
    kept ``NamedTemporaryFile``): a crash mid-write leaves a torn
    prefix where ``reliability.integrity.atomic_write`` would leave
    old-or-new.  Append mode is exempt (the event log's contract: a
    crash tears at most the final line, never the file)."""

    code = "XGT003"
    name = "non-atomic-persistence"

    EXEMPT_FILES = ("reliability/integrity.py",)  # the implementation
    _MODE_RE = re.compile(r"[rwxab+tU]{1,4}\Z")

    def applies(self, path: str) -> bool:
        return not _path_has(path, self.EXEMPT_FILES)

    @classmethod
    def _open_mode(cls, node: ast.Call) -> Optional[str]:
        """The constant mode of an ``open``-named call, wherever the
        calling convention puts it: builtin/``io.open``/``gzip.open``
        take it as the 2nd positional, ``Path.open``/``fsspec.open``
        as the 1st — so scan the first two positionals for a
        mode-SHAPED constant string (a path literal like ``"out.txt"``
        never matches the mode charset), plus the ``mode=`` keyword."""
        for kw in node.keywords:
            if kw.arg == "mode":
                return const_str(kw.value)
        for arg in node.args[:2]:
            s = const_str(arg)
            if s is not None and cls._MODE_RE.match(s):
                return s
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = terminal_name(node.func)
            if fname == "open":
                mode = self._open_mode(node)
                if _mode_writes(mode):
                    yield ctx.finding(
                        self.code, node,
                        f"open(..., {mode!r}) writes the destination in "
                        "place — a crash mid-write leaves a torn file; "
                        "route through reliability.integrity."
                        "atomic_write (tmp+rename)")
            elif fname == "NamedTemporaryFile":
                mode = const_str(node.args[0]) if node.args else "w+b"
                delete = True
                for kw in node.keywords:
                    if kw.arg == "mode":
                        mode = const_str(kw.value)
                    if (kw.arg == "delete"
                            and isinstance(kw.value, ast.Constant)):
                        delete = bool(kw.value.value)
                if _mode_writes(mode) and not delete:
                    yield ctx.finding(
                        self.code, node,
                        "NamedTemporaryFile(delete=False) persists a "
                        "file without the tmp+rename discipline — write "
                        "the final path via reliability.integrity."
                        "atomic_write instead")


# ---------------------------------------------------------------- XGT004
_BROAD_EXC = ("Exception", "BaseException")
#: a call to any of these inside a handler counts as surfacing the error
_SURFACE_CALLS = frozenset({
    "print", "print_exc", "format_exc", "warn", "warning", "error",
    "exception", "critical", "log", "debug", "info", "fail", "event",
    "emit", "inc", "observe", "swallowed_error", "perror"})


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        tn = terminal_name(n)
        if tn in _BROAD_EXC:
            return True
    return False


class SwallowedException(Rule):
    """XGT004: a broad ``except`` whose handler neither re-raises, nor
    references the exception, nor calls anything that surfaces it (log/
    print/obs event/metric inc) — the error vanishes.  Fix recipe:
    ``obs.swallowed_error(site, exc)`` (counted on
    ``xgbtpu_swallowed_errors_total{site=...}`` + a throttled obs
    event), or narrow the except, or re-raise typed."""

    code = "XGT004"
    name = "swallowed-exception"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if self._surfaces(node):
                continue
            yield ctx.finding(
                self.code, node,
                "broad except swallows the error with no re-raise, log, "
                "obs event, or metric — call obs.swallowed_error(site, "
                "exc) (or narrow/re-raise) so failures stay countable")

    @staticmethod
    def _surfaces(handler: ast.ExceptHandler) -> bool:
        bound = handler.name
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                tn = terminal_name(node.func)
                if tn in _SURFACE_CALLS:
                    return True
                if tn and any(s in tn.lower()
                              for s in ("log", "warn", "error", "swallow")):
                    return True
            if (bound and isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id == bound):
                return True
        return False


# ---------------------------------------------------------------- XGT005
def _with_lock_attrs(node: ast.With) -> List[str]:
    """Lock attribute names entered by a ``with`` statement
    (``with self._lock:`` -> ['_lock'])."""
    out = []
    for item in node.items:
        e = item.context_expr
        if (isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Name)
                and e.value.id == "self"):
            out.append(e.attr)
    return out


class LockDiscipline(Rule):
    """XGT005: an attribute that is elsewhere mutated under ``with
    self.<lock>:`` is mutated here with NO lock held — a data race once
    two threads touch the object.  Analysis is per class: ``__init__``
    (single-threaded construction) and ``*_locked`` helper methods
    (called with the lock held, by convention) are exempt."""

    code = "XGT005"
    name = "lock-discipline"

    EXEMPT_METHODS = ("__init__", "__new__", "__del__")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    def _self_attr_writes(self, stmt: ast.AST) -> Iterable[str]:
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                if (isinstance(e, ast.Attribute)
                        and isinstance(e.value, ast.Name)
                        and e.value.id == "self"):
                    yield e.attr

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        lock_names: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.With):
                for attr in _with_lock_attrs(node):
                    if "lock" in attr.lower():
                        lock_names.add(attr)
        if not lock_names:
            return

        def under_lock(node: ast.AST) -> bool:
            for anc in ctx.ancestors(node):
                if isinstance(anc, ast.With) and any(
                        a in lock_names for a in _with_lock_attrs(anc)):
                    return True
                if anc is cls:
                    return False
            return False

        def method_of(node: ast.AST) -> Optional[ast.FunctionDef]:
            fn = None
            for anc in ctx.ancestors(node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = anc
                if anc is cls:
                    return fn
            return None

        guarded: Set[str] = set()
        writes: List = []  # (attr, stmt)
        for node in ast.walk(cls):
            for attr in self._self_attr_writes(node):
                if attr in lock_names:
                    continue
                m = method_of(node)
                if m is None or m.name in self.EXEMPT_METHODS:
                    continue
                if under_lock(node):
                    guarded.add(attr)
                elif not m.name.endswith("_locked"):
                    writes.append((attr, node))
        for attr, stmt in writes:
            if attr in guarded:
                yield ctx.finding(
                    self.code, stmt,
                    f"self.{attr} is mutated under a lock elsewhere in "
                    f"{cls.name} but written here with no lock held — "
                    "wrap in the guarding `with self.<lock>:` (or name "
                    "the method *_locked if the caller holds it)")


# ---------------------------------------------------------------- XGT006
class WallClockDuration(Rule):
    """XGT006: a duration measured as a difference of wall-clock
    ``time.time()`` readings — NTP steps/slews make it lie (negative or
    inflated).  Use ``time.perf_counter()`` for durations; wall-clock
    stays correct for event-log TIMESTAMPS (never flagged: only
    subtractions are)."""

    code = "XGT006"
    name = "wallclock-duration"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            for side in (node.left, node.right):
                if (isinstance(side, ast.Call)
                        and dotted_name(side.func) == "time.time"):
                    yield ctx.finding(
                        self.code, node,
                        "duration measured with wall-clock time.time() "
                        "— an NTP step mid-measurement skews it; use "
                        "time.perf_counter() (keep time.time() only for "
                        "event timestamps)")
                    break


# ---------------------------------------------------------------- XGT007
_COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "reduce_scatter", "broadcast_one_to_all", "allreduce",
    "allgather", "allgatherv", "allsum", "collective",
    "process_allgather"})


class CollectiveUnderRankBranch(Rule):
    """XGT007: a collective executed under control flow whose condition
    differs across ranks (``if rank == 0: psum(...)``) — the other
    ranks never enter the collective and the mesh deadlocks (or
    silently diverges).  Every rank must execute the same collective
    sequence; branch on rank AROUND the data, not around the
    collective."""

    code = "XGT007"
    name = "collective-under-rank-branch"

    # learner.py joined the scope with the mesh-fused scan driver: its
    # update_many/_eval_parts_sharded paths issue allsum/allgatherv
    # collectives that every rank must reach
    SCOPED_PATHS = ("parallel/", "cli.py", "models/gbtree.py",
                    "obs/comm.py", "learner.py")

    def applies(self, path: str) -> bool:
        return _path_has(path, self.SCOPED_PATHS)

    @staticmethod
    def _rank_dependent(test: ast.AST) -> bool:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Name) and sub.id == "rank":
                return True
            if isinstance(sub, ast.Attribute) and sub.attr in (
                    "rank", "process_index"):
                return True
            if (isinstance(sub, ast.Call)
                    and terminal_name(sub.func) == "process_index"):
                return True
        return False

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node.func) not in _COLLECTIVES:
                continue
            for anc in ctx.ancestors(node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break
                test = None
                if isinstance(anc, (ast.If, ast.While)):
                    test = anc.test
                elif isinstance(anc, ast.IfExp):
                    test = anc.test
                if test is not None and self._rank_dependent(test):
                    yield ctx.finding(
                        self.code, node,
                        f"collective {terminal_name(node.func)}() under "
                        "rank-dependent control flow: ranks that skip "
                        "the branch never join the collective — "
                        "deadlock/divergence; run the collective on "
                        "every rank and branch on the data instead")
                    break


# the v3 dataflow-aware rules live in their own module (they share the
# def-use/traced-scope layer); imported here, at the bottom, so the
# registry stays the single source of truth without an import cycle
from xgboost_tpu.analysis.dataflow import (CollectiveAxisDiscipline,  # noqa: E402
                                           ImpureTracedScope,
                                           UseAfterDonate)

_ALL_RULES = (RecompileHazards, HostSyncInHotLoop, NonAtomicPersistence,
              SwallowedException, LockDiscipline, WallClockDuration,
              CollectiveUnderRankBranch, UseAfterDonate,
              ImpureTracedScope, CollectiveAxisDiscipline)


def all_rules() -> List[Rule]:
    """Fresh instances of every registered rule, in code order."""
    return [cls() for cls in _ALL_RULES]


def rules_by_code(codes: Iterable[str]) -> List[Rule]:
    wanted = {c.strip().upper() for c in codes}
    out = [cls() for cls in _ALL_RULES if cls.code in wanted]
    unknown = wanted - {cls.code for cls in _ALL_RULES}
    if unknown:
        raise ValueError(f"unknown rule code(s): {sorted(unknown)}")
    return out

"""Per-feature distribution drift tracking over the streaming pipeline.

Built entirely on the weighted quantile sketch (``sketch.py`` — the
reference's WQSummary semantics): each micro-cycle's batches collapse
into one bounded :class:`~xgboost_tpu.sketch.QuantileSummary` per
feature, a sliding window of the last ``window`` cycles forms the
"current" distribution, and a reference distribution (rebased at every
cut refresh) anchors the comparison.  The drift score is PSI
(population stability index) over bucket edges drawn from the
REFERENCE summary's quantiles — the classic monitoring statistic,
computed here from sketch rank interpolation instead of raw rows, so
the tracker's memory is O(features × summary_size) no matter how much
data streams past.

Hysteresis: the tracker *fires* when any feature's PSI crosses
``threshold`` and stays fired until every feature drops below
``clear`` — a score oscillating around the threshold triggers ONE cut
refresh, not one per cycle (tests/test_stream_drift.py pins this).

Determinism: the whole tracker state round-trips through
:meth:`FeatureDriftTracker.to_arrays` / :meth:`from_arrays` (plain
numpy arrays, persisted by the stream trainer's per-cycle plan files),
so a trainer SIGKILLed mid-cycle rebuilds the identical tracker and
makes the identical refresh decision on resume.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from xgboost_tpu.sketch import (QuantileSummary, empty_summary,
                                make_summary, merge_summaries,
                                propose_cuts, prune_summary)

# PSI bucket proportions are clamped away from zero before the log —
# an empty bucket is strong evidence, not an infinity
_PSI_EPS = 1e-4


def summarize_columns(X: np.ndarray, max_size: int = 256
                      ) -> List[QuantileSummary]:
    """One pruned summary per column of a raw (N, F) batch
    (NaN = missing, excluded by ``make_summary``)."""
    X = np.asarray(X)
    return [prune_summary(make_summary(X[:, f]), max_size)
            for f in range(X.shape[1])]


def merge_column_summaries(a: Sequence[QuantileSummary],
                           b: Sequence[QuantileSummary],
                           max_size: int = 256) -> List[QuantileSummary]:
    """Element-wise merge+prune of two per-feature summary lists."""
    if not a:
        return list(b)
    if not b:
        return list(a)
    return [prune_summary(merge_summaries(x, y), max_size)
            for x, y in zip(a, b)]


def summary_cdf(s: QuantileSummary, v: np.ndarray) -> np.ndarray:
    """Approximate CDF of a summary at values ``v`` via mid-rank
    interpolation (monotone; exact at summary entries up to the
    summary's own rank-error bound)."""
    v = np.asarray(v, dtype=np.float64)
    if s.size == 0 or s.total_weight <= 0:
        return np.zeros_like(v)
    mid = (s.rmin + s.rmax) * 0.5
    return np.interp(v, s.value, mid) / s.total_weight


def psi_score(ref: QuantileSummary, cur: QuantileSummary,
              n_edges: int = 10) -> float:
    """PSI of ``cur`` against ``ref`` over ``n_edges`` equal-rank
    buckets of the reference distribution.  0 = identical; common
    monitoring folklore reads >0.1 as shifting, >0.25 as shifted."""
    if ref.size == 0 or cur.size == 0:
        return 0.0
    qs = np.arange(1, n_edges) / n_edges
    mid = (ref.rmin + ref.rmax) * 0.5
    edges = np.interp(qs * ref.total_weight, mid, ref.value)
    edges = np.unique(edges)
    if edges.size == 0:
        return 0.0
    p_ref = np.diff(np.concatenate([[0.0], summary_cdf(ref, edges), [1.0]]))
    p_cur = np.diff(np.concatenate([[0.0], summary_cdf(cur, edges), [1.0]]))
    p_ref = np.clip(p_ref, _PSI_EPS, None)
    p_cur = np.clip(p_cur, _PSI_EPS, None)
    p_ref = p_ref / p_ref.sum()
    p_cur = p_cur / p_cur.sum()
    return float(np.sum((p_cur - p_ref) * np.log(p_cur / p_ref)))


class FeatureDriftTracker:
    """Sliding-window per-feature drift scores with fire/clear
    hysteresis and a running reference sketch for cut proposal."""

    def __init__(self, n_features: int, window: int = 4,
                 threshold: float = 0.25, clear: float = 0.1,
                 n_edges: int = 10, max_size: int = 256):
        self.n_features = int(n_features)
        self.window = max(1, int(window))
        self.threshold = float(threshold)
        self.clear = float(clear)
        self.n_edges = int(n_edges)
        self.max_size = int(max_size)
        self.reference: List[QuantileSummary] = [
            empty_summary() for _ in range(self.n_features)]
        # newest-last per-cycle summaries, at most `window` entries
        self.recent: List[List[QuantileSummary]] = []
        self.fired = False

    # ----------------------------------------------------------- observe
    def observe_cycle(self, col_summaries: Sequence[QuantileSummary]
                      ) -> None:
        """Fold one micro-cycle's per-feature summaries into the
        sliding window (and, while the reference is still empty —
        before the first rebase — into the reference too, so cycle 0
        scores ≈ 0 against itself instead of against nothing)."""
        if len(col_summaries) != self.n_features:
            raise ValueError(
                f"expected {self.n_features} feature summaries, "
                f"got {len(col_summaries)}")
        self.recent.append(list(col_summaries))
        if len(self.recent) > self.window:
            self.recent.pop(0)
        if all(s.size == 0 for s in self.reference):
            self.reference = merge_column_summaries(
                self.reference, col_summaries, self.max_size)

    def current(self) -> List[QuantileSummary]:
        """The sliding window merged into one summary per feature."""
        acc: List[QuantileSummary] = [
            empty_summary() for _ in range(self.n_features)]
        for cycle in self.recent:
            acc = merge_column_summaries(acc, cycle, self.max_size)
        return acc

    # ------------------------------------------------------------ scores
    def scores(self) -> np.ndarray:
        """(F,) PSI of the current window against the reference."""
        cur = self.current()
        return np.asarray(
            [psi_score(self.reference[f], cur[f], self.n_edges)
             for f in range(self.n_features)], dtype=np.float64)

    def step(self) -> dict:
        """Score + hysteresis update for the cycle just observed.
        Returns ``{scores, max_score, fired, refresh}`` where
        ``refresh`` is True exactly on the not-fired -> fired edge —
        the one moment a cut refresh should run."""
        scores = self.scores()
        mx = float(scores.max()) if scores.size else 0.0
        refresh = False
        if not self.fired and mx >= self.threshold:
            self.fired = True
            refresh = True
        elif self.fired and mx < self.clear:
            self.fired = False
        return {"scores": scores, "max_score": mx,
                "fired": self.fired, "refresh": refresh}

    def rebase(self) -> None:
        """Adopt the current window as the new reference — called after
        a cut refresh so the next drift episode measures against the
        distribution the refreshed cuts were built from."""
        self.reference = self.current()

    # ------------------------------------------------------ persistence
    def to_arrays(self) -> Dict[str, np.ndarray]:
        """Flatten the full tracker state to plain arrays (npz-able)."""
        out: Dict[str, np.ndarray] = {
            "meta": np.asarray([self.n_features, self.window,
                                self.n_edges, self.max_size,
                                int(self.fired), len(self.recent)],
                               dtype=np.int64),
            "thresholds": np.asarray([self.threshold, self.clear],
                                     dtype=np.float64),
        }

        def put(prefix: str, s: QuantileSummary, f: int) -> None:
            out[f"{prefix}{f}_v"] = s.value
            out[f"{prefix}{f}_rmin"] = s.rmin
            out[f"{prefix}{f}_rmax"] = s.rmax
            out[f"{prefix}{f}_wmin"] = s.wmin

        for f, s in enumerate(self.reference):
            put("ref", s, f)
        for j, cycle in enumerate(self.recent):
            for f, s in enumerate(cycle):
                put(f"w{j}_", s, f)
        return out

    @classmethod
    def from_arrays(cls, arrays: Dict[str, np.ndarray]
                    ) -> "FeatureDriftTracker":
        meta = np.asarray(arrays["meta"])
        thr = np.asarray(arrays["thresholds"])
        self = cls(int(meta[0]), window=int(meta[1]),
                   threshold=float(thr[0]), clear=float(thr[1]),
                   n_edges=int(meta[2]), max_size=int(meta[3]))
        self.fired = bool(meta[4])

        def get(prefix: str, f: int) -> QuantileSummary:
            return QuantileSummary(
                np.asarray(arrays[f"{prefix}{f}_v"], np.float64),
                np.asarray(arrays[f"{prefix}{f}_rmin"], np.float64),
                np.asarray(arrays[f"{prefix}{f}_rmax"], np.float64),
                np.asarray(arrays[f"{prefix}{f}_wmin"], np.float64))

        self.reference = [get("ref", f) for f in range(self.n_features)]
        self.recent = [[get(f"w{j}_", f) for f in range(self.n_features)]
                       for j in range(int(meta[5]))]
        return self


def propose_refreshed_cuts(summaries: Sequence[QuantileSummary],
                           live_thresholds: Sequence[np.ndarray],
                           max_bin: int):
    """New :class:`~xgboost_tpu.binning.CutMatrix` for an online cut
    refresh: per feature, the sketch proposal over the CURRENT
    distribution, unioned with every raw threshold live in the
    incumbent's trees.  The union makes the swap EXACT — every live
    split's "v < threshold" boundary survives as a cut, so
    ``GBTree.rebind_cuts`` remaps old trees without moving a single
    decision boundary (bit-parity pinned in tests/test_stream.py).
    The union at most doubles a feature's cut row (live thresholds are
    a subset of the OLD row, which was itself ``max_bin``-bounded)."""
    from xgboost_tpu.binning import pack_cuts
    per_feature = []
    for f, s in enumerate(summaries):
        cuts = propose_cuts(s, max_bin - 1)  # leave room for missing bin
        thr = (np.asarray(live_thresholds[f], np.float32)  # xgtpu: disable=XGT002 — host arrays, once per cut refresh
               if f < len(live_thresholds) else np.zeros(0, np.float32))
        per_feature.append(np.unique(np.concatenate(
            [cuts.astype(np.float32), thr])))
    return pack_cuts(per_feature)


def live_thresholds_of(gbtree, n_features: int) -> List[np.ndarray]:
    """Per-feature raw split thresholds live in an ensemble (the values
    a cut refresh must preserve).  Empty lists for an empty model."""
    acc: List[list] = [[] for _ in range(n_features)]
    if gbtree is not None:
        for t in gbtree.trees:
            f = np.asarray(t.feature)  # xgtpu: disable=XGT002 — tiny per-tree pulls, once per cut refresh
            thr = np.asarray(t.threshold)  # xgtpu: disable=XGT002 — tiny per-tree pulls, once per cut refresh
            m = (f >= 0) & (f < n_features)
            for fi, tv in zip(f[m], thr[m]):
                acc[int(fi)].append(np.float32(tv))
    return [np.unique(np.asarray(a, np.float32)) for a in acc]

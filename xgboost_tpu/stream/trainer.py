"""StreamTrainer: drift-aware micro-cycles over the pipeline loop.

A :class:`~xgboost_tpu.pipeline.trainer.ContinuousTrainer` subclass
that plugs the streaming subsystem into the ``_prepare_booster`` seam:
before every cycle's first boosted round it (1) folds the cycle's raw
batches into the per-feature drift sketch, (2) on a drift *fire* edge
rebuilds the quantile cuts online (sketch proposal ∪ live thresholds —
``GBTree.rebind_cuts`` remaps the incumbent exactly, no decision
boundary moves), and (3) refreshes the EMA-gain feature screen that
``ema_fs=`` uses to shrink the histogram working set.

Crash discipline mirrors the base trainer: the per-cycle drift
decision is committed to a **plan file** (``plans/plan-NNNNNN.json``,
written atomically AFTER its sketch/cuts artifacts) before any of it
is applied to the booster.  A trainer SIGKILLed anywhere in the cycle
re-enters ``_prepare_booster`` on resume, finds the plan, and replays
the identical decision — the drift tracker is never re-advanced for a
cycle that already has a plan, so ring resumes stay bit-identical.
"""

from __future__ import annotations

import io
import json
import os
import time
from typing import List, Optional

import numpy as np

from xgboost_tpu.binning import CutMatrix
from xgboost_tpu.obs.metrics import stream_metrics
from xgboost_tpu.pipeline.trainer import ContinuousTrainer
from xgboost_tpu.stream.drift import (FeatureDriftTracker,
                                      live_thresholds_of,
                                      propose_refreshed_cuts,
                                      summarize_columns)

_PLAN_FMT = "plan-%06d.json"
_SKETCH_FMT = "sketch-%06d.npz"
_CUTS_FMT = "cuts-%06d.npz"


def _save_npz(path: str, arrays: dict) -> None:
    from xgboost_tpu.reliability.integrity import atomic_write
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    atomic_write(path, buf.getvalue())


class StreamTrainer(ContinuousTrainer):
    """Continuous trainer with per-cycle drift tracking, online cut
    refresh, and EMA-gain feature screening."""

    def __init__(self, *args, drift_threshold: float = 0.25,
                 drift_clear: float = 0.1, drift_window: int = 4,
                 sketch_size: int = 256, **kw):
        super().__init__(*args, **kw)
        self.drift_threshold = float(drift_threshold)
        self.drift_clear = float(drift_clear)
        self.drift_window = max(1, int(drift_window))
        self.sketch_size = max(16, int(sketch_size))
        self.plans_dir = os.path.join(self.workdir, "plans")
        os.makedirs(self.plans_dir, exist_ok=True)
        self.stream_metrics = stream_metrics()

    # ------------------------------------------------------------- plans
    def _plan_path(self, cycle: int) -> str:
        return os.path.join(self.plans_dir, _PLAN_FMT % cycle)

    def _sketch_path(self, cycle: int) -> str:
        return os.path.join(self.plans_dir, _SKETCH_FMT % cycle)

    def _cuts_path(self, cycle: int) -> str:
        return os.path.join(self.plans_dir, _CUTS_FMT % cycle)

    def _read_plan(self, cycle: int) -> Optional[dict]:
        try:
            with open(self._plan_path(cycle), encoding="utf-8") as f:
                p = json.load(f)
            return p if isinstance(p, dict) else None
        except (OSError, ValueError):
            return None

    def _load_tracker(self, cycle: int, n_features: int):
        """The tracker + EMA state as of the END of the previous cycle
        (cycle 0 or missing artifacts start fresh)."""
        ema = np.zeros(n_features, np.float64)
        ntrees = 0
        path = self._sketch_path(cycle - 1)
        if cycle > 0 and os.path.exists(path):
            with np.load(path, allow_pickle=False) as z:
                arrays = {k: z[k] for k in z.files}
            tracker = FeatureDriftTracker.from_arrays(arrays)
            if "ema" in arrays and arrays["ema"].shape[0] == n_features:
                ema = np.asarray(arrays["ema"], np.float64)
            if "ntrees" in arrays:
                ntrees = int(arrays["ntrees"])
        else:
            tracker = FeatureDriftTracker(
                n_features, window=self.drift_window,
                threshold=self.drift_threshold, clear=self.drift_clear,
                max_size=self.sketch_size)
        return tracker, ema, ntrees

    # --------------------------------------------------------------- EMA
    def _ema_update(self, bst, ema: np.ndarray, prev_ntrees: int
                    ) -> tuple:
        """Fold the gain mass of the trees appended since the previous
        cycle into the per-feature EMA.  Returns (ema, ntrees_now)."""
        decay = float(self.params.get("ema_fs_decay", 0.9))
        trees = bst.gbtree.trees if bst.gbtree is not None else []
        n_features = ema.shape[0]
        if len(trees) > prev_ntrees:
            g = np.zeros(n_features, np.float64)
            for t in trees[prev_ntrees:]:
                f = np.asarray(t.feature)  # xgtpu: disable=XGT002 — tiny per-tree pulls, once per cycle
                gain = np.asarray(t.gain, np.float64)  # xgtpu: disable=XGT002 — tiny per-tree pulls, once per cycle
                m = (f >= 0) & (f < n_features)
                np.add.at(g, f[m], gain[m])
            total = g.sum()
            share = g / total if total > 0 else g
            ema = decay * ema + (1.0 - decay) * share
        return ema, len(trees)

    def _screen_of(self, ema: np.ndarray) -> Optional[List[int]]:
        """Smallest EMA-descending feature prefix covering ``ema_fs``
        of the gain mass (floored at ``ema_fs_min_features``), or None
        to keep every feature."""
        frac = float(self.params.get("ema_fs", 0.0))
        if frac <= 0 or frac >= 1.0:
            return None
        total = float(ema.sum())
        if total <= 0:
            return None  # no gain signal yet: screen nothing
        order = np.argsort(-ema, kind="stable")
        csum = np.cumsum(ema[order]) / total
        n_keep = int(np.searchsorted(csum, frac) + 1)
        n_keep = max(n_keep,
                     int(self.params.get("ema_fs_min_features", 8)))
        if n_keep >= ema.shape[0]:
            return None
        return sorted(int(i) for i in order[:n_keep])

    # ------------------------------------------------------------ prepare
    def _prepare_booster(self, bst, cycle: int) -> None:
        plan = self._read_plan(cycle)
        if plan is None:
            plan = self._compose_plan(bst, cycle)
        self._apply_plan(bst, plan)

    def _compose_plan(self, bst, cycle: int) -> dict:
        """Advance the drift tracker over cycle ``cycle``'s batches and
        commit the resulting decision.  Runs at most once per cycle —
        resumes replay the committed plan instead."""
        X, _ = self.source.read_cycle_arrays(cycle)
        n_features = int(X.shape[1])
        tracker, ema, prev_ntrees = self._load_tracker(cycle, n_features)
        if tracker.n_features != n_features:
            # stream schema changed: restart drift tracking
            tracker = FeatureDriftTracker(
                n_features, window=self.drift_window,
                threshold=self.drift_threshold, clear=self.drift_clear,
                max_size=self.sketch_size)
            ema = np.zeros(n_features, np.float64)
        tracker.observe_cycle(
            summarize_columns(X, max_size=self.sketch_size))
        step = tracker.step()
        sm = self.stream_metrics
        sm.drift_score.set(step["max_score"])
        # refresh only with an incumbent to rebind — a cold-start model
        # gets fresh cuts from its own quantile pass anyway
        refresh = bool(step["refresh"]) and bst.gbtree is not None
        if step["refresh"]:
            sm.drift_events.inc()
            self._event("stream.drift", cycle=cycle,
                        max_score=round(step["max_score"], 6),
                        refresh=refresh)
            self._say(f"cycle {cycle}: drift fired "
                      f"(max PSI {step['max_score']:.4f})")
        if refresh:
            t0 = time.monotonic()
            max_bin = int(self.params.get("max_bin", 256))
            cuts = propose_refreshed_cuts(
                tracker.current(),
                live_thresholds_of(bst.gbtree, n_features), max_bin)
            _save_npz(self._cuts_path(cycle),
                      {"cut_values": cuts.cut_values,
                       "n_cuts": cuts.n_cuts})
            tracker.rebase()
            sm.cut_refreshes.inc()
            sm.refresh_seconds.observe(time.monotonic() - t0)
            self._event("stream.cut_refresh", cycle=cycle,
                        max_cuts=int(cuts.cut_values.shape[1]))
        ema, ntrees = self._ema_update(bst, ema, prev_ntrees)
        kept = self._screen_of(ema)
        arrays = tracker.to_arrays()
        arrays["ema"] = ema
        arrays["ntrees"] = np.asarray(ntrees, np.int64)
        _save_npz(self._sketch_path(cycle), arrays)
        plan = {"cycle": cycle,
                "max_score": step["max_score"],
                "fired": bool(step["fired"]),
                "refresh": refresh,
                "kept": kept}
        # the plan is the commit point: written last, so a plan on disk
        # guarantees its sketch/cuts artifacts are complete
        from xgboost_tpu.reliability.integrity import atomic_write
        atomic_write(self._plan_path(cycle),
                     (json.dumps(plan, sort_keys=True) + "\n").encode())
        return plan

    def _apply_plan(self, bst, plan: dict) -> None:
        cycle = int(plan["cycle"])
        if plan.get("refresh"):
            with np.load(self._cuts_path(cycle),
                         allow_pickle=False) as z:
                cuts = CutMatrix(
                    np.asarray(z["cut_values"], np.float32),
                    np.asarray(z["n_cuts"], np.int32))
            # idempotent: ring bytes saved after a pre-crash rebind
            # already carry these cuts; remapping again is exact
            if bst.gbtree is not None:
                bst.rebind_cuts(cuts)
        kept = plan.get("kept")
        n_features = (bst.gbtree.cuts.num_feature
                      if bst.gbtree is not None and bst.gbtree.cuts
                      is not None else 0)
        bst.set_feature_screen(kept if kept else None)
        self.stream_metrics.kept_features.set(
            float(len(kept) if kept else n_features))

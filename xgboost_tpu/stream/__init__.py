"""xgboost_tpu.stream — streaming, drift-aware continuous learning.

Layers four pieces on the continuous-training pipeline (PIPELINE.md
has the state machine and failure matrix):

- :class:`StreamDataSource` — a directory-spool consumer that turns
  arriving row batches into deterministic micro-cycles via per-cycle
  batch manifests (ring resumes and clean replays stay bit-identical),
  with backpressure (:class:`StreamBacklogFull`) and an
  idle/collecting/ready/catch-up state machine.
- Drift detection — :class:`FeatureDriftTracker` scores PSI per
  feature over sliding sketch summaries; the EvalGate's holdout
  becomes a sliding window of recent cycles.
- Online cut refresh — on a drift fire edge, new quantile cuts are
  proposed from the running sketch and unioned with the incumbent's
  live thresholds, so ``Booster.rebind_cuts`` re-quantizes without a
  full pass and without moving any decision boundary.
- EMA-gain feature screening (``ema_fs=``) — the fused trainer grows
  over the (C, N, F_kept) working set of the features carrying the
  recent gain mass; bit-identical to the full build when off.

Quickstart::

    python -m xgboost_tpu task=stream \\
        stream_publish_path=serving/model.bin stream_dir=./stream-in \\
        stream_rounds_per_cycle=5 stream_cycles=0 \\
        objective=binary:logistic max_depth=4 ema_fs=0.95
"""

from typing import Optional

from xgboost_tpu.pipeline import (EvalGate, Publisher,  # noqa: F401
                                  RolloutPublisher)
from xgboost_tpu.stream.drift import (FeatureDriftTracker,  # noqa: F401
                                      live_thresholds_of,
                                      propose_refreshed_cuts, psi_score,
                                      summarize_columns)
from xgboost_tpu.stream.source import (StreamBacklogFull,  # noqa: F401
                                       StreamDataSource)
from xgboost_tpu.stream.trainer import StreamTrainer  # noqa: F401


def run_stream(publish_path: str, workdir: str = "./stream",
               stream_dir: str = "", rounds_per_cycle: int = 5,
               cycles: int = 1, min_batches: int = 1,
               max_batches: int = 8, catchup_backlog: int = 16,
               max_backlog: int = 256, holdout_cycles: int = 4,
               metric: str = "", min_delta: float = 0.0,
               max_regression: float = 0.0, router_url: str = "",
               sleep_sec: float = 0.05, drift_threshold: float = 0.25,
               drift_clear: float = 0.1, drift_window: int = 4,
               sketch_size: int = 256,
               params: Optional[dict] = None,
               source: Optional[StreamDataSource] = None,
               quiet: bool = False, lane: str = "") -> dict:
    """Assemble the streaming loop from flat knob values (the CLI
    ``task=stream`` surface — every ``STREAM_PARAMS`` key maps to one
    argument) and run it.  ``source`` overrides the spool seam for
    embedders (tests, the chaos harness's in-process producers)."""
    if not publish_path:
        raise ValueError("stream_publish_path is required")
    if source is None:
        if not stream_dir:
            raise ValueError("stream_dir is required "
                             "(or pass a StreamDataSource)")
        source = StreamDataSource(
            stream_dir, min_batches=min_batches,
            max_batches=max_batches, catchup_backlog=catchup_backlog,
            max_backlog=max_backlog, holdout_cycles=holdout_cycles)
    gate = EvalGate(metric=metric, min_delta=min_delta,
                    max_regression=max_regression)
    publisher = (RolloutPublisher(publish_path, router_url, model=lane)
                 if router_url else Publisher(publish_path))
    trainer = StreamTrainer(
        publish_path, source, workdir,
        rounds_per_cycle=rounds_per_cycle, params=params, gate=gate,
        publisher=publisher, quiet=quiet, lane=lane,
        drift_threshold=drift_threshold, drift_clear=drift_clear,
        drift_window=drift_window, sketch_size=sketch_size)
    return trainer.run(cycles=cycles, sleep_sec=sleep_sec)


__all__ = [
    "StreamDataSource", "StreamBacklogFull", "StreamTrainer",
    "FeatureDriftTracker", "run_stream", "psi_score",
    "propose_refreshed_cuts", "live_thresholds_of", "summarize_columns",
]

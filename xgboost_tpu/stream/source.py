"""Streaming DataSource: arriving row batches -> deterministic micro-cycles.

The spool directory is the wire format: producers drop one ``.npz``
per row batch (``push`` writes them atomically — a consumer can never
read a torn batch), and the consumer side composes micro-cycles from
whatever has arrived.  The determinism contract of the pipeline's
:class:`~xgboost_tpu.pipeline.datasource.DataSource` seam ("same
cycle index -> same bytes, every call") is carried by per-cycle
**manifests**: the first ``next_cycle(k)`` call commits an atomic
manifest naming exactly which batch files make up cycle ``k`` BEFORE
any data is returned, and every later call — a ring resume after a
SIGKILL mid-train, a crash-recovery re-gate, or a clean replay from a
fresh workdir over the same stream directory — replays the manifest
instead of re-deciding.  Batch files are append-only and never
deleted, so a replay months later still finds its bytes.

State machine (reported via ``state`` + the
``xgbtpu_stream_state`` gauge):

    idle        no unclaimed batches
    collecting  some batches, fewer than ``min_batches``
    ready       >= min_batches; the next cycle takes up to
                ``max_batches`` of them
    catch_up    backlog >= ``catchup_backlog``: the consumer is behind;
                cycles take full ``max_batches`` bites until drained

Backpressure: ``push`` raises :class:`StreamBacklogFull` once
``max_backlog`` unclaimed batches are spooled — the producer slows
down instead of the directory growing without bound.

Sliding holdout: ``holdout_for(k)`` is the concatenation of the
batches of the previous ``holdout_cycles`` manifests — the gate
judges candidates on RECENT data that the candidate itself did not
train on (cycle ``k``'s own batches are excluded, except at cycle 0
where nothing earlier exists), which is what makes the gate
drift-aware: as the stream moves, so does the window.
"""

from __future__ import annotations

import io
import json
import os
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from xgboost_tpu.pipeline.datasource import DataSource

_BATCH_RE = re.compile(r"batch-(\d{12})\.npz$")
_MANIFEST_FMT = "cycle-%06d.json"


class StreamBacklogFull(RuntimeError):
    """``push`` refused: the unclaimed-batch backlog hit the cap."""


def _metrics():
    from xgboost_tpu.obs.metrics import stream_metrics
    return stream_metrics()


class StreamDataSource(DataSource):
    """Directory-spool streaming feed with per-cycle batch manifests."""

    STATES = ("idle", "collecting", "ready", "catch_up")

    def __init__(self, stream_dir: str, min_batches: int = 1,
                 max_batches: int = 8, catchup_backlog: int = 16,
                 max_backlog: int = 256, holdout_cycles: int = 4):
        self.stream_dir = stream_dir
        self.spool_dir = os.path.join(stream_dir, "spool")
        self.manifest_dir = os.path.join(stream_dir, "manifests")
        self.min_batches = max(1, int(min_batches))
        self.max_batches = max(self.min_batches, int(max_batches))
        self.catchup_backlog = max(1, int(catchup_backlog))
        self.max_backlog = max(1, int(max_backlog))
        self.holdout_cycles = max(1, int(holdout_cycles))
        self.state = "idle"
        self._holdout_memo: Dict[int, object] = {}
        os.makedirs(self.spool_dir, exist_ok=True)
        os.makedirs(self.manifest_dir, exist_ok=True)

    # ------------------------------------------------------------ producer
    def push(self, X: np.ndarray, y: np.ndarray) -> str:
        """Spool one row batch atomically; returns the batch file name.
        Raises :class:`StreamBacklogFull` under backpressure."""
        backlog = self.backlog()
        if backlog >= self.max_backlog:
            m = _metrics()
            m.backpressure.inc()
            m.backlog.set(float(backlog))
            raise StreamBacklogFull(
                f"{self.spool_dir}: {backlog} unclaimed batches "
                f"(max_backlog={self.max_backlog})")
        X = np.asarray(X, np.float32)
        y = np.asarray(y, np.float32).ravel()
        if X.shape[0] != y.shape[0]:
            raise ValueError(f"X has {X.shape[0]} rows, y has {y.shape[0]}")
        buf = io.BytesIO()
        np.savez(buf, X=X, y=y)
        from xgboost_tpu.reliability.integrity import atomic_write
        tmp = os.path.join(self.spool_dir,
                           f".incoming-{os.getpid()}-{id(buf):x}.npz")
        atomic_write(tmp, buf.getvalue())
        try:
            seq = self._max_seq() + 1
            while True:
                final = os.path.join(self.spool_dir, f"batch-{seq:012d}.npz")
                try:
                    # exclusive claim of the sequence slot: concurrent
                    # producers race on link(2), never on file content
                    os.link(tmp, final)
                    return os.path.basename(final)
                except FileExistsError:
                    seq += 1
        finally:
            try:
                os.unlink(tmp)
            except OSError as e:
                from xgboost_tpu.obs.metrics import swallowed_error
                swallowed_error("stream.push_tmp", e)

    # ------------------------------------------------------------ geometry
    def _batches(self) -> List[Tuple[int, str]]:
        out = []
        for name in os.listdir(self.spool_dir):
            m = _BATCH_RE.match(name)
            if m:
                out.append((int(m.group(1)), name))
        out.sort()
        return out

    def _max_seq(self) -> int:
        b = self._batches()
        return b[-1][0] if b else 0

    def _manifest_path(self, cycle: int) -> str:
        return os.path.join(self.manifest_dir, _MANIFEST_FMT % cycle)

    def _read_manifest(self, cycle: int) -> Optional[dict]:
        try:
            with open(self._manifest_path(cycle), encoding="utf-8") as f:
                m = json.load(f)
            return m if isinstance(m, dict) else None
        except (OSError, ValueError):
            return None

    def _claimed_through(self, cycle: int) -> int:
        """Highest batch seq claimed by cycles before ``cycle`` (cycles
        are contiguous — the trainer never skips an index)."""
        if cycle <= 0:
            return 0
        m = self._read_manifest(cycle - 1)
        if m is None:
            raise RuntimeError(
                f"stream manifest for cycle {cycle - 1} is missing — "
                f"cycles must be composed in order ({self.manifest_dir})")
        return int(m["through"])

    def backlog(self, cycle: Optional[int] = None) -> int:
        """Unclaimed batch count (``cycle`` = next cycle to compose;
        None = against the newest existing manifest)."""
        if cycle is None:
            cycles = self._manifest_cycles()
            cycle = (cycles[-1] + 1) if cycles else 0
        through = self._claimed_through(cycle)
        return sum(1 for seq, _ in self._batches() if seq > through)

    def _manifest_cycles(self) -> List[int]:
        out = []
        for name in os.listdir(self.manifest_dir):
            m = re.match(r"cycle-(\d{6})\.json$", name)
            if m:
                out.append(int(m.group(1)))
        out.sort()
        return out

    # ------------------------------------------------------------ consumer
    def _compose(self, cycle: int) -> Optional[dict]:
        """Commit cycle ``cycle``'s manifest from unclaimed batches, or
        None when fewer than ``min_batches`` have arrived."""
        through = self._claimed_through(cycle)
        unclaimed = [(seq, name) for seq, name in self._batches()
                     if seq > through]
        backlog = len(unclaimed)
        m = _metrics()
        m.backlog.set(float(backlog))
        if backlog < self.min_batches:
            self._set_state("collecting" if backlog else "idle")
            return None
        self._set_state("catch_up" if backlog >= self.catchup_backlog
                        else "ready")
        take = unclaimed[:self.max_batches]
        manifest = {"cycle": cycle,
                    "batches": [name for _, name in take],
                    "through": take[-1][0]}
        from xgboost_tpu.reliability.integrity import atomic_write
        atomic_write(self._manifest_path(cycle),
                     (json.dumps(manifest, sort_keys=True) + "\n").encode())
        m.cycles.inc()
        m.batches.inc(len(take))
        return manifest

    def _set_state(self, state: str) -> None:
        self.state = state
        _metrics().state.set(float(self.STATES.index(state)))

    def batches_for(self, cycle: int) -> Optional[List[str]]:
        """The committed batch file names of a cycle, or None before
        its manifest exists."""
        m = self._read_manifest(cycle)
        return None if m is None else list(m["batches"])

    def read_cycle_arrays(self, cycle: int
                          ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(X, y) of a cycle's committed batches, concatenated — the
        raw-row view the drift tracker sketches from."""
        names = self.batches_for(cycle)
        if names is None:
            return None
        return self._read_batches(names)

    def _read_batches(self, names: List[str]
                      ) -> Tuple[np.ndarray, np.ndarray]:
        xs, ys = [], []
        for name in names:
            with np.load(os.path.join(self.spool_dir, name),
                         allow_pickle=False) as z:
                xs.append(np.asarray(z["X"], np.float32))  # xgtpu: disable=XGT002 — host npz read, once per cycle
                ys.append(np.asarray(z["y"], np.float32))  # xgtpu: disable=XGT002 — host npz read, once per cycle
        return np.concatenate(xs), np.concatenate(ys)

    def next_cycle(self, cycle: int):
        manifest = self._read_manifest(cycle)
        if manifest is None:
            manifest = self._compose(cycle)
            if manifest is None:
                return None
        X, y = self._read_batches(manifest["batches"])
        _metrics().rows.inc(len(y))
        from xgboost_tpu.data import DMatrix
        return DMatrix(X, label=y), self.holdout_for(cycle)

    def holdout_for(self, cycle: int):
        """Sliding holdout: the previous ``holdout_cycles`` cycles'
        batches (cycle 0, with no history, judges on its own batches —
        the gate passes unconditionally there anyway, cold start)."""
        if cycle in self._holdout_memo:
            return self._holdout_memo[cycle]
        lo = max(0, cycle - self.holdout_cycles)
        window = list(range(lo, cycle)) if cycle > 0 else [0]
        names: List[str] = []
        for c in window:
            part = self.batches_for(c)
            if part is None:
                return None
            names.extend(part)
        X, y = self._read_batches(names)
        from xgboost_tpu.data import DMatrix
        hold = DMatrix(X, label=y)
        # one object per cycle index: the trainer's incumbent-score
        # cache keys on id(holdout), so a NEW window naturally
        # invalidates it while re-gates within a cycle reuse it
        self._holdout_memo[cycle] = hold
        while len(self._holdout_memo) > 4:
            self._holdout_memo.pop(min(self._holdout_memo))
        return hold

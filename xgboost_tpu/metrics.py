"""Evaluation metrics.

Re-implements the reference metric set and registry
(``src/learner/evaluation-inl.hpp``, registry ``evaluation.h:42-59``):
elementwise rmse/logloss/error (:24-107), multiclass merror/mlogloss
(:113-199), AMS (:243-300), precision-ratio family (:302-352), AUC
(:355-419), and the ranklist metrics pre@n/ndcg@n/map@n (:422-565) with
the trailing ``-`` convention (lists without positives score 0 instead
of 1).

Metrics run host-side in numpy (they are cheap relative to training);
predictions arrive already eval-transformed by the objective.  With a
replicated load the controller sees the full prediction vector, so the
(sum, wsum) rabit allreduce of the reference (``evaluation-inl.hpp:45``)
is unnecessary and AUC is computed exactly.  With PER-RANK SPLIT
loading (``parallel/sharded.py``) each process holds only its shard:
the ``_DIST_METRICS`` table below provides per-shard partials + a
finalize over the cross-process sum — and distributed AUC is then the
reference's approximate mean-of-shards form (``:405-414``), NOT the
exact global AUC (documented difference between the two modes).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

_EPS = 1e-16


def _wmean(values: np.ndarray, weights: np.ndarray) -> float:
    return float(np.sum(values * weights) / np.sum(weights))


# ------------------------------------------------------------ elementwise

def rmse(preds, labels, weights, group_ptr=None):
    return float(np.sqrt(_wmean((preds - labels) ** 2, weights)))


def logloss(preds, labels, weights, group_ptr=None):
    p = np.clip(preds, _EPS, 1.0 - _EPS)
    ll = -(labels * np.log(p) + (1.0 - labels) * np.log(1.0 - p))
    return _wmean(ll, weights)


def error(preds, labels, weights, group_ptr=None):
    wrong = np.where(preds > 0.5, labels != 1.0, labels != 0.0)
    return _wmean(wrong.astype(np.float64), weights)


def merror(preds, labels, weights, group_ptr=None):
    yhat = np.argmax(preds, axis=1)
    return _wmean((yhat != labels.astype(np.int64)).astype(np.float64), weights)


def mlogloss(preds, labels, weights, group_ptr=None):
    p = np.clip(preds[np.arange(len(labels)), labels.astype(np.int64)],
                _EPS, None)
    return _wmean(-np.log(p), weights)


# ------------------------------------------------------------------- AUC

def auc(preds, labels, weights, group_ptr=None):
    """Weighted AUC; averaged over groups when group_ptr is given
    (reference EvalAuc, evaluation-inl.hpp:355-419).  Tied predictions are
    handled as half-credit buckets, matching the reference's bucket scan
    (:377-397), vectorized over tie-groups."""
    preds = preds.ravel()
    if group_ptr is None:
        group_ptr = np.array([0, len(preds)])
    total, ngroup = 0.0, 0
    for g in range(len(group_ptr) - 1):
        s, e = group_ptr[g], group_ptr[g + 1]
        v = _auc_group(preds[s:e], labels[s:e], weights[s:e])
        if v is None:
            continue
        total += v
        ngroup += 1
    if ngroup == 0:
        raise ValueError("AUC: the dataset only contains pos or neg samples")
    return float(total / ngroup)


def _value_runs(p, wpos, wneg):
    """Compress (value, pos_weight, neg_weight) triples into sorted
    distinct-value runs — the tie-grouping idiom shared by the local,
    compressed-partial, and merged AUC paths (one implementation so a
    tie/weight fix cannot silently diverge them)."""
    order = np.argsort(p, kind="stable")
    p, wpos, wneg = p[order], wpos[order], wneg[order]
    if len(p) == 0:
        return p, wpos, wneg
    boundary = np.concatenate([[True], p[1:] != p[:-1]])
    gid = np.cumsum(boundary) - 1
    gpos = np.zeros(gid[-1] + 1)
    gneg = np.zeros(gid[-1] + 1)
    np.add.at(gpos, gid, wpos)
    np.add.at(gneg, gid, wneg)
    return p[boundary], gpos, gneg


def _runs_auc(gpos, gneg):
    """Average-tied-rank AUC from sorted distinct-value runs; None if
    one class is absent."""
    tot_pos, tot_neg = gpos.sum(), gneg.sum()
    if tot_pos <= 0 or tot_neg <= 0:
        return None
    cum_neg_before = np.cumsum(gneg) - gneg
    return np.sum(gpos * (cum_neg_before + 0.5 * gneg)) / (
        tot_pos * tot_neg)


def _auc_group(p, y, w):
    _, gpos, gneg = _value_runs(p, w * (y > 0), w * (y <= 0))
    return _runs_auc(gpos, gneg)


# ------------------------------------------------------------------- AMS

def ams(preds, labels, weights, group_ptr=None, ratio: float = 0.15):
    """Approximate median significance at threshold `ratio`
    (reference EvalAMS, evaluation-inl.hpp:243-300; Higgs challenge)."""
    preds = preds.ravel()
    order = np.argsort(-preds, kind="stable")
    ntop = int(ratio * len(preds))
    if ntop == 0:
        ntop = len(preds)
    sel = order[:ntop]
    br = 10.0
    s = float(np.sum(weights[sel] * (labels[sel] == 1.0)))
    b = float(np.sum(weights[sel] * (labels[sel] != 1.0)))
    val = 2.0 * ((s + b + br) * np.log(1.0 + s / (b + br)) - s)
    return float(np.sqrt(max(val, 0.0)))


# --------------------------------------------------- precision-ratio family

def precision_ratio(preds, labels, weights, group_ptr=None,
                    ratio: float = 0.1, use_ap: bool = False):
    """Precision in the top ``ratio`` fraction by prediction
    (reference EvalPrecisionRatio, evaluation-inl.hpp:302-352):
    ``pratio@r`` is the weighted hit rate within the cutoff; ``apratio@r``
    averages the running precision over every rank up to the cutoff.

    Deviation: the reference weights position ``j`` of the *sorted* list
    with ``GetWeight(j)`` — i.e. the weight of an unrelated row
    (evaluation-inl.hpp:340) — which only coincides with instance weights
    when all weights are equal.  We weight the selected instance itself.
    """
    # like the reference, only the first labels.size() entries of the FLAT
    # (row-major) prediction vector are ranked (evaluation-inl.hpp:317-320
    # over preds laid out preds[row*ngroup+group], gbtree-inl.hpp:157)
    n = len(labels)
    preds = np.asarray(preds).ravel()[:n]
    order = np.argsort(-preds, kind="stable")
    cutoff = int(ratio * len(preds))
    if cutoff == 0:
        return 0.0
    sel = order[:cutoff]
    w = weights[sel]
    hit = np.cumsum(labels[sel] * w)
    wsum = np.cumsum(w)
    if use_ap:
        return float(np.mean(hit / wsum))
    return float(hit[-1] / wsum[-1])


# ------------------------------------------------------- cross-fold ctest

def ctest(base_fn, preds, labels, weights, fold_index):
    """Cross-validation test metric ``ct-<base>`` (reference EvalCTest,
    evaluation-inl.hpp:202-240): predictions carry ``ngroup+1`` stacked
    prediction sets of ``ndata`` each (the head set is the full model;
    set ``k+1`` is the model that held out fold ``k``); the base metric is
    evaluated per fold on its held-out rows and averaged over folds."""
    preds = np.asarray(preds)
    if preds.ndim != 1:
        raise ValueError(
            "ct-: expects 1D stacked prediction sets (got shape "
            f"{preds.shape}); multiclass per-class outputs are not a "
            "fold-stacked layout")
    n = len(labels)
    if preds.size % n != 0:
        raise ValueError("ct-: label and prediction size not match")
    ngroup = preds.size // n - 1
    if ngroup <= 1:
        raise ValueError("ct-: pred size does not meet requirement")
    if fold_index is None or len(fold_index) != n:
        raise ValueError("ct-: need fold index")
    fold_index = np.asarray(fold_index)
    wsum = 0.0
    for k in range(ngroup):
        mask = fold_index == k
        if not mask.any():
            raise ValueError(
                f"ct-: fold {k} has no rows — fold_index must be 0-based "
                f"ids in [0, {ngroup})")
        wsum += base_fn(preds[(k + 1) * n:(k + 2) * n][mask],
                        labels[mask], weights[mask], None)
    return float(wsum / ngroup)


# ------------------------------------------------------- ranklist metrics

def _dcg_at(rels: np.ndarray, n: int) -> float:
    rels = rels[:n]
    return float(np.sum((2.0 ** rels - 1.0) / np.log2(np.arange(len(rels)) + 2.0)))


def ndcg(preds, labels, weights, group_ptr=None, n: int = 0, minus=False):
    return _rank_metric(preds, labels, group_ptr, n, minus, _ndcg_group)


def _ndcg_group(p, y, n):
    n = n if n > 0 else len(p)
    order = np.argsort(-p, kind="stable")
    dcg = _dcg_at(y[order], n)
    idcg = _dcg_at(np.sort(y)[::-1], n)
    if idcg == 0.0:
        return None  # no relevant docs
    return dcg / idcg


def map_metric(preds, labels, weights, group_ptr=None, n: int = 0, minus=False):
    return _rank_metric(preds, labels, group_ptr, n, minus, _map_group)


def _map_group(p, y, n):
    order = np.argsort(-p, kind="stable")
    rel = (y[order] > 0).astype(np.float64)
    if rel.sum() == 0:
        return None
    n = n if n > 0 else len(p)
    hits = np.cumsum(rel)
    prec = rel * hits / np.arange(1, len(rel) + 1)
    return float(np.sum(prec[:n]) / min(rel.sum(), n))


def precision_at(preds, labels, weights, group_ptr=None, n: int = 0, minus=False):
    return _rank_metric(preds, labels, group_ptr, n, minus, _pre_group)


def _pre_group(p, y, n):
    n = n if n > 0 else len(p)
    order = np.argsort(-p, kind="stable")
    return float(np.sum(y[order][:n] > 0) / n)


def _rank_metric(preds, labels, group_ptr, n, minus, fn):
    preds = preds.ravel()
    if group_ptr is None:
        group_ptr = np.array([0, len(preds)])
    total, ngroup = 0.0, 0
    for g in range(len(group_ptr) - 1):
        s, e = group_ptr[g], group_ptr[g + 1]
        v = fn(preds[s:e], labels[s:e], n)
        if v is None:
            v = 0.0 if minus else 1.0
        total += v
        ngroup += 1
    return float(total / max(ngroup, 1))


# ----------------------------------------------- distributed partial sums
#
# Per-shard (sum, wsum) partials + cross-process reduction — the
# reference's rabit::Allreduce in EvalEWiseBase::Eval
# (evaluation-inl.hpp:45) and EvalAuc (:405-414).  Used by the per-rank
# split-loaded evaluation path (parallel/sharded.py) instead of
# all-gathering predictions.

def _ewise_partial(point_fn):
    def partial(preds, labels, weights, group_ptr=None):
        return np.array([float(np.sum(point_fn(preds, labels) * weights)),
                         float(np.sum(weights))], np.float64)
    return partial


def _ratio_final(s):
    return float(s[0] / s[1])


def _auc_partial(preds, labels, weights, group_ptr=None):
    """Sum of per-group AUCs + group count on this shard.  Without group
    structure the shard is ONE group, so the reduced result is the mean
    of per-shard AUCs — the reference's documented approximation for
    distributed AUC (evaluation-inl.hpp:405-414), NOT the exact global
    AUC the single-host path computes."""
    preds = np.asarray(preds).ravel()
    if group_ptr is None:
        group_ptr = np.array([0, len(preds)])
    total, ngroup = 0.0, 0
    for g in range(len(group_ptr) - 1):
        s, e = group_ptr[g], group_ptr[g + 1]
        v = _auc_group(preds[s:e], labels[s:e], weights[s:e])
        if v is None:
            continue
        total += v
        ngroup += 1
    return np.array([total, float(ngroup)], np.float64)


def _auc_final(s):
    if s[1] == 0:
        raise ValueError("AUC: the dataset only contains pos or neg samples")
    return float(s[0] / s[1])


# ------------------------------------------------------ exact sharded AUC
#
# The reference's distributed AUC is the MEAN of per-shard AUCs
# (evaluation-inl.hpp:405-414) — an approximation this framework only
# keeps as the reference-compat fallback (dist_auc=approx).  The exact
# default: each shard compresses its predictions into (value, pos_w,
# neg_w) runs — one row per DISTINCT predicted value, so the payload is
# bounded by the shard's distinct-value count — the runs allgather
# across processes (cheap on ICI/DCN; the 2014-era ethernet cost that
# motivated the reference's approximation does not apply), and the
# merged distribution yields the same average-tied-rank AUC the
# replicated path computes, to f64 summation order.

def auc_compress(preds, labels, weights) -> np.ndarray:
    """(K, 3) float64 [value, pos_weight, neg_weight] runs, sorted by
    value — this shard's exact-AUC partial."""
    p = np.asarray(preds, np.float64).ravel()
    y = np.asarray(labels, np.float64).ravel()
    w = np.asarray(weights, np.float64).ravel()
    v, gpos, gneg = _value_runs(p, w * (y > 0), w * (y <= 0))
    return np.stack([v, gpos, gneg], axis=1)


def auc_exact_from_runs(runs: np.ndarray) -> float:
    """Exact weighted AUC (ties at half credit — _auc_group's formula)
    from concatenated per-shard (value, pos_w, neg_w) runs: merging
    runs of the same value from different shards is itself a
    _value_runs pass."""
    _, mp, mn = _value_runs(runs[:, 0], runs[:, 1], runs[:, 2])
    v = _runs_auc(mp, mn)
    if v is None:
        raise ValueError(
            "AUC: the dataset only contains pos or neg samples")
    return float(v)


def _mlogloss_points(preds, labels):
    p = np.clip(preds[np.arange(len(labels)), labels.astype(np.int64)],
                _EPS, None)
    return -np.log(p)


_DIST_METRICS = {
    "rmse": (_ewise_partial(lambda p, l: (p - l) ** 2),
             lambda s: float(np.sqrt(s[0] / s[1]))),
    "logloss": (_ewise_partial(lambda p, l: -(
        l * np.log(np.clip(p, _EPS, 1 - _EPS))
        + (1.0 - l) * np.log(1.0 - np.clip(p, _EPS, 1 - _EPS)))),
        _ratio_final),
    "error": (_ewise_partial(lambda p, l: np.where(
        p > 0.5, l != 1.0, l != 0.0).astype(np.float64)), _ratio_final),
    "merror": (_ewise_partial(lambda p, l: (
        np.argmax(p, axis=1) != l.astype(np.int64)).astype(np.float64)),
        _ratio_final),
    "mlogloss": (_ewise_partial(_mlogloss_points), _ratio_final),
    "auc": (_auc_partial, _auc_final),
}


# --------------------------------------------------------------- registry

def create_metric(name: str) -> Callable:
    """Metric factory (reference CreateEvaluator, evaluation.h:42-59).

    Supports suffixed names: ``ndcg@10``, ``map@5-``, ``pre@3``, ``ams@0.15``.
    """
    if name.startswith("ct-"):
        base_fn = create_metric(name[3:])
        wrapped = _named(
            lambda p, l, w, g=None, fold_index=None: ctest(
                base_fn, p, l, w, fold_index), name)
        wrapped.needs_fold_index = True
        return wrapped
    base, at, suffix = name.partition("@")
    minus = False
    if suffix.endswith("-"):
        minus, suffix = True, suffix[:-1]
    simple: Dict[str, Callable] = {
        "rmse": rmse, "logloss": logloss, "error": error,
        "merror": merror, "mlogloss": mlogloss, "auc": auc,
    }
    if not at and base in simple:
        fn = _named(simple[base], name)
        if base in _DIST_METRICS:
            fn.partial_fn, fn.finalize_fn = _DIST_METRICS[base]
        return fn
    if base == "ams":
        ratio = float(suffix) if suffix else 0.15
        return _named(lambda p, l, w, g=None: ams(p, l, w, g, ratio), name)
    if base in ("pratio", "apratio"):
        ratio = float(suffix) if suffix else 0.1
        use_ap = base == "apratio"
        return _named(lambda p, l, w, g=None: precision_ratio(
            p, l, w, g, ratio, use_ap), name)
    topn = int(float(suffix)) if suffix else 0
    rankers = {"ndcg": ndcg, "map": map_metric, "pre": precision_at}
    if base in rankers:
        fn = rankers[base]
        return _named(
            lambda p, l, w, g=None: fn(p, l, w, g, topn, minus), name)
    raise ValueError(f"unknown evaluation metric type: {name}")


def _named(fn: Callable, name: str) -> Callable:
    fn.metric_name = name
    return fn

"""Worker-side gang protocol: partition fencing, host loss, liveness.

The elastic gang recovery design (RECOVERY.md degraded-mode matrix)
splits responsibilities: the LAUNCHER (``parallel/launch.py``) owns
detection of death/stall, size re-planning and coordinator-state
snapshots; the WORKER owns the two decisions only it can make —

- **self-fencing**: a worker that cannot see a fresh coordinator
  beacon for ``XGBTPU_GANG_PARTITION_SEC`` seconds must assume it has
  been declared dead and REPLACED.  It stops writing heartbeats and
  checkpoints and dies with :data:`FENCE_RC`, so a healed partition
  can never produce two writers racing the checkpoint ring
  (split-brain).  The launcher restarts/readmits it like any other
  death — a fenced worker re-joins cleanly as a grow-back candidate.
- **host-loss reporting**: the ``host_loss`` chaos fault
  (``reliability/faults.py`` gang seam) models a permanently dead
  host: the worker writes a ``lost-<rank>`` tombstone and dies with
  :data:`HOST_LOSS_RC`, and because the env-armed spec re-fires in
  every respawn, the "host" stays dead until the launcher re-plans the
  gang without it (degraded attempts export ``XGBTPU_GANG_DEGRADED``
  and skip the check — the lost host is no longer scheduled).

The coordinator's liveness beacon is the ``coord`` file in
``XGBTPU_GANG_DIR``, touched by the launcher every poll tick; a worker
observes it at round boundaries (``parallel/mock.py:begin_round`` →
:func:`on_round`) exactly the way the launcher observes worker
heartbeats — mtime CHANGES on the observer's monotonic clock, never
wall-clock arithmetic (XGT006).  ``done-<rank>`` markers
(:func:`mark_done`) let a restarted coordinator that re-ADOPTED
non-child workers distinguish their clean exits from crashes.
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional, Tuple

#: shared gang-protocol directory (beacon, tombstones, done markers,
#: grow-back signal), exported by the launcher when elastic features
#: are on; unset = the whole protocol is a no-op
GANG_DIR_ENV = "XGBTPU_GANG_DIR"
#: seconds of coordinator unreachability after which a worker
#: self-fences (0/unset = fencing off)
PARTITION_SEC_ENV = "XGBTPU_GANG_PARTITION_SEC"
#: exported by the launcher on attempts running at REDUCED size: the
#: host_loss fault no longer fires (the lost host is not scheduled)
DEGRADED_ENV = "XGBTPU_GANG_DEGRADED"

#: worker exit codes (registry: reliability/rc.py, lint rule XGT016):
#: FENCE_RC for a self-fence (coordinator unreachable too long),
#: HOST_LOSS_RC for a simulated permanent host death; re-exported here
#: for the launcher and tests, which read them off this module
from xgboost_tpu.reliability.rc import (FENCE_RC,  # noqa: F401
                                        HOST_LOSS_RC)

#: beacon file the launcher touches every poll tick
BEACON_NAME = "coord"
#: default partition-window seconds when the fault spec gives no arg
DEFAULT_WINDOW_SEC = 5.0


class PartitionClock:
    """Coordinator-reachability tracker for one worker.

    Pure logic with an injectable monotonic clock (the chaos selftest
    drives it with a mock clock): :meth:`open_window` starts a
    message-drop window (the ``partition`` fault), :meth:`observe`
    folds in the latest beacon mtime and classifies the round:

    - ``"ok"`` — coordinator reachable; beacons/heartbeats flow;
    - ``"partitioned"`` — messages dropping (window open) or the beacon
      has gone stale, but not yet for ``partition_sec``;
    - ``"fence"`` — unreachable past ``partition_sec``: the worker must
      stop writing and die (``partition_sec <= 0`` disables fencing, so
      this state is never returned then).

    Beacon freshness is mtime CHANGE observed on this clock — wall
    mtimes are only ever compared with each other, the launcher's own
    heartbeat-watchdog discipline.
    """

    def __init__(self, partition_sec: float = 0.0, monotonic=None):
        self.partition_sec = float(partition_sec)
        self._mono = monotonic if monotonic is not None else time.monotonic
        self._window_until = 0.0
        self._last_mtime: Optional[float] = None
        self._last_change: Optional[float] = None

    def open_window(self, sec: float) -> None:
        """Open (or extend) a both-directions message-drop window."""
        self._window_until = max(self._window_until,
                                 self._mono() + float(sec))

    def window_open(self) -> bool:
        return self._mono() < self._window_until

    def observe(self, beacon_mtime: Optional[float]) -> str:
        now = self._mono()
        if self._last_change is None:
            self._last_change = now  # grace starts at first observation
        dropped = self.window_open()
        if not dropped and beacon_mtime is not None \
                and beacon_mtime != self._last_mtime:
            # a beacon read only lands when the link is up: reads
            # during an open window are dropped like everything else
            self._last_mtime = beacon_mtime
            self._last_change = now
            return "ok"
        unreachable = now - self._last_change
        if self.partition_sec > 0 and unreachable > self.partition_sec:
            return "fence"
        return "partitioned" if dropped else "ok"


_clock: Optional[PartitionClock] = None
_fenced = False


def _reset() -> None:
    """Forget all per-process gang state (test isolation)."""
    global _clock, _fenced
    _clock = None
    _fenced = False


def fenced() -> bool:
    """True once this worker has self-fenced: checkpoint writers must
    refuse to touch the ring (cli._save_checkpoint gate)."""
    return _fenced


def _get_clock(partition_sec: float) -> PartitionClock:
    global _clock
    if _clock is None:
        _clock = PartitionClock(partition_sec)
    return _clock


def _rank_trial() -> Tuple[str, str]:
    return (os.environ.get("XGBTPU_WORKER_ID", "0"),
            os.environ.get("XGBTPU_NUM_TRIAL", "0"))


def _die(rc: int) -> None:
    # die HARD (RECOVERY.md "die hard"): the obs event log flushes per
    # line, and a normal interpreter exit can hang in distributed
    # teardown — the launcher needs to see this pid dead NOW
    sys.stderr.flush()
    os._exit(rc)


def on_round(version: int) -> bool:
    """Round-boundary gang hook (called by ``mock.begin_round``).

    Fires armed gang faults at the ``t<trial>.r<rank>.v<version>.``
    coordinate, tracks coordinator reachability, and self-fences when
    unreachable past the threshold (this call then never returns).
    Returns False when the heartbeat beacon must be SUPPRESSED this
    round (messages to the coordinator are dropping)."""
    global _fenced
    rank, trial = _rank_trial()
    gang_dir = os.environ.get(GANG_DIR_ENV)
    partition_sec = float(os.environ.get(PARTITION_SEC_ENV) or 0.0)

    if not os.environ.get(DEGRADED_ENV):
        from xgboost_tpu.reliability import faults
        coord = f"t{trial}.r{rank}.v{version}."
        for kind, arg in faults.gang_fault(coord):
            if kind == "host_loss":
                _host_loss(gang_dir, rank, trial, version)  # no return
            elif kind == "partition":
                sec = float(arg) if arg is not None else DEFAULT_WINDOW_SEC
                _get_clock(partition_sec).open_window(sec)
                from xgboost_tpu.obs import trace
                trace.event("gang.partition", rank=rank, trial=trial,
                            window_sec=sec)
                print(f"[gang] partition window {sec}s open at "
                      f"version={version} trial={trial} (beacons drop "
                      "both ways)", file=sys.stderr)

    if _clock is None and partition_sec <= 0:
        return True  # no window ever opened, fencing off: fast path
    clock = _get_clock(partition_sec)
    mtime = None
    if gang_dir:
        try:
            mtime = os.stat(os.path.join(gang_dir, BEACON_NAME)).st_mtime
        except OSError:
            mtime = None  # unreadable beacon counts as unreachable
    elif partition_sec > 0:
        return True  # threshold armed but no gang dir: nothing to watch
    status = clock.observe(mtime)
    if status == "fence":
        _fenced = True
        from xgboost_tpu.obs import trace
        from xgboost_tpu.profiling import reliability_metrics
        reliability_metrics().launch_fences.inc()
        trace.event("gang.fence", rank=rank, trial=trial,
                    version=version, partition_sec=partition_sec)
        print(f"[gang] FENCED: coordinator unreachable > "
              f"{partition_sec}s at version={version} trial={trial}; "
              "no further checkpoint/beacon writes, exiting "
              f"rc={FENCE_RC}", file=sys.stderr)
        _die(FENCE_RC)
    return status == "ok"


def _host_loss(gang_dir: Optional[str], rank: str, trial: str,
               version: int) -> None:
    from xgboost_tpu.obs import trace
    trace.event("gang.host_loss", rank=rank, trial=trial,
                version=version)
    if gang_dir:
        try:
            # a tombstone, not durable state: the launcher also keys off
            # HOST_LOSS_RC, so a torn marker costs nothing
            with open(os.path.join(gang_dir, f"lost-{rank}"),  # xgtpu: disable=XGT003
                      "w") as f:
                f.write(f"v{version} t{trial}\n")
        except OSError as e:
            from xgboost_tpu.obs.metrics import swallowed_error
            swallowed_error("parallel.gang.tombstone", e)
    print(f"[gang] HOST LOSS at version={version} trial={trial} "
          f"rank={rank}: permanent, exiting rc={HOST_LOSS_RC} (the "
          "launcher must re-plan without this host)", file=sys.stderr)
    _die(HOST_LOSS_RC)


def mark_done() -> None:
    """Touch this rank's ``done-<rank>`` marker on clean exit, so a
    coordinator that re-adopted this (non-child, thus unwaitable)
    worker can tell success from a crash.  No-op without a gang dir;
    never raises."""
    gang_dir = os.environ.get(GANG_DIR_ENV)
    if not gang_dir or _fenced:
        return
    rank, _ = _rank_trial()
    try:
        with open(os.path.join(gang_dir, f"done-{rank}"),  # xgtpu: disable=XGT003
                  "w") as f:
            f.write("done\n")
    except OSError as e:
        from xgboost_tpu.obs.metrics import swallowed_error
        swallowed_error("parallel.gang.mark_done", e)


def live_tombstones(gang_dir: str) -> List[str]:
    """Ranks with a ``lost-<rank>`` tombstone in the gang dir (launcher
    side: hosts declared permanently dead this job)."""
    try:
        names = os.listdir(gang_dir)
    except OSError:
        return []
    return sorted(n[len("lost-"):] for n in names if n.startswith("lost-"))

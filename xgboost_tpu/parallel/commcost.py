"""Collective-cost accounting for distributed training (VERDICT r3
item 2).

The network boundary of data-parallel tree growth is the per-depth
histogram allreduce — the role of the reference's
``histred.Allreduce`` (``updater_histmaker-inl.hpp:343-346``), whose
payload is TStats x bins x features x nodes.  Here the same payload is
``n_node x F x B x 2`` f32 per level, psum-reduced over the mesh's
data axis (``parallel/dp.py``).

This module makes that cost a NUMBER instead of prose:

  - :func:`hist_psum_bytes` — the analytic per-level/total payload;
  - :func:`hlo_collectives` — the collectives ACTUALLY present in a
    compiled XLA program, with their payload bytes (what the
    regression test pins against the analytic model);
  - :func:`project_round_time` — a compute/communication model for a
    k-chip mesh, used for the v5e-16 projection in PROFILE.md.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Tuple

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8}

# one collective op; shapes like f32[32,28,64,2].  The result type is
# everything between '=' and the opcode TOKEN (which is immediately
# followed by '('): anchoring on the paren keeps operand names like
# '%all-reduce.3' inside the operand list from matching as the opcode,
# and a strict result-type group keeps operand shapes out of the
# payload (both bugs a looser regex exhibited — caught in review).
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_COLL_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.-]+\s*=\s*"
    r"((?:\([^)]*\))|(?:[a-z0-9]+\[[0-9,]*\](?:\{[0-9,]*\})?))\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute)"
    r"(-start)?\(")


def _shapes_in(shape_list: str) -> List[Tuple[str, str]]:
    return _SHAPE_RE.findall(shape_list)


def _one_shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 0)


def hlo_collectives(hlo_text: str) -> List[Tuple[str, str, int]]:
    """[(op, shape, payload_bytes)] for every collective in an HLO
    dump (``jax.jit(f).lower(...).compile().as_text()``).

    Async pairs: a ``-start`` tuple result holds (operand-alias,
    produced buffer[, u32[] context scalars...]); context scalars are
    dropped, then the payload is the produced buffer: the LARGEST
    remaining element for all-reduce / collective-permute /
    all-gather, but the SMALLEST for reduce-scatter (its result is
    1/n_shards of the operand).  ``-done`` ops carry none."""
    out = []
    for line in hlo_text.splitlines():
        m = _COLL_RE.match(line)
        if not m:
            continue
        shapes, op, start = m.group(1), m.group(2), m.group(3)
        parsed = _shapes_in(shapes)
        if not parsed:
            continue
        sizes = [_one_shape_bytes(t, d) for t, d in parsed]
        if start and shapes.startswith("("):
            real = [s for s in sizes if s > 8] or sizes
            payload = min(real) if op == "reduce-scatter" else max(real)
        else:
            payload = sum(sizes)
        out.append((op, shapes.strip(), payload))
    return out


def hist_psum_bytes(max_depth: int, n_feat: int, n_bin: int,
                    stat_bytes: int = 8) -> Dict[int, int]:
    """Analytic per-level histogram-psum payload: ``2**d * F * B *
    stat_bytes`` (the (G, H) f32 pair = 8 bytes), for non-terminal
    levels d = 0..max_depth-1.  Matches the f32[n,F,B,2] all-reduce
    shapes the compiled program carries (test_distributed pins this)."""
    return {d: (1 << d) * n_feat * n_bin * stat_bytes
            for d in range(max_depth)}


_ROUND_MODEL_CACHE: Optional[tuple] = None  # (mtime_ns or None, model)


def fitted_round_model() -> Optional[dict]:
    """The measured compute model from ``ROUND_MODEL.json`` (written by
    ``tools/fit_round_model.py`` from a single-chip row sweep at the
    bench config), or None if no fit has been recorded.  Fields:
    ``fixed_round_s`` (per-round launch/levels overhead — the
    row-count-independent intercept) and ``per_row_s`` (the slope).
    Cached by file mtime: auto rounds-per-dispatch sizing consults this
    on EVERY fused segment plan (64 tenant lanes ask 64 times a cycle),
    and a json parse per ask is measurable host overhead."""
    global _ROUND_MODEL_CACHE
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "ROUND_MODEL.json")
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        mtime = None
    if _ROUND_MODEL_CACHE is not None and _ROUND_MODEL_CACHE[0] == mtime:
        return _ROUND_MODEL_CACHE[1]
    if mtime is None:
        _ROUND_MODEL_CACHE = (None, None)
        return None
    try:
        with open(path) as f:
            m = json.load(f)
        float(m["fixed_round_s"]), float(m["per_row_s"])
        _ROUND_MODEL_CACHE = (mtime, m)
        return m
    except Exception as e:
        # a torn/hand-edited fit file falls back to the analytic model;
        # counted so a projection silently ignoring the fit is visible
        from xgboost_tpu.obs.metrics import swallowed_error
        swallowed_error("parallel.commcost.round_model", e)
        return None


def project_round_time(rows: int, max_depth: int, n_feat: int,
                       n_bin: int, n_chips: int,
                       single_chip_round_s: float,
                       single_chip_rows: int,
                       ici_allreduce_bw: float = 1e11,
                       fixed_round_s: Optional[float] = None,
                       per_row_s: Optional[float] = None
                       ) -> Dict[str, float]:
    """Projected per-round time on a k-chip mesh.

    Model: compute = ``fixed + per_row * rows/chip`` — a fixed per-round
    launch/levels overhead plus a row-proportional term; the psum adds
    ring-allreduce time ``2 * bytes * (k-1)/k / bw`` per level (the
    levels synchronize, so comm does NOT overlap compute here — a
    conservative model).  ``ici_allreduce_bw`` defaults to 1e11 B/s
    per chip — the order of the public v5e ICI figure (4 links x ~25
    GB/s/direction on the 2D torus); it enters only the psum term,
    which is microseconds at these payloads, so the projection is
    insensitive to it.

    ``fixed_round_s`` / ``per_row_s`` default to the MEASURED fit in
    ``ROUND_MODEL.json`` (single-chip row sweep at the bench config —
    tools/fit_round_model.py; round 5, replacing round 4's assumed
    4 ms intercept).  With no fit on disk, the intercept falls back to
    that historical assumption and the slope is derived from the
    caller's measured single-chip point, so callers always pass the
    anchor (single_chip_round_s, single_chip_rows): it cross-checks
    the fit and carries the fallback.
    """
    model = fitted_round_model()
    if fixed_round_s is None:
        fixed_round_s = model["fixed_round_s"] if model else 0.004
    if per_row_s is None:
        per_row_s = (model["per_row_s"] if model
                     else max(single_chip_round_s - fixed_round_s, 0.0)
                     / single_chip_rows)
    compute = fixed_round_s + per_row_s * (rows / n_chips)
    total_bytes = sum(hist_psum_bytes(max_depth, n_feat, n_bin).values())
    comm = (2.0 * total_bytes * (n_chips - 1) / n_chips
            / ici_allreduce_bw) if n_chips > 1 else 0.0
    return {"compute_s": compute, "psum_s": comm,
            "round_s": compute + comm,
            "rounds_per_sec": 1.0 / (compute + comm),
            "psum_bytes_per_round": float(total_bytes),
            "fixed_round_s": float(fixed_round_s),
            "per_row_s": float(per_row_s),
            "fitted": bool(model)}

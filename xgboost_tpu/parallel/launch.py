"""Multi-host job launcher — the tracker/submitter analog.

The reference's cluster layer is a Python rendezvous tracker plus
submitters that spawn workers with rank/world env vars
(``subtree/rabit/tracker/rabit_tracker.py:125-309``,
``tracker/rabit_demo.py`` local multi-process with keepalive restart,
``rabit_mpi/sge/yarn``).  Under JAX the tracker itself disappears — the
JAX distributed runtime owns rendezvous — so what remains is exactly
this launcher: assign (coordinator, num_processes, process_id), spawn,
optionally restart dead workers (keepalive), and a worker-side
``init_worker()`` that calls ``jax.distributed.initialize``.

Local usage (the rabit_demo.py equivalent — N processes on one host):

    python -m xgboost_tpu.launch -n 4 [--keepalive] \
        python my_worker.py ...

Cluster usage: run the same worker command on every host with
``XGBTPU_COORD`` (host:port of process 0), ``XGBTPU_NUM_WORKER`` and
``XGBTPU_WORKER_ID`` exported by the scheduler; ``init_worker()`` picks
them up.  Workers load only their row shard (``parse_libsvm`` rank /
nparts modulo split — reference ``simple_dmatrix-inl.hpp:89-96``) and
assemble global arrays with ``jax.make_array_from_process_local_data``.

The FULL stack is multi-process capable (tests/test_launch.py proves
2-process x 2-device jobs end to end): launcher + ``init_worker``
rendezvous, the global data-parallel mesh, the distributed growth /
sketch kernels, and the high-level ``Booster``/CLI training loop —
each process holds the replicated host copy of the data, compute
shards over the global mesh, host pulls (metrics/predictions)
all-gather first (``Booster._replicated``), and ranks produce
byte-identical models (rank 0 saves, like the reference).
"""

from __future__ import annotations

import argparse
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
from typing import List, Optional

COORD_ENV = "XGBTPU_COORD"
NWORKER_ENV = "XGBTPU_NUM_WORKER"
RANK_ENV = "XGBTPU_WORKER_ID"
TRIAL_ENV = "XGBTPU_NUM_TRIAL"

#: exit code launch_local returns for an unrecovered stall (no
#: keepalive / restart budget exhausted) — worker rcs are small
STALL_RC = 142


def init_worker(local_device_count: Optional[int] = None) -> bool:
    """Initialize this process as a distributed JAX worker when the
    launcher env is present.  Returns True iff distributed mode is on.

    Call BEFORE any other jax API touches the backend.  After it,
    ``jax.devices()`` spans all workers and
    :func:`xgboost_tpu.parallel.mesh.data_parallel_mesh` builds the
    global mesh (collectives ride ICI within a slice, DCN across).
    """
    coord = os.environ.get(COORD_ENV)
    if local_device_count is None and os.environ.get("XGBTPU_LOCAL_DEVICES"):
        local_device_count = int(os.environ["XGBTPU_LOCAL_DEVICES"])
    if not coord:
        # standalone gang worker (launch_local(standalone=True) exports
        # no coordinator): still honor the virtual-device request so a
        # single-controller worker can run the mesh-fused scan over an
        # in-process multi-device mesh — the live multi-device target
        # on hosts whose backend cannot execute multi-process programs
        if local_device_count is not None:
            _force_local_devices(local_device_count)
        return False
    if RANK_ENV in os.environ:
        n = int(os.environ[NWORKER_ENV])
        rank = int(os.environ[RANK_ENV])
    else:
        # scheduler-launched worker (mpirun/srun/qsub via
        # parallel/submit.py): rank/world come from the scheduler's env
        from xgboost_tpu.parallel.submit import scheduler_rank
        rw = scheduler_rank()
        if rw is None:
            raise RuntimeError(
                f"{COORD_ENV} is set but no rank source found: export "
                f"{RANK_ENV}/{NWORKER_ENV} or launch under a scheduler "
                "(OpenMPI/PMI/Slurm/SGE)")
        rank, sched_n = rw
        n = int(os.environ.get(NWORKER_ENV, sched_n))
    if local_device_count is not None:
        _force_local_devices(local_device_count)
    import jax
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=n, process_id=rank)
    return True


def _force_local_devices(local_device_count: int) -> None:
    """Give this process a fixed virtual CPU device count and pin the
    platform.  Must run before any jax API touches the backend."""
    # CPU workers: give each process a fixed virtual device count.  An
    # explicit request REPLACES any inherited count (a parent test
    # harness or launcher may have exported its own) — the operator
    # asked for exactly this many devices.
    import re
    flags = os.environ.get("XLA_FLAGS", "")
    want = f"--xla_force_host_platform_device_count={local_device_count}"
    if "host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", want, flags)
    else:
        flags = (flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = flags
    import jax
    # virtual-CPU testing mode: pin the platform so a co-resident
    # accelerator plugin (which overrides the JAX_PLATFORMS env var
    # at import time) cannot become default_backend() and steer
    # backend-conditional code (e.g. the histogram kernel choice)
    # at a CPU-device mesh
    jax.config.update("jax_platforms", "cpu")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _reap(procs: List[Optional[subprocess.Popen]],
          grace: float = 3.0) -> None:
    """Terminate-then-kill every live child and wait() them all.  A
    survivor blocked in a collective of a doomed gang ignores SIGTERM
    (it is inside the coordination-service wait), so the grace is
    short: these processes are about to be replaced by the restart and
    their state is reconstructed from the checkpoint ring anyway."""
    for q in procs:
        if q is not None and q.poll() is None:
            q.terminate()
    for q in procs:
        if q is None:
            continue
        try:
            q.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            q.kill()
            q.wait()


def _latest_heartbeat(hb_dir: str) -> Optional[float]:
    """Newest heartbeat-file mtime across ranks (monotonic-comparable
    only against other mtimes from the same filesystem), or None when
    no rank has beaten yet."""
    latest = None
    try:
        names = os.listdir(hb_dir)
    except OSError:
        return None
    for name in names:
        if not name.startswith("hb-"):
            continue
        try:
            m = os.stat(os.path.join(hb_dir, name)).st_mtime
        except OSError:
            continue  # racing a rewrite; the next poll sees it
        if latest is None or m > latest:
            latest = m
    return latest


def launch_local(n: int, cmd: List[str], keepalive: bool = False,
                 local_devices: Optional[int] = None,
                 max_restarts: int = 10,
                 watchdog_stall_sec: float = 0.0,
                 restart_backoff_sec: float = 0.5,
                 standalone: bool = False) -> int:
    """Spawn ``n`` local worker processes running ``cmd`` (the
    rabit_demo.py submitter).

    With ``keepalive``, any nonzero worker death restarts the WHOLE gang
    with a bumped trial counter and a fresh coordinator port: a single
    restarted process cannot rejoin a live ``jax.distributed`` job, so
    recovery is whole-job restart + resume from ``checkpoint_dir`` —
    exactly the per-round-checkpoint fault model (SURVEY.md §5.3 TPU
    mapping).  The fresh port per attempt also sidesteps the
    free_port() probe/bind race.

    ``watchdog_stall_sec > 0`` extends keepalive from death-detection
    to STALL-detection (the reference's allreduce_robust timeout
    recovery, RELIABILITY.md stall matrix): every worker touches a
    per-rank heartbeat file at each round boundary
    (``mock.begin_round``), and when ALL ranks stop advancing for that
    long — a gang wedged in a collective, a worker hung in device code
    — the launcher kills and restarts the gang exactly as it would for
    a death.  The window must cover startup + the slowest single round
    (data load and jit compilation count against it until the first
    round lands).  Restarts draw from one ``max_restarts`` budget with
    jittered exponential backoff between trials
    (``restart_backoff_sec`` doubling per trial, capped at 30 s).

    ``standalone=True`` supervises WITHOUT distributed rendezvous: no
    ``XGBTPU_COORD`` is exported, so workers run single-controller and
    the launcher contributes only keepalive + the stall watchdog —
    process supervision for jobs (or containers) where the
    ``jax.distributed`` mesh path is unavailable.
    """
    from xgboost_tpu.obs import event
    from xgboost_tpu.profiling import reliability_metrics
    from xgboost_tpu.reliability.deadline import backoff_delay

    hb_root = None
    if watchdog_stall_sec > 0:
        hb_root = tempfile.mkdtemp(prefix="xgbtpu_hb_")
    try:
        trial = 0
        while True:
            coord = f"localhost:{free_port()}"
            t_attempt = time.perf_counter()  # duration anchor (XGT006)
            hb_dir = None
            if hb_root is not None:
                # fresh beacon dir per attempt: a stale heartbeat from
                # the previous trial must not vouch for this one
                hb_dir = os.path.join(hb_root, f"t{trial}")
                os.makedirs(hb_dir, exist_ok=True)

            def spawn(rank: int) -> subprocess.Popen:
                env = dict(os.environ)
                if not standalone:
                    env[COORD_ENV] = coord
                env[NWORKER_ENV] = str(n)
                env[RANK_ENV] = str(rank)
                env[TRIAL_ENV] = str(trial)
                if hb_dir is not None:
                    env["XGBTPU_HEARTBEAT_DIR"] = hb_dir
                if local_devices is not None:
                    env["XGBTPU_LOCAL_DEVICES"] = str(local_devices)
                return subprocess.Popen(cmd, env=env)

            procs: List[Optional[subprocess.Popen]] = [spawn(r)
                                                       for r in range(n)]
            # stall clock: progress = the newest heartbeat mtime CHANGED
            # since the last poll (mtimes are wall-clock, so they are
            # only ever compared with each other; the silence DURATION
            # is measured on the monotonic clock, XGT006)
            last_progress = time.monotonic()
            last_hb_seen: Optional[float] = None
            failed_rc = None
            stalled = False
            while any(p is not None for p in procs) and failed_rc is None:
                time.sleep(0.2)
                for r, p in enumerate(procs):
                    if p is None or p.poll() is None:
                        continue
                    if p.returncode == 0:
                        procs[r] = None
                    else:
                        failed_rc = p.returncode
                        reliability_metrics().launch_worker_deaths.inc()
                        event("launch.worker_death", rank=r,
                              rc=p.returncode, trial=trial)
                        print(f"[launch] worker {r} died "
                              f"(rc={p.returncode}, trial {trial})",
                              file=sys.stderr)
                        break
                if (failed_rc is None and hb_dir is not None
                        and any(p is not None for p in procs)):
                    # stall watchdog: progress = a NEW heartbeat from
                    # any rank since the last poll (spawn time until
                    # the first one lands — startup counts against the
                    # window, so it must cover compile time)
                    hb = _latest_heartbeat(hb_dir)
                    if hb is not None and hb != last_hb_seen:
                        last_hb_seen = hb
                        last_progress = time.monotonic()
                    silent = time.monotonic() - last_progress
                    if silent > watchdog_stall_sec:
                        stalled = True
                        event("launch.stall", trial=trial,
                              silent_sec=round(silent, 2),
                              stall_window_sec=watchdog_stall_sec)
                        print(f"[launch] STALL: no rank advanced for "
                              f"{silent:.1f}s (> {watchdog_stall_sec}s"
                              f", trial {trial}); killing the gang",
                              file=sys.stderr)
                        break
            if failed_rc is None and not stalled:
                return 0
            t_detect = time.perf_counter()
            _reap(procs)
            if not keepalive or trial >= max_restarts:
                return STALL_RC if stalled else failed_rc
            trial += 1
            reason = "stall" if stalled else "death"
            reliability_metrics().launch_restarts.inc(reason)
            event("launch.restart", reason=reason, trial=trial,
                  attempt_sec=round(t_detect - t_attempt, 2))
            # jittered exponential backoff between trials (the shared
            # reliability helper): a crash loop (bad input, wedged
            # device) must not hot-spin the host it is supposed to be
            # recovering on
            delay = backoff_delay(trial, base=restart_backoff_sec,
                                  cap=30.0)
            # recovery-cost accounting (RECOVERY.md): attempt wall time
            # up to detection, plus the reap (SIGTERM the survivors)
            print(f"[launch] restarting all {n} workers, trial {trial} "
                  f"(reason {reason}, attempt ran "
                  f"{t_detect - t_attempt:.2f}s, "
                  f"reap {time.perf_counter() - t_detect:.2f}s, "
                  f"backoff {delay:.2f}s)",
                  file=sys.stderr)
            time.sleep(delay)
    finally:
        if hb_root is not None:
            shutil.rmtree(hb_root, ignore_errors=True)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m xgboost_tpu.launch",
        description="spawn N distributed workers (rabit_demo.py analog)")
    ap.add_argument("-n", "--nworker", type=int, required=True)
    ap.add_argument("--keepalive", action="store_true",
                    help="restart workers that die nonzero (and gangs "
                         "the stall watchdog kills)")
    ap.add_argument("--local-devices", type=int, default=None,
                    help="virtual CPU devices per worker (testing)")
    ap.add_argument("--watchdog-stall-sec", type=float, default=0.0,
                    help="kill+restart the gang when ALL ranks stop "
                         "advancing (heartbeats at round boundaries) "
                         "for this long; must cover startup + the "
                         "slowest round (0 = off)")
    ap.add_argument("--max-restarts", type=int, default=10,
                    help="total gang restarts (death + stall) before "
                         "giving up")
    ap.add_argument("--restart-backoff-sec", type=float, default=0.5,
                    help="base backoff between gang restarts "
                         "(doubles per trial, jittered, capped 30s)")
    ap.add_argument("--standalone", action="store_true",
                    help="supervise without distributed rendezvous "
                         "(no XGBTPU_COORD): keepalive + watchdog only")
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if args.cmd and args.cmd[0] == "--":
        args.cmd = args.cmd[1:]
    if not args.cmd:
        ap.error("missing worker command")
    return launch_local(args.nworker, args.cmd, keepalive=args.keepalive,
                        local_devices=args.local_devices,
                        max_restarts=args.max_restarts,
                        watchdog_stall_sec=args.watchdog_stall_sec,
                        restart_backoff_sec=args.restart_backoff_sec,
                        standalone=args.standalone)


if __name__ == "__main__":
    sys.exit(main())

"""Multi-host job launcher — the tracker/submitter analog.

The reference's cluster layer is a Python rendezvous tracker plus
submitters that spawn workers with rank/world env vars
(``subtree/rabit/tracker/rabit_tracker.py:125-309``,
``tracker/rabit_demo.py`` local multi-process with keepalive restart,
``rabit_mpi/sge/yarn``).  Under JAX the tracker itself disappears — the
JAX distributed runtime owns rendezvous — so what remains is exactly
this launcher: assign (coordinator, num_processes, process_id), spawn,
optionally restart dead workers (keepalive), and a worker-side
``init_worker()`` that calls ``jax.distributed.initialize``.

Local usage (the rabit_demo.py equivalent — N processes on one host):

    python -m xgboost_tpu.launch -n 4 [--keepalive] \
        python my_worker.py ...

Cluster usage: run the same worker command on every host with
``XGBTPU_COORD`` (host:port of process 0), ``XGBTPU_NUM_WORKER`` and
``XGBTPU_WORKER_ID`` exported by the scheduler; ``init_worker()`` picks
them up.  Workers load only their row shard (``parse_libsvm`` rank /
nparts modulo split — reference ``simple_dmatrix-inl.hpp:89-96``) and
assemble global arrays with ``jax.make_array_from_process_local_data``.

The FULL stack is multi-process capable (tests/test_launch.py proves
2-process x 2-device jobs end to end): launcher + ``init_worker``
rendezvous, the global data-parallel mesh, the distributed growth /
sketch kernels, and the high-level ``Booster``/CLI training loop —
each process holds the replicated host copy of the data, compute
shards over the global mesh, host pulls (metrics/predictions)
all-gather first (``Booster._replicated``), and ranks produce
byte-identical models (rank 0 saves, like the reference).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

COORD_ENV = "XGBTPU_COORD"
NWORKER_ENV = "XGBTPU_NUM_WORKER"
RANK_ENV = "XGBTPU_WORKER_ID"
TRIAL_ENV = "XGBTPU_NUM_TRIAL"

#: exit codes (registry: reliability/rc.py, lint rule XGT016) —
#: STALL_RC for an unrecovered stall (no keepalive / restart budget
#: exhausted), COORD_FENCED_RC for a coordinator superseded by a
#: standby takeover (it must stop supervising and report neither
#: success nor worker failure); re-exported here for callers that
#: import them from the launcher
from xgboost_tpu.reliability.rc import (COORD_FENCED_RC,  # noqa: F401
                                        STALL_RC)
#: grow-back signal file in the gang dir: a replacement worker (or the
#: operator) touches it to ask a DEGRADED gang to re-expand to full
#: size at the next segment boundary (= checkpoint resume point)
GROW_SIGNAL = "grow"


def init_worker(local_device_count: Optional[int] = None) -> bool:
    """Initialize this process as a distributed JAX worker when the
    launcher env is present.  Returns True iff distributed mode is on.

    Call BEFORE any other jax API touches the backend.  After it,
    ``jax.devices()`` spans all workers and
    :func:`xgboost_tpu.parallel.mesh.data_parallel_mesh` builds the
    global mesh (collectives ride ICI within a slice, DCN across).
    """
    coord = os.environ.get(COORD_ENV)
    if local_device_count is None and os.environ.get("XGBTPU_LOCAL_DEVICES"):
        local_device_count = int(os.environ["XGBTPU_LOCAL_DEVICES"])
    if not coord:
        # standalone gang worker (launch_local(standalone=True) exports
        # no coordinator): still honor the virtual-device request so a
        # single-controller worker can run the mesh-fused scan over an
        # in-process multi-device mesh — the live multi-device target
        # on hosts whose backend cannot execute multi-process programs
        if local_device_count is not None:
            _force_local_devices(local_device_count)
        return False
    if RANK_ENV in os.environ:
        n = int(os.environ[NWORKER_ENV])
        rank = int(os.environ[RANK_ENV])
    else:
        # scheduler-launched worker (mpirun/srun/qsub via
        # parallel/submit.py): rank/world come from the scheduler's env
        from xgboost_tpu.parallel.submit import scheduler_rank
        rw = scheduler_rank()
        if rw is None:
            raise RuntimeError(
                f"{COORD_ENV} is set but no rank source found: export "
                f"{RANK_ENV}/{NWORKER_ENV} or launch under a scheduler "
                "(OpenMPI/PMI/Slurm/SGE)")
        rank, sched_n = rw
        n = int(os.environ.get(NWORKER_ENV, sched_n))
    if local_device_count is not None:
        _force_local_devices(local_device_count)
    import jax
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=n, process_id=rank)
    return True


def _force_local_devices(local_device_count: int) -> None:
    """Give this process a fixed virtual CPU device count and pin the
    platform.  Must run before any jax API touches the backend."""
    # CPU workers: give each process a fixed virtual device count.  An
    # explicit request REPLACES any inherited count (a parent test
    # harness or launcher may have exported its own) — the operator
    # asked for exactly this many devices.
    import re
    flags = os.environ.get("XLA_FLAGS", "")
    want = f"--xla_force_host_platform_device_count={local_device_count}"
    if "host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", want, flags)
    else:
        flags = (flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = flags
    import jax
    # virtual-CPU testing mode: pin the platform so a co-resident
    # accelerator plugin (which overrides the JAX_PLATFORMS env var
    # at import time) cannot become default_backend() and steer
    # backend-conditional code (e.g. the histogram kernel choice)
    # at a CPU-device mesh
    jax.config.update("jax_platforms", "cpu")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _reap(procs: List[Optional[subprocess.Popen]],
          grace: float = 3.0) -> None:
    """Terminate-then-kill every live child and wait() them all.  A
    survivor blocked in a collective of a doomed gang ignores SIGTERM
    (it is inside the coordination-service wait), so the grace is
    short: these processes are about to be replaced by the restart and
    their state is reconstructed from the checkpoint ring anyway."""
    for q in procs:
        if q is not None and q.poll() is None:
            q.terminate()
    for q in procs:
        if q is None:
            continue
        try:
            q.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            q.kill()
            q.wait()


def _latest_heartbeat(hb_dir: str) -> Optional[float]:
    """Newest heartbeat-file mtime across ranks (monotonic-comparable
    only against other mtimes from the same filesystem), or None when
    no rank has beaten yet."""
    latest = None
    try:
        names = os.listdir(hb_dir)
    except OSError:
        return None
    for name in names:
        if not name.startswith("hb-"):
            continue
        try:
            m = os.stat(os.path.join(hb_dir, name)).st_mtime
        except OSError:
            continue  # racing a rewrite; the next poll sees it
        if latest is None or m > latest:
            latest = m
    return latest


def plan_degrade(n: int, local_devices: Optional[int],
                 min_workers: int = 1
                 ) -> Optional[Tuple[int, Optional[int]]]:
    """The largest viable smaller gang plan, or None when already
    minimal.  Device counts HALVE (the mesh-size-invariance family PR 12
    proved bit-identical is the power-of-two ladder 8/4/2/1); worker
    counts step down by one (the rank/nparts modulo row split re-shards
    at any count).  Pure — the chaos selftest drives it directly."""
    if local_devices is not None and local_devices > 1:
        return n, local_devices // 2
    if n > max(1, min_workers):
        return n - 1, local_devices
    return None


def _write_state(state_path: str, state: dict, holder: str) -> None:
    """Snapshot coordinator state (gang roster, attempt counter, plan)
    atomically with the standard CRC footer — the same discipline as a
    ring member, because a restarted coordinator re-adopting live
    workers off a torn snapshot would be its own split brain."""
    from xgboost_tpu.reliability.integrity import add_footer, atomic_write
    payload = json.dumps(dict(state, holder=holder),
                         sort_keys=True).encode()
    atomic_write(state_path, add_footer(payload))


def _read_state(state_path: str) -> Optional[dict]:
    """Load + CRC-verify a coordinator snapshot; None when missing or
    unusable (a corrupt snapshot means fresh-start, not crash)."""
    from xgboost_tpu.reliability.integrity import (read_file,
                                                   verify_model_bytes)
    try:
        raw = read_file(state_path)
    except OSError:
        return None
    try:
        payload = verify_model_bytes(raw, name=state_path, warn=False)
        return json.loads(payload.decode())
    except ValueError as e:
        from xgboost_tpu.obs import event
        event("launch.state_corrupt", path=state_path, error=str(e))
        print(f"[launch] coordinator state {state_path} unusable "
              f"({e}); starting fresh", file=sys.stderr)
        return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # alive, just not ours to signal


def _reap_pids(pids: List[int], grace: float = 3.0) -> None:
    """The :func:`_reap` discipline for ADOPTED workers — non-children
    this coordinator cannot ``wait()``: SIGTERM, poll for death within
    the grace, then SIGKILL."""
    for pid in pids:
        if _pid_alive(pid):
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass  # died between the check and the signal
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if not any(_pid_alive(p) for p in pids):
            return
        time.sleep(0.1)
    for pid in pids:
        if _pid_alive(pid):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
    while any(_pid_alive(p) for p in pids):
        time.sleep(0.05)


def _touch(path: str) -> None:
    """mtime-bump a beacon file (created on first touch); never raises
    — a beacon failure must not kill a healthy coordinator loop."""
    try:
        os.utime(path, None)
    except OSError:
        try:
            with open(path, "a"):  # xgtpu: disable=XGT003 — liveness beacon
                pass
        except OSError as e:
            from xgboost_tpu.obs.metrics import swallowed_error
            swallowed_error("parallel.launch.beacon", e, emit_event=False)


def _wait_for_stale_lease(state_path: str, lease_sec: float,
                          poll: float = 0.25) -> None:
    """Standby-coordinator wait (the placer's single-holder-lease idea
    on a file): the primary renews its lease by mtime-bumping the state
    snapshot every poll tick; block until that stops for ``lease_sec``
    (or the file never appears for that long) — then the primary is
    dead and this process may take over."""
    last_mtime: Optional[float] = None
    last_change = time.monotonic()
    while True:
        try:
            m = os.stat(state_path).st_mtime
        except OSError:
            m = None
        if m is not None and m != last_mtime:
            last_mtime = m
            last_change = time.monotonic()
        elif time.monotonic() - last_change > lease_sec:
            return
        time.sleep(poll)


def launch_local(n: int, cmd: List[str], keepalive: bool = False,
                 local_devices: Optional[int] = None,
                 max_restarts: int = 10,
                 watchdog_stall_sec: float = 0.0,
                 restart_backoff_sec: float = 0.5,
                 standalone: bool = False,
                 degrade_after: int = 0,
                 min_workers: int = 1,
                 gang_partition_sec: float = 0.0,
                 gang_dir: Optional[str] = None,
                 state_path: Optional[str] = None,
                 standby: bool = False,
                 coord_lease_sec: float = 10.0) -> int:
    """Spawn ``n`` local worker processes running ``cmd`` (the
    rabit_demo.py submitter).

    With ``keepalive``, any nonzero worker death restarts the WHOLE gang
    with a bumped trial counter and a fresh coordinator port: a single
    restarted process cannot rejoin a live ``jax.distributed`` job, so
    recovery is whole-job restart + resume from ``checkpoint_dir`` —
    exactly the per-round-checkpoint fault model (SURVEY.md §5.3 TPU
    mapping).  The fresh port per attempt also sidesteps the
    free_port() probe/bind race.

    ``watchdog_stall_sec > 0`` extends keepalive from death-detection
    to STALL-detection (the reference's allreduce_robust timeout
    recovery, RELIABILITY.md stall matrix): every worker touches a
    per-rank heartbeat file at each round boundary
    (``mock.begin_round``), and when ALL ranks stop advancing for that
    long — a gang wedged in a collective, a worker hung in device code
    — the launcher kills and restarts the gang exactly as it would for
    a death.  The window must cover startup + the slowest single round
    (data load and jit compilation count against it until the first
    round lands).  Restarts draw from one ``max_restarts`` budget with
    jittered exponential backoff between trials
    (``restart_backoff_sec`` doubling per trial, capped at 30 s).

    ``standalone=True`` supervises WITHOUT distributed rendezvous: no
    ``XGBTPU_COORD`` is exported, so workers run single-controller and
    the launcher contributes only keepalive + the stall watchdog —
    process supervision for jobs (or containers) where the
    ``jax.distributed`` mesh path is unavailable.

    **Elastic degraded-mesh recovery** (RECOVERY.md degraded-mode
    matrix) arms when any of the gang knobs is set:

    - ``degrade_after > 0``: after that many consecutive failed
      attempts at the current size — or IMMEDIATELY on a permanent
      host loss (worker rc ``HOST_LOSS_RC`` / ``lost-<rank>``
      tombstone) — the gang is re-planned at the largest viable
      smaller size (:func:`plan_degrade`) and resumes from the last
      segment-boundary ring member; mesh-size invariance (PR 12) makes
      the finished model bit-identical to an uninterrupted run.
    - While degraded, a ``grow`` file appearing in the gang dir (a
      replacement worker registered) re-expands the gang to full size
      at the next segment boundary — the restart resumes from the last
      boundary's checkpoint, which IS the boundary.
    - ``gang_partition_sec > 0``: the launcher maintains a ``coord``
      beacon in the gang dir; a worker that cannot see it advance for
      that long self-fences (``parallel/gang.py``) — it stops writing
      checkpoints/heartbeats and dies ``FENCE_RC``, so a healed
      partition can never put two writers on the ring.
    - ``state_path`` (default ``<gang_dir>/coord-state.json``):
      coordinator state (gang roster + pids, attempt counter, current
      plan) snapshots via ``atomic_write``+CRC at every attempt
      boundary; a SIGKILL'd coordinator restarted with the same path
      RE-ADOPTS the live workers (pid-polled, clean exits visible via
      ``done-<rank>`` markers) instead of orphaning them.
    - ``standby=True``: warm-standby coordinator (the placer's
      single-holder-lease pattern on a file): block until the
      primary's lease — the state-file mtime it bumps every poll tick
      — goes stale for ``coord_lease_sec``, then take over and adopt.
      A superseded primary notices the holder change and exits
      ``COORD_FENCED_RC`` without touching the workers.
    """
    from xgboost_tpu.obs import event
    from xgboost_tpu.parallel import gang as gangmod
    from xgboost_tpu.profiling import reliability_metrics
    from xgboost_tpu.reliability.deadline import backoff_delay

    rm = reliability_metrics()
    gang_on = bool(degrade_after or gang_partition_sec > 0 or gang_dir
                   or state_path or standby)
    own_gang_dir = False
    if gang_on:
        if gang_dir is None:
            gang_dir = tempfile.mkdtemp(prefix="xgbtpu_gang_")
            own_gang_dir = True
        else:
            os.makedirs(gang_dir, exist_ok=True)
        if state_path is None:
            state_path = os.path.join(gang_dir, "coord-state.json")
    holder = f"pid{os.getpid()}"

    if standby:
        print(f"[launch] standby coordinator: watching {state_path} "
              f"(lease {coord_lease_sec}s)", file=sys.stderr)
        _wait_for_stale_lease(state_path, coord_lease_sec)
        event("launch.standby_takeover", state_path=state_path,
              holder=holder)
        print(f"[launch] standby takeover: lease stale, {holder} is "
              "now the coordinator", file=sys.stderr)

    hb_root = None
    if watchdog_stall_sec > 0:
        hb_root = tempfile.mkdtemp(prefix="xgbtpu_hb_")

    # the gang plan: full size is what the caller asked for; the
    # current size shrinks on degrade and restores on grow-back
    cur_n, cur_devices = n, local_devices
    degraded = False
    trial = 0
    fails_at_size = 0

    # coordinator failover: a previous holder's snapshot with every
    # worker pid still alive means ADOPT, not respawn — a SIGKILL'd
    # coordinator must not orphan (or needlessly kill) a healthy gang
    adopt_pids: Optional[Dict[int, int]] = None
    adopt_hb_dir: Optional[str] = None
    if gang_on and os.path.exists(state_path):
        st = _read_state(state_path)
        if st and int(st.get("full_n", -1)) == n:
            trial = int(st.get("trial", 0))
            cur_n = int(st.get("cur_n", n))
            cd = st.get("cur_devices")
            cur_devices = int(cd) if cd is not None else None
            degraded = bool(st.get("degraded"))
            workers = {int(w["rank"]): int(w["pid"])
                       for w in st.get("workers", [])}
            live = {r: p for r, p in workers.items() if _pid_alive(p)}
            done_marks = {r for r in workers
                          if os.path.exists(os.path.join(
                              gang_dir, f"done-{r}"))}
            if workers and all(r in live or r in done_marks
                               for r in workers):
                adopt_pids = workers
                adopt_hb_dir = st.get("hb_dir")
            elif live:
                # partial gang: the stragglers are doomed (their gang
                # is broken) — reap them and restart normally
                _reap_pids(list(live.values()))

    try:
        while True:
            rm.launch_mesh_size.set(cur_n * (cur_devices or 1))
            rm.launch_degraded.set(1 if degraded else 0)
            t_attempt = time.perf_counter()  # duration anchor (XGT006)
            adopted = adopt_pids is not None
            grow_path = (os.path.join(gang_dir, GROW_SIGNAL)
                         if gang_on else None)

            if adopted:
                live_pids = dict(adopt_pids)
                adopt_pids = None
                hb_dir = adopt_hb_dir
                event("launch.adopt", trial=trial,
                      workers=sorted(live_pids.values()))
                print(f"[launch] re-adopting live gang "
                      f"{sorted(live_pids.items())} (trial {trial})",
                      file=sys.stderr)
                _write_state(state_path, {
                    "full_n": n, "cur_n": cur_n,
                    "cur_devices": cur_devices, "degraded": degraded,
                    "trial": trial, "hb_dir": hb_dir,
                    "gang_dir": gang_dir,
                    "workers": [{"rank": r, "pid": p}
                                for r, p in live_pids.items()],
                }, holder)
                procs = []
            else:
                coord = f"localhost:{free_port()}"
                hb_dir = None
                if hb_root is not None:
                    # fresh beacon dir per attempt: a stale heartbeat
                    # from the previous trial must not vouch for this
                    hb_dir = os.path.join(hb_root, f"t{trial}")
                    os.makedirs(hb_dir, exist_ok=True)
                if gang_on:
                    # stale completion markers must not vouch for the
                    # ranks of THIS attempt
                    for name in os.listdir(gang_dir):
                        if name.startswith("done-"):
                            try:
                                os.remove(os.path.join(gang_dir, name))
                            except OSError:
                                pass  # racing a concurrent cleaner
                    _touch(os.path.join(gang_dir, gangmod.BEACON_NAME))

                def spawn(rank: int) -> subprocess.Popen:
                    env = dict(os.environ)
                    if not standalone:
                        env[COORD_ENV] = coord
                    env[NWORKER_ENV] = str(cur_n)
                    env[RANK_ENV] = str(rank)
                    env[TRIAL_ENV] = str(trial)
                    if hb_dir is not None:
                        env["XGBTPU_HEARTBEAT_DIR"] = hb_dir
                    if cur_devices is not None:
                        env["XGBTPU_LOCAL_DEVICES"] = str(cur_devices)
                    if gang_on:
                        env[gangmod.GANG_DIR_ENV] = gang_dir
                        if gang_partition_sec > 0:
                            env[gangmod.PARTITION_SEC_ENV] = str(
                                gang_partition_sec)
                        if degraded:
                            env[gangmod.DEGRADED_ENV] = "1"
                        else:
                            env.pop(gangmod.DEGRADED_ENV, None)
                    return subprocess.Popen(cmd, env=env)

                procs = [spawn(r) for r in range(cur_n)]
                live_pids = {}
                if gang_on:
                    # attempt-boundary snapshot: everything a restarted
                    # coordinator needs to re-adopt this exact gang
                    _write_state(state_path, {
                        "full_n": n, "cur_n": cur_n,
                        "cur_devices": cur_devices,
                        "degraded": degraded, "trial": trial,
                        "hb_dir": hb_dir, "gang_dir": gang_dir,
                        "workers": [{"rank": r, "pid": p.pid}
                                    for r, p in enumerate(procs)],
                    }, holder)

            procs_left: List[Optional[subprocess.Popen]] = list(procs)
            done_ranks: set = set()
            # stall clock: progress = the newest heartbeat mtime CHANGED
            # since the last poll (mtimes are wall-clock, so they are
            # only ever compared with each other; the silence DURATION
            # is measured on the monotonic clock, XGT006)
            last_progress = time.monotonic()
            last_hb_seen: Optional[float] = None
            failed_rc = None
            host_lost = False
            stalled = False
            grow = False
            superseded = False
            tick = 0

            def gang_alive() -> bool:
                if adopted:
                    return any(r not in done_ranks for r in live_pids)
                return any(p is not None for p in procs_left)

            while gang_alive() and failed_rc is None:
                time.sleep(0.2)
                tick += 1
                if gang_on:
                    # coordinator liveness beacon (workers fence off
                    # its staleness) + lease renewal for any standby
                    _touch(os.path.join(gang_dir, gangmod.BEACON_NAME))
                    _touch(state_path)
                    if tick % 10 == 0:
                        st = _read_state(state_path)
                        if st is not None and st.get("holder") != holder:
                            superseded = True
                            break
                    if degraded and os.path.exists(grow_path):
                        grow = True
                        try:
                            os.remove(grow_path)
                        except OSError:
                            pass  # signal already consumed either way
                        break
                if adopted:
                    for r, pid in live_pids.items():
                        if r in done_ranks or _pid_alive(pid):
                            continue
                        if os.path.exists(os.path.join(
                                gang_dir, f"done-{r}")):
                            done_ranks.add(r)
                            continue
                        failed_rc = 1  # unwaitable: rc unknowable
                        rm.launch_worker_deaths.inc()
                        event("launch.worker_death", rank=r, rc=None,
                              trial=trial, adopted=True)
                        print(f"[launch] adopted worker {r} (pid {pid})"
                              f" died without a done marker "
                              f"(trial {trial})", file=sys.stderr)
                        break
                else:
                    for r, p in enumerate(procs_left):
                        if p is None or p.poll() is None:
                            continue
                        if p.returncode == 0:
                            procs_left[r] = None
                        else:
                            failed_rc = p.returncode
                            if p.returncode == gangmod.HOST_LOSS_RC:
                                host_lost = True
                            rm.launch_worker_deaths.inc()
                            event("launch.worker_death", rank=r,
                                  rc=p.returncode, trial=trial)
                            print(f"[launch] worker {r} died "
                                  f"(rc={p.returncode}, trial {trial})",
                                  file=sys.stderr)
                            break
                if (failed_rc is None and hb_dir is not None
                        and gang_alive()):
                    # stall watchdog: progress = a NEW heartbeat from
                    # any rank since the last poll (spawn time until
                    # the first one lands — startup counts against the
                    # window, so it must cover compile time)
                    hb = _latest_heartbeat(hb_dir)
                    if hb is not None and hb != last_hb_seen:
                        last_hb_seen = hb
                        last_progress = time.monotonic()
                    silent = time.monotonic() - last_progress
                    if silent > watchdog_stall_sec:
                        stalled = True
                        event("launch.stall", trial=trial,
                              silent_sec=round(silent, 2),
                              stall_window_sec=watchdog_stall_sec)
                        print(f"[launch] STALL: no rank advanced for "
                              f"{silent:.1f}s (> {watchdog_stall_sec}s"
                              f", trial {trial}); killing the gang",
                              file=sys.stderr)
                        break

            if superseded:
                # a standby took the lease: the workers are THEIRS now
                # — touching them (or the beacon, or the state file)
                # from here would be exactly the two-coordinator race
                # the single-holder lease exists to prevent
                event("launch.coord_fenced", trial=trial, holder=holder)
                print(f"[launch] coordinator fenced: state holder "
                      f"changed under {holder}; exiting "
                      f"rc={COORD_FENCED_RC} without touching the "
                      "gang", file=sys.stderr)
                return COORD_FENCED_RC
            if failed_rc is None and not stalled and not grow:
                if gang_on:
                    try:
                        os.remove(state_path)  # job done: nothing to adopt
                    except OSError:
                        pass  # never written / already gone
                return 0
            t_detect = time.perf_counter()
            if adopted:
                _reap_pids([p for r, p in live_pids.items()
                            if r not in done_ranks])
            else:
                _reap(procs_left)

            if grow:
                trial += 1
                prev = (cur_n, cur_devices)
                cur_n, cur_devices = n, local_devices
                degraded = False
                fails_at_size = 0
                rm.launch_growbacks.inc()
                rm.launch_restarts.inc("growback")
                event("launch.growback", trial=trial,
                      from_size=prev[0] * (prev[1] or 1),
                      to_size=cur_n * (cur_devices or 1))
                print(f"[launch] GROW-BACK: replacement registered; "
                      f"re-expanding {prev[0]}x{prev[1] or 1} -> "
                      f"{cur_n}x{cur_devices or 1} from the last "
                      f"segment boundary (trial {trial})",
                      file=sys.stderr)
                continue  # a healthy gang was cut: restart immediately

            if not keepalive or trial >= max_restarts:
                return STALL_RC if stalled else failed_rc
            trial += 1
            fails_at_size += 1
            tombs = gangmod.live_tombstones(gang_dir) if gang_on else []
            reason = ("stall" if stalled
                      else "host_loss" if host_lost or tombs
                      else "fence" if failed_rc == gangmod.FENCE_RC
                      else "death")
            rm.launch_restarts.inc(reason)
            event("launch.restart", reason=reason, trial=trial,
                  attempt_sec=round(t_detect - t_attempt, 2))

            # degraded-mode re-plan: immediately on permanent host
            # loss, or after degrade_after consecutive same-size
            # failures; the resume point is the last segment-boundary
            # ring member, and PR 12's mesh-size invariance keeps the
            # finished model bit-identical at the smaller size
            if gang_on and (host_lost or tombs
                            or (degrade_after > 0
                                and fails_at_size >= degrade_after)):
                plan = plan_degrade(cur_n, cur_devices, min_workers)
                if plan is not None:
                    prev = (cur_n, cur_devices)
                    cur_n, cur_devices = plan
                    degraded = True
                    fails_at_size = 0
                    event("launch.degrade", trial=trial,
                          reason=("host_loss" if host_lost or tombs
                                  else "restart_budget"),
                          from_size=prev[0] * (prev[1] or 1),
                          to_size=cur_n * (cur_devices or 1))
                    print(f"[launch] DEGRADE: re-planning "
                          f"{prev[0]}x{prev[1] or 1} -> "
                          f"{cur_n}x{cur_devices or 1} "
                          f"({'host loss' if host_lost or tombs else 'restart budget'}"
                          f", trial {trial}); resuming from the last "
                          "segment boundary", file=sys.stderr)
                    for t in tombs:  # consumed: no longer scheduled
                        try:
                            os.remove(os.path.join(gang_dir, f"lost-{t}"))
                        except OSError:
                            pass
                else:
                    print("[launch] cannot degrade below "
                          f"{cur_n}x{cur_devices or 1}; retrying at "
                          "the same size", file=sys.stderr)

            # jittered exponential backoff between trials (the shared
            # reliability helper): a crash loop (bad input, wedged
            # device) must not hot-spin the host it is supposed to be
            # recovering on
            delay = backoff_delay(trial, base=restart_backoff_sec,
                                  cap=30.0)
            # recovery-cost accounting (RECOVERY.md): attempt wall time
            # up to detection, plus the reap (SIGTERM the survivors)
            print(f"[launch] restarting all {cur_n} workers, trial "
                  f"{trial} (reason {reason}, attempt ran "
                  f"{t_detect - t_attempt:.2f}s, "
                  f"reap {time.perf_counter() - t_detect:.2f}s, "
                  f"backoff {delay:.2f}s)",
                  file=sys.stderr)
            time.sleep(delay)
    finally:
        if hb_root is not None:
            shutil.rmtree(hb_root, ignore_errors=True)
        if own_gang_dir:
            shutil.rmtree(gang_dir, ignore_errors=True)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m xgboost_tpu.launch",
        description="spawn N distributed workers (rabit_demo.py analog)")
    ap.add_argument("-n", "--nworker", type=int, required=True)
    ap.add_argument("--keepalive", action="store_true",
                    help="restart workers that die nonzero (and gangs "
                         "the stall watchdog kills)")
    ap.add_argument("--local-devices", type=int, default=None,
                    help="virtual CPU devices per worker (testing)")
    ap.add_argument("--watchdog-stall-sec", type=float, default=0.0,
                    help="kill+restart the gang when ALL ranks stop "
                         "advancing (heartbeats at round boundaries) "
                         "for this long; must cover startup + the "
                         "slowest round (0 = off)")
    ap.add_argument("--max-restarts", type=int, default=10,
                    help="total gang restarts (death + stall) before "
                         "giving up")
    ap.add_argument("--restart-backoff-sec", type=float, default=0.5,
                    help="base backoff between gang restarts "
                         "(doubles per trial, jittered, capped 30s)")
    ap.add_argument("--standalone", action="store_true",
                    help="supervise without distributed rendezvous "
                         "(no XGBTPU_COORD): keepalive + watchdog only")
    ap.add_argument("--degrade-after", type=int, default=0,
                    help="after this many consecutive failed attempts "
                         "at the current size (or immediately on a "
                         "permanent host loss), re-plan the gang at "
                         "the largest viable smaller size and resume "
                         "from the last segment boundary (0 = off)")
    ap.add_argument("--min-workers", type=int, default=1,
                    help="never degrade below this many workers")
    ap.add_argument("--gang-partition-sec", type=float, default=0.0,
                    help="workers self-fence (stop checkpoint/beacon "
                         "writes, exit 143) after this long without a "
                         "fresh coordinator beacon (0 = off)")
    ap.add_argument("--gang-dir", default=None,
                    help="shared gang-protocol directory (beacon, "
                         "tombstones, grow signal); default: a fresh "
                         "tempdir, removed on exit")
    ap.add_argument("--state-path", default=None,
                    help="coordinator-state snapshot (CRC-footered "
                         "JSON, atomic): restart with the same path to "
                         "re-adopt a live gang after coordinator death "
                         "(default: <gang-dir>/coord-state.json)")
    ap.add_argument("--standby", action="store_true",
                    help="warm-standby coordinator: block until the "
                         "primary's lease on --state-path goes stale, "
                         "then take over and adopt its workers")
    ap.add_argument("--coord-lease-sec", type=float, default=10.0,
                    help="coordinator lease: the primary bumps the "
                         "state-file mtime every poll tick; a standby "
                         "takes over after this long without a bump")
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if args.cmd and args.cmd[0] == "--":
        args.cmd = args.cmd[1:]
    if not args.cmd:
        ap.error("missing worker command")
    return launch_local(args.nworker, args.cmd, keepalive=args.keepalive,
                        local_devices=args.local_devices,
                        max_restarts=args.max_restarts,
                        watchdog_stall_sec=args.watchdog_stall_sec,
                        restart_backoff_sec=args.restart_backoff_sec,
                        standalone=args.standalone,
                        degrade_after=args.degrade_after,
                        min_workers=args.min_workers,
                        gang_partition_sec=args.gang_partition_sec,
                        gang_dir=args.gang_dir,
                        state_path=args.state_path,
                        standby=args.standby,
                        coord_lease_sec=args.coord_lease_sec)


if __name__ == "__main__":
    sys.exit(main())

"""Fault injection for the collective/training seam.

The reference's ``AllreduceMock`` kills a worker at an exact
``(rank, version, seqno, ntrial)`` collective call
(``subtree/rabit/src/allreduce_mock.h:37-44,166-172``); a keepalive
wrapper restarts it and recovery must reproduce bit-identical state
(``tracker/rabit_demo.py:26-40``, ``test/local_recover.cc:30-60``).

Under XLA, collectives inside a jitted step are not interruptible
mid-step, so the injection points are the host-side entries into
collective work: one "seqno" per tree-growth launch within a boosting
round ("version").  ``ntrial`` counts process restarts, so an injection
fires once and the restarted run sails past it — exactly the reference's
mock semantics.

Deterministic recovery holds because per-iteration seeding is derived by
``fold_in(seed, iteration)`` (the reference forces seed_per_iteration in
distributed mode for the same reason, learner-inl.hpp:275-277).

This module owns the COLLECTIVE seam only; the same injection idea for
the I/O and serving seams (torn writes, bit flips, ENOSPC, slow reads,
reload failures) lives in ``xgboost_tpu.reliability.faults`` — the two
compose in the chaos suite (kill a worker AND corrupt the checkpoint it
must restart from; tests/test_reliability.py, tools/chaos_loop.py).
"""

from __future__ import annotations

from typing import List, Optional, Tuple


class WorkerFailure(RuntimeError):
    """Simulated worker death (reference mock's exit(-2))."""


class FaultInjector:
    """Dies when a registered (version, seqno, ntrial) coordinate is hit."""

    def __init__(self, spec: List[Tuple[int, int, int]], trial: int = 0):
        self.spec = set(spec)
        self.trial = trial
        self.version = -1
        self.seqno = 0

    def begin_round(self, version: int) -> None:
        self.version = version
        self.seqno = 0

    def collective(self) -> None:
        coord = (self.version, self.seqno, self.trial)
        self.seqno += 1
        if (self.version, coord[1], self.trial) in self.spec:
            from xgboost_tpu.obs import trace
            trace.event("fault.injected", kind="worker_death",
                        seam="collective", seqno=coord[1],
                        trial=self.trial)
            raise WorkerFailure(
                f"[mock] die at version={coord[0]} seqno={coord[1]} "
                f"trial={self.trial}")


_injector: Optional[FaultInjector] = None
_calls = 0  # lifetime collective-seam entries (the report_stats count)


def set_fault_injection(spec: List[Tuple[int, int, int]],
                        trial: int = 0) -> None:
    """Install a process-wide injector (reference mock= parameter)."""
    global _injector
    _injector = FaultInjector(spec, trial)


def clear_fault_injection() -> None:
    global _injector
    _injector = None


def begin_round(version: int) -> None:
    # the round boundary doubles as the observability round marker:
    # collective stats (obs/comm.py) and discrete events correlate by
    # this version, the report_stats "version" role
    from xgboost_tpu.obs import comm, trace
    comm.begin_round(version)
    trace.set_round(version)
    if _injector is not None:
        _injector.begin_round(version)


def collective(op: str = "allreduce", nbytes: float = 0.0) -> None:
    """Call at every host-side collective entry (tree-growth launch).

    Besides the fault-injection seqno, each entry is COUNTED into the
    per-worker collective stats (``xgbtpu_comm_<op>_total`` and the
    per-round tallies, obs/comm.py) with the caller's logical payload
    estimate — so the exported allreduce count matches this seam's
    seqno space by construction.  Wall seconds are added by the caller
    timing the launch (``comm.timed(..., count=0)``)."""
    global _calls
    _calls += 1
    # record BEFORE the injector can raise: a simulated worker death
    # at this coordinate still counted an attempted collective, so
    # xgbtpu_comm_<op>_total and collective_calls() stay equal even
    # across fault trials
    from xgboost_tpu.obs import comm
    comm.record(op, nbytes=nbytes)
    if _injector is not None:
        _injector.collective()


def collective_calls() -> int:
    """Lifetime number of collective-seam entries in this process (the
    number the exported ``xgbtpu_comm_allreduce_total`` must match)."""
    return _calls


def active() -> bool:
    """True when fault injection is armed (fused multi-round launches
    must fall back to per-round launches so coordinates can fire)."""
    return _injector is not None

"""Fault injection for the collective/training seam.

The reference's ``AllreduceMock`` kills a worker at an exact
``(rank, version, seqno, ntrial)`` collective call
(``subtree/rabit/src/allreduce_mock.h:37-44,166-172``); a keepalive
wrapper restarts it and recovery must reproduce bit-identical state
(``tracker/rabit_demo.py:26-40``, ``test/local_recover.cc:30-60``).

Under XLA, collectives inside a jitted step are not interruptible
mid-step, so the injection points are the host-side entries into
collective work: one "seqno" per tree-growth launch within a boosting
round ("version").  ``ntrial`` counts process restarts, so an injection
fires once and the restarted run sails past it — exactly the reference's
mock semantics.

Two fault KINDS share the coordinate space:

- ``die`` (default) — raise :class:`WorkerFailure`, the reference
  mock's ``exit(-2)``: a crash the keepalive restart must absorb;
- ``stall`` — sleep at the coordinate (default effectively forever),
  the HANG twin of death: the worker stays alive but stops making
  progress, which only the gang launcher's heartbeat watchdog
  (``parallel/launch.py``) can detect and kill.  The reference's
  analog is ``allreduce_robust``'s timeout recovery — workers that
  stop progressing, not just workers that exit.

The round boundary (:func:`begin_round`) doubles as the LIVENESS
beacon: when the launcher exports ``XGBTPU_HEARTBEAT_DIR``, every rank
touches its per-rank heartbeat file there at each round, so "all ranks
stopped advancing" is observable from outside the gang.  It is also the
GANG-protocol checkpoint (``parallel/gang.py``): the ``partition`` and
``host_loss`` chaos kinds (``reliability/faults.py``) fire here, a
worker inside an open partition window suppresses its heartbeat (the
message is "dropped"), and one unreachable past
``XGBTPU_GANG_PARTITION_SEC`` self-fences before this function returns.

Deterministic recovery holds because per-iteration seeding is derived by
``fold_in(seed, iteration)`` (the reference forces seed_per_iteration in
distributed mode for the same reason, learner-inl.hpp:275-277).

This module owns the COLLECTIVE seam only; the same injection idea for
the I/O and serving seams (torn writes, bit flips, ENOSPC, slow reads,
reload failures) lives in ``xgboost_tpu.reliability.faults`` — the two
compose in the chaos suite (kill a worker AND corrupt the checkpoint it
must restart from; tests/test_reliability.py, tools/chaos_loop.py).
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional, Tuple

#: directory of per-rank heartbeat files, exported by the gang
#: launcher's watchdog (parallel/launch.py); unset = no beacon
HEARTBEAT_DIR_ENV = "XGBTPU_HEARTBEAT_DIR"

#: how long a ``stall`` fault sleeps — effectively forever: the point
#: is that the WATCHDOG ends it (SIGTERM/SIGKILL), not the sleep
STALL_SEC = 10_000.0


class WorkerFailure(RuntimeError):
    """Simulated worker death (reference mock's exit(-2))."""


class FaultInjector:
    """Fires when a registered (version, seqno, ntrial) coordinate is
    hit: ``die`` raises :class:`WorkerFailure`, ``stall`` sleeps (the
    hang twin — see module docstring).  Spec entries are 3-tuples
    (die) or 4-tuples ``(version, seqno, ntrial, kind)``."""

    def __init__(self, spec: List[Tuple], trial: int = 0,
                 stall_sec: float = STALL_SEC):
        self.spec = {}
        for item in spec:
            if len(item) == 3:
                v, s, t = item
                kind = "die"
            else:
                v, s, t, kind = item
            if kind not in ("die", "stall"):
                raise ValueError(f"unknown mock fault kind {kind!r}")
            self.spec[(int(v), int(s), int(t))] = kind
        self.trial = trial
        self.stall_sec = float(stall_sec)
        self.version = -1
        self.seqno = 0

    def begin_round(self, version: int) -> None:
        self.version = version
        self.seqno = 0

    def collective(self) -> None:
        coord = (self.version, self.seqno, self.trial)
        self.seqno += 1
        kind = self.spec.get(coord)
        if kind is None:
            return
        from xgboost_tpu.obs import trace
        if kind == "die":
            trace.event("fault.injected", kind="worker_death",
                        seam="collective", seqno=coord[1],
                        trial=self.trial)
            raise WorkerFailure(
                f"[mock] die at version={coord[0]} seqno={coord[1]} "
                f"trial={self.trial}")
        trace.event("fault.injected", kind="worker_stall",
                    seam="collective", seqno=coord[1], trial=self.trial)
        print(f"[mock] stall at version={coord[0]} seqno={coord[1]} "
              f"trial={self.trial} (heartbeats stop; the watchdog "
              "must kill this gang)", file=sys.stderr)
        sys.stderr.flush()
        # sleep in slices so a SIGTERM from the launcher's reap lands
        # between syscalls and the default handler exits promptly
        deadline = time.monotonic() + self.stall_sec
        while time.monotonic() < deadline:
            time.sleep(0.25)


_injector: Optional[FaultInjector] = None
_calls = 0  # lifetime collective-seam entries (the report_stats count)


def set_fault_injection(spec: List[Tuple], trial: int = 0,
                        stall_sec: float = STALL_SEC) -> None:
    """Install a process-wide injector (reference mock= parameter).
    Spec entries: ``(version, seqno, ntrial)`` for death, or
    ``(version, seqno, ntrial, "stall")`` for a hang."""
    global _injector
    _injector = FaultInjector(spec, trial, stall_sec=stall_sec)


def clear_fault_injection() -> None:
    global _injector
    _injector = None


def touch_heartbeat(version: int) -> None:
    """Touch this rank's heartbeat file (liveness beacon for the gang
    launcher's stall watchdog).  No-op unless the launcher exported
    ``XGBTPU_HEARTBEAT_DIR``.  Never raises: a beacon failure must not
    kill a healthy worker."""
    hb_dir = os.environ.get(HEARTBEAT_DIR_ENV)
    if not hb_dir:
        return
    rank = os.environ.get("XGBTPU_WORKER_ID", "0")
    try:
        # a liveness beacon, not durable state: the watchdog reads only
        # the mtime, so a torn write is harmless (the round number is
        # a debugging courtesy)
        with open(os.path.join(hb_dir, f"hb-{rank}"),  # xgtpu: disable=XGT003
                  "w") as f:
            f.write(str(version))
    except OSError as e:
        from xgboost_tpu.obs.metrics import swallowed_error
        swallowed_error("parallel.mock.touch_heartbeat", e,
                        emit_event=False)


def begin_round(version: int) -> None:
    # the round boundary doubles as the observability round marker:
    # collective stats (obs/comm.py) and discrete events correlate by
    # this version, the report_stats "version" role — AND as the
    # per-rank liveness beacon the stall watchdog reads.  The gang
    # protocol hook runs FIRST: it may kill the process (host_loss /
    # self-fence) or veto the beacon (open partition window drops
    # worker->coordinator messages too)
    from xgboost_tpu.obs import comm, trace
    from xgboost_tpu.parallel import gang
    if gang.on_round(version):
        touch_heartbeat(version)
    comm.begin_round(version)
    trace.set_round(version)
    if _injector is not None:
        _injector.begin_round(version)


def collective(op: str = "allreduce", nbytes: float = 0.0,
               count: int = 1) -> None:
    """Call at every host-side collective entry (tree-growth launch).

    Besides the fault-injection seqno, each entry is COUNTED into the
    per-worker collective stats (``xgbtpu_comm_<op>_total`` and the
    per-round tallies, obs/comm.py) with the caller's logical payload
    estimate — so the exported allreduce count matches this seam's
    seqno space by construction.  Wall seconds are added by the caller
    timing the launch (``comm.timed(..., count=0)``).

    ``count`` lets one seam entry (one injector seqno — one tree-growth
    launch) record several device collectives: the mesh-fused scan
    psums one histogram per level, so its growth steps count
    ``max_depth`` into ``xgbtpu_comm_psum_total`` while staying ONE
    fault-injection coordinate."""
    global _calls
    _calls += 1
    # record BEFORE the injector can raise: a simulated worker death
    # at this coordinate still counted an attempted collective, so
    # xgbtpu_comm_<op>_total and collective_calls() stay equal even
    # across fault trials
    from xgboost_tpu.obs import comm
    comm.record(op, nbytes=nbytes, count=count)
    if _injector is not None:
        _injector.collective()


def collective_calls() -> int:
    """Lifetime number of collective-seam entries in this process (the
    number the exported ``xgbtpu_comm_allreduce_total`` must match)."""
    return _calls


def active() -> bool:
    """True when fault injection is armed (fused multi-round launches
    must fall back to per-round launches so coordinates can fire)."""
    return _injector is not None

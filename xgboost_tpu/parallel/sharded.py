"""Per-rank split data loading: each process parses ONLY its row shard.

The reference's core scaling property: a distributed worker loads only
its own partition of the input text (``src/io/simple_dmatrix-inl.hpp:
89-96``, routed per rank by ``src/io/io.cpp:56-61``), so host memory per
worker is O(N / world) regardless of total data size.  This module is
the TPU-native equivalent for the multi-process (multi-host) Booster:

  - :class:`ShardedDMatrix` parses the CONTIGUOUS block of rows that
    lands on this process's devices under the global ``'data'``-axis
    mesh (block split rather than the reference's ``i % nparts == rank``
    round-robin, so the global device layout — and therefore every
    histogram partial sum — is bit-identical to a replicated-load run
    over the same mesh).
  - Global device arrays are assembled with
    ``jax.make_array_from_process_local_data``: each process contributes
    its local block; no host ever holds the full matrix.
  - Cut proposal uses the device sketch
    (:func:`xgboost_tpu.parallel.sketch_device.sketch_cuts_global`) —
    mandatory here, since no process could sketch a full column.
  - Metric evaluation reduces per-shard partial sums across processes
    (:meth:`ShardedDMatrix.allsum` — the rabit ``Allreduce`` of
    (sum, wsum) in the reference's metrics, ``evaluation-inl.hpp:45``)
    instead of all-gathering predictions.

Limitations (loud, not silent): ranking group structure does not
compose with row-block splitting (the reference has the same problem —
its ``.group`` sidecars are loaded whole and misalign under split
loading), and custom Python objectives/fevals need full-vector host
access; both raise with instructions to use replicated loading.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from xgboost_tpu.data import DMatrix, MetaInfo
from xgboost_tpu.parallel.mesh import DATA_AXIS


def _count_rows(path: str) -> int:
    """Number of data rows (non-empty lines) in a libsvm text file."""
    n = 0
    with open(path, "rb") as f:
        for raw in f:
            if raw.strip():
                n += 1
    return n


def _read_row_block(path: str, start: int, end: int):
    """Parse rows [start, end) (0-based, counting non-empty lines) into
    CSR (indptr, indices, values, labels)."""
    labels: list = []
    indptr: list = [0]
    indices: list = []
    values: list = []
    row = 0
    with open(path, "rb") as f:
        for raw in f:
            if not raw.strip():  # rows before `start` are skipped
                continue         # WITHOUT tokenizing (just the emptiness
            if row >= end:       # test; split() per skipped row would
                break            # dominate load time for high ranks)
            if row >= start:
                parts = raw.split()
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    k, _, v = tok.partition(b":")
                    indices.append(int(k))
                    values.append(float(v))
                indptr.append(len(indices))
            row += 1
    return (np.asarray(indptr, np.int64), np.asarray(indices, np.int32),
            np.asarray(values, np.float32), np.asarray(labels, np.float32))


class ShardedDMatrix:
    """A row-shard-loaded data matrix for multi-process training.

    Every process holds ONLY the rows that its local devices own under
    the global data-parallel mesh; ``num_row`` is still the GLOBAL row
    count (the Booster pads/shards exactly as it would for a replicated
    matrix, so the two paths produce bit-identical models).
    """

    is_sharded = True
    is_external = False

    def __init__(self, data: str, label=None, weight=None,
                 missing: float = np.nan, silent: bool = True, mesh=None):
        import jax
        from xgboost_tpu.parallel import mesh as pmesh

        if not isinstance(data, str):
            raise TypeError(
                "ShardedDMatrix loads from a libsvm text path; in-memory "
                "arrays are already host-resident — use DMatrix")
        self.mesh = mesh or pmesh.get_mesh() or pmesh.data_parallel_mesh()
        if DATA_AXIS not in self.mesh.axis_names:
            raise ValueError("ShardedDMatrix needs a mesh with a "
                             f"'{DATA_AXIS}' axis")
        rank = jax.process_index()

        n_global = _count_rows(data)
        n_dev = self.mesh.devices.size
        self._rows_per_dev = -(-n_global // max(n_dev, 1)) if n_global else 0
        self.padded_global_rows = self._rows_per_dev * n_dev
        # contiguous device positions along the mesh owned by this process
        mine = [k for k, d in enumerate(self.mesh.devices.flat)
                if d.process_index == rank]
        if not mine:
            raise ValueError(f"process {rank} owns no devices in the mesh")
        if mine != list(range(mine[0], mine[-1] + 1)):
            raise ValueError(
                "mesh devices of one process must be contiguous along the "
                "data axis for block split loading (got positions "
                f"{mine}); build the mesh over jax.devices() order")
        self.block_start = mine[0] * self._rows_per_dev      # padded coords
        self.block_rows = len(mine) * self._rows_per_dev     # incl. padding
        self.row_start = min(self.block_start, n_global)
        self.row_end = min(self.block_start + self.block_rows, n_global)
        self.global_num_row = n_global

        indptr, indices, values, labels = _read_row_block(
            data, self.row_start, self.row_end)

        # global feature count: allreduce-Max of the local max feature id
        # (the reference allreduces num_feature, learner-inl.hpp:136)
        local_ncol = int(indices.max()) + 1 if len(indices) else 0
        self._num_col = int(np.max(self._allgather_i64(local_ncol)))
        self._local = DMatrix((indptr, indices, values, self._num_col))

        self.info = MetaInfo()
        self.info.label = labels
        self._full_base_margin: Optional[np.ndarray] = None
        if label is not None:
            self.info.set_field("label", self._slice_if_global(
                np.asarray(label), "label"))
        if weight is not None:
            self.info.set_field("weight", self._slice_if_global(
                np.asarray(weight), "weight"))
        self._load_sidecars(data)
        self.feature_names = None
        if not silent:
            print(f"[shard_load] rank {rank}: rows "
                  f"[{self.row_start}, {self.row_end}) of {n_global}")

    # ------------------------------------------------------------- metadata
    @property
    def num_row(self) -> int:
        return self.global_num_row

    @property
    def num_col(self) -> int:
        return self._num_col

    @property
    def local_num_row(self) -> int:
        return self.row_end - self.row_start

    def get_label(self):
        """LOCAL shard labels (this process's real rows)."""
        return None if self.info.label is None else self.info.label.copy()

    def get_weight(self):
        w = self.info.get_weight(self.local_num_row)
        return w.copy() if self.info.weight is not None else w

    def _slice_if_global(self, arr: np.ndarray, what: str) -> np.ndarray:
        """Accept a per-row vector either GLOBAL (sliced to our block) or
        already local; anything else is a loud shape error."""
        if arr.shape[0] == self.global_num_row:
            return arr[self.row_start:self.row_end]
        if arr.shape[0] == self.local_num_row:
            return arr
        raise ValueError(
            f"{what}: expected {self.global_num_row} (global) or "
            f"{self.local_num_row} (this process's shard) values, got "
            f"{arr.shape[0]}")

    def _load_sidecars(self, path: str) -> None:
        """Sidecar files hold GLOBAL per-row values; slice our block
        (reference MetaInfo::TryLoadFloatInfo, dmatrix.h:108-137)."""
        if os.path.exists(path + ".group"):
            raise NotImplementedError(
                "ranking group files do not compose with per-rank row-block "
                "loading (a group would straddle shard boundaries); load "
                "this data with DMatrix (replicated) instead")
        if os.path.exists(path + ".weight"):
            full = np.loadtxt(path + ".weight", dtype=np.float32, ndmin=1)
            self.info.set_field(
                "weight", full[self.row_start:self.row_end])
        if os.path.exists(path + ".base_margin"):
            # may hold N*K flat values (multiclass); K is unknown here, so
            # keep the FULL array and let the learner slice rows with K
            self._full_base_margin = np.loadtxt(
                path + ".base_margin", dtype=np.float32, ndmin=1)

    def set_label(self, label):
        self.info.set_field("label", self._slice_if_global(
            np.asarray(label), "label"))

    def set_weight(self, weight):
        self.info.set_field("weight", self._slice_if_global(
            np.asarray(weight), "weight"))

    def slice(self, rindex):
        raise NotImplementedError(
            "slice is process-local-undefined on a ShardedDMatrix; load "
            "replicated for cv/slicing")

    # ------------------------------------------------------- device assembly
    def make_global(self, local_block: np.ndarray, dtype=None):
        """Assemble a global row-sharded device array from this process's
        padded local block (``block_rows`` rows)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        arr = np.asarray(local_block)
        if dtype is not None:
            arr = arr.astype(dtype)
        assert arr.shape[0] == self.block_rows, \
            (arr.shape, self.block_rows)
        sharding = NamedSharding(
            self.mesh, P(DATA_AXIS, *([None] * (arr.ndim - 1))))
        return jax.make_array_from_process_local_data(
            sharding, arr, (self.padded_global_rows,) + arr.shape[1:])

    def pad_local(self, arr: np.ndarray, fill=0) -> np.ndarray:
        """Pad a (local_num_row, ...) array to the padded block size."""
        pad = self.block_rows - arr.shape[0]
        if pad == 0:
            return arr
        widths = ((0, pad),) + ((0, 0),) * (arr.ndim - 1)
        return np.pad(arr, widths, constant_values=fill)

    def local_block_of(self, global_arr) -> np.ndarray:
        """Pull THIS process's (padded) block of a row-sharded global
        device array to host — the distributed-eval replacement for a
        full all-gather."""
        shards = [s for s in global_arr.addressable_shards]
        shards.sort(key=lambda s: (s.index[0].start or 0))
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)

    def device_raw(self):
        """(values, weights) global device arrays for the device sketch:
        raw feature values (+inf = missing, matching sketch_cuts_mesh's
        sanitized input bit-for-bit) and per-row sketch weights (0 on
        padding rows)."""
        vals = self._local.to_dense(missing=np.inf)
        vals = self.pad_local(vals, fill=np.inf)
        w = self.pad_local(self.info.get_weight(self.local_num_row), fill=0.0)
        return (self.make_global(vals, np.float32),
                self.make_global(w, np.float32))

    def row_valid_global(self):
        gids = self.block_start + np.arange(self.block_rows)
        return self.make_global(gids < self.global_num_row)

    # --------------------------------------------------------- collectives
    # Every host-side collective below records (count, bytes, seconds)
    # into the per-worker collective stats (obs/comm.py, the
    # report_stats analog) as op "allgather" — these really are
    # process_allgather launches, unlike the in-XLA psum reductions the
    # growth seam accounts as "allreduce".

    @staticmethod
    def _allgather_i64(x: int) -> np.ndarray:
        import jax
        if jax.process_count() == 1:
            return np.asarray([x], np.int64)
        from jax.experimental import multihost_utils as mhu
        from xgboost_tpu.obs import comm
        with comm.timed("allgather", nbytes=8 * jax.process_count()):
            return np.asarray(mhu.process_allgather(np.int64(x)))

    @staticmethod
    def allgatherv(mat: np.ndarray) -> np.ndarray:
        """Concatenate per-process float64 (k_i, C) matrices across
        processes (variable k_i; rows padded to the max and trimmed
        after the gather).  Carries the exact-AUC value runs — the
        reference has no equivalent collective because it approximates
        instead (evaluation-inl.hpp:405-414)."""
        import jax
        m = np.ascontiguousarray(np.asarray(mat, np.float64))
        if jax.process_count() == 1:
            return m
        from jax.experimental import multihost_utils as mhu
        from xgboost_tpu.obs import comm
        lens = np.asarray(mhu.process_allgather(np.int64(m.shape[0])))
        kmax = int(lens.max())
        pad = np.zeros((kmax, m.shape[1]), np.float64)
        pad[:m.shape[0]] = m
        buf = np.frombuffer(pad.tobytes(), np.uint8)
        with comm.timed("allgather",
                        nbytes=buf.nbytes * jax.process_count()):
            gathered = np.asarray(mhu.process_allgather(buf))
        out = np.frombuffer(gathered.tobytes(), np.float64).reshape(
            jax.process_count(), kmax, m.shape[1])
        return np.concatenate(
            [out[i, :lens[i]] for i in range(len(lens))], axis=0)

    @staticmethod
    def allsum(vec: np.ndarray) -> np.ndarray:
        """Sum a small float64 host vector across processes exactly (the
        metric (sum, wsum) allreduce role).  Bytes ride the gather as
        uint8 so float64 partials survive x64-disabled JAX configs."""
        import jax
        v = np.ascontiguousarray(np.asarray(vec, np.float64))
        if jax.process_count() == 1:
            return v
        from jax.experimental import multihost_utils as mhu
        from xgboost_tpu.obs import comm
        buf = np.frombuffer(v.tobytes(), np.uint8)
        with comm.timed("allgather",
                        nbytes=buf.nbytes * jax.process_count()):
            gathered = np.asarray(mhu.process_allgather(buf))
        return np.frombuffer(
            gathered.tobytes(), np.float64).reshape(
                jax.process_count(), -1).sum(axis=0)

"""Device-side distributed weighted quantile sketch.

The TPU replacement for rabit's ``SerializeReducer`` reduction of
serialized quantile summaries (reference
``src/tree/updater_histmaker-inl.hpp:417-424``,
``src/utils/quantile.h:587-593``): each shard of a row-sharded dataset
builds a bounded-size summary of every feature ON DEVICE, summaries are
``all_gather``-ed over the mesh axis and folded with the associative
merge+prune — no host ever needs a full column.

A summary is a fixed-shape padded tensor (jit/pjit friendly): four
``(K,)`` float32 arrays (value, rmin, rmax, wmin), sorted by value, with
padding slots at ``value=+inf, rmin=rmax=total_weight, wmin=0``.  That
padding is rank-consistent — a padded slot behaves like "an entry above
every real value" — so merge needs no masks beyond the representation.

Semantics mirror the host sketch (:mod:`xgboost_tpu.sketch`, itself the
reference's ``WQSummary`` SetCombine/SetPrune, ``quantile.h:189-278``);
the rank-error guarantee eps = O(1/K) carries over because merge is
exact on rank bounds and prune is applied at bounded size.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class DeviceSummary(NamedTuple):
    """Padded weighted quantile summary (per feature: each field (..., K))."""
    value: jax.Array
    rmin: jax.Array
    rmax: jax.Array
    wmin: jax.Array


def _pad_entry(total):
    """Rank-consistent padding slot: sits above every real value."""
    return jnp.inf, total, total, jnp.float32(0.0)


def _select_prune(value, rmin, rmax, wmin, last_idx, n_real, total, K: int):
    """SetPrune (quantile.h:189-219) on sorted, possibly duplicated
    entries: keep extremes, pick interior entries nearest evenly spaced
    ranks with the (RMinNext, RMaxPrev) straddle test.  Returns a (K,)
    padded deduplicated DeviceSummary."""
    L = value.shape[0]
    begin = rmax[0]
    rng = jnp.take(rmin, jnp.maximum(n_real - 1, 0)) - begin
    n = K - 2
    k = jnp.arange(1, max(n, 1), dtype=jnp.float32)
    dx2 = 2.0 * (k * rng / max(n, 1) + begin)
    mid = rmin + rmax  # 2x midpoint rank; pads have mid = 2*total (>= dx2)
    ii = jnp.clip(jnp.searchsorted(mid, dx2, side="right") - 1, 0, L - 1)
    rmin_next = rmin + wmin
    rmax_prev = rmax - wmin
    nxt = jnp.minimum(last_idx[ii] + 1, L - 1)  # first slot of next group
    use_i = dx2 < rmin_next[ii] + rmax_prev[nxt]
    sel = jnp.where(use_i, ii, nxt)
    sel = jnp.concatenate([jnp.zeros(1, sel.dtype), sel,
                           jnp.maximum(n_real - 1, 0)[None]])
    sel = jnp.clip(sel, 0, jnp.maximum(n_real - 1, 0))

    sv, srmin, srmax, swmin = value[sel], rmin[sel], rmax[sel], wmin[sel]
    # dedup (selection may hit one group twice); padded slots dedup too
    keep = jnp.concatenate([jnp.array([True]), sv[1:] != sv[:-1]])
    keep &= jnp.isfinite(sv) & (n_real > 0)
    pv, prmin, prmax, pwmin = _pad_entry(total)
    sv = jnp.where(keep, sv, pv)
    srmin = jnp.where(keep, srmin, prmin)
    srmax = jnp.where(keep, srmax, prmax)
    swmin = jnp.where(keep, swmin, pwmin)
    # restore sortedness (masked slots went to +inf mid-array); K is tiny
    order = jnp.argsort(sv, stable=True)
    out = DeviceSummary(sv[order], srmin[order], srmax[order], swmin[order])
    # pad from K-1 selected slots up to K
    pad = jnp.full(K - sv.shape[0], 1.0)
    return DeviceSummary(
        jnp.concatenate([out.value, pad * pv]),
        jnp.concatenate([out.rmin, pad * prmin]),
        jnp.concatenate([out.rmax, pad * prmax]),
        jnp.concatenate([out.wmin, pad * pwmin]))


def local_summary(values: jax.Array, weights: jax.Array, K: int
                  ) -> DeviceSummary:
    """Exact summary of one feature shard, pruned to K slots.

    values: (N,) raw feature values (NaN/inf = missing); weights: (N,)
    (zero-weight rows are dropped, matching host make_summary).
    """
    N = values.shape[0]
    valid = jnp.isfinite(values) & (weights > 0)
    v = jnp.where(valid, values, jnp.inf).astype(jnp.float32)
    w = jnp.where(valid, weights, 0.0).astype(jnp.float32)
    order = jnp.argsort(v, stable=True)
    vs, ws = v[order], w[order]
    cum = jnp.cumsum(ws)
    total = cum[-1]
    n_real = jnp.sum(valid)
    i = jnp.arange(N)
    neq = vs[1:] != vs[:-1]
    first_b = jnp.concatenate([jnp.array([True]), neq])
    last_b = jnp.concatenate([neq, jnp.array([True])])
    first_idx = jax.lax.cummax(jnp.where(first_b, i, 0))
    last_idx = jax.lax.cummin(jnp.where(last_b, i, N - 1), reverse=True)
    cum0 = jnp.concatenate([jnp.zeros(1, jnp.float32), cum])
    rmin = cum0[first_idx]          # weight strictly below the group
    rmax = cum[last_idx]            # weight at or below the group
    wmin = rmax - rmin
    # pads (missing rows sorted to +inf with w=0) get rank-consistent slots
    real = jnp.arange(N) < n_real
    vs = jnp.where(real, vs, jnp.inf)
    rmin = jnp.where(real, rmin, total)
    rmax = jnp.where(real, rmax, total)
    wmin = jnp.where(real, wmin, 0.0)
    return _select_prune(vs, rmin, rmax, wmin, last_idx, n_real, total, K)


def _total(s: DeviceSummary):
    """Total weight: pads carry it by construction; last slot is pad-or-max."""
    return s.rmax[..., -1]


def merge_summaries_dev(a: DeviceSummary, b: DeviceSummary, K: int
                        ) -> DeviceSummary:
    """Associative merge + prune back to K (SetCombine, quantile.h:225-278).

    Both inputs are (K,)-padded deduplicated summaries.
    """
    def contrib(x: DeviceSummary, other: DeviceSummary):
        L = other.value.shape[0]
        lo = jnp.searchsorted(other.value, x.value, side="left")
        hi = jnp.searchsorted(other.value, x.value, side="right")
        exact = hi > lo
        tot = _total(other)
        rmin_next = jnp.concatenate(
            [jnp.zeros(1, jnp.float32), other.rmin + other.wmin])
        rmax_prev = jnp.concatenate(
            [other.rmax - other.wmin, tot[None]])
        loc = jnp.minimum(lo, L - 1)
        add_rmin = jnp.where(exact, other.rmin[loc], rmin_next[lo])
        add_rmax = jnp.where(exact, other.rmax[loc], rmax_prev[hi])
        add_wmin = jnp.where(exact, other.wmin[loc], 0.0)
        return add_rmin, add_rmax, add_wmin

    ar, ax, aw = contrib(a, b)
    br, bx, bw = contrib(b, a)
    allv = jnp.concatenate([a.value, b.value])
    allrmin = jnp.concatenate([a.rmin + ar, b.rmin + br])
    allrmax = jnp.concatenate([a.rmax + ax, b.rmax + bx])
    allwmin = jnp.concatenate([a.wmin + aw, b.wmin + bw])
    order = jnp.argsort(allv, stable=True)
    allv, allrmin, allrmax, allwmin = (allv[order], allrmin[order],
                                       allrmax[order], allwmin[order])
    total = _total(a) + _total(b)
    # dedup equal values (each side already absorbed the other's mass);
    # re-pad with the merged total
    keep = jnp.concatenate([jnp.array([True]), allv[1:] != allv[:-1]])
    keep &= jnp.isfinite(allv)
    pv, prmin, prmax, pwmin = _pad_entry(total)
    allv = jnp.where(keep, allv, pv)
    allrmin = jnp.where(keep, allrmin, prmin)
    allrmax = jnp.where(keep, allrmax, prmax)
    allwmin = jnp.where(keep, allwmin, pwmin)
    order = jnp.argsort(allv, stable=True)
    allv, allrmin, allrmax, allwmin = (allv[order], allrmin[order],
                                       allrmax[order], allwmin[order])
    n_real = jnp.sum(jnp.isfinite(allv))
    L = allv.shape[0]
    return _select_prune(allv, allrmin, allrmax, allwmin,
                         jnp.arange(L), n_real, total, K)


def propose_cuts_dev(s: DeviceSummary, max_bin: int) -> jax.Array:
    """Padded cut proposal from a device summary: up to max_bin-1 strictly
    increasing cut values, +inf padded (host propose_cuts semantics)."""
    K = s.value.shape[-1]
    n_cut = max_bin - 1
    n_real = jnp.sum(jnp.isfinite(s.value))
    total = _total(s)
    # dense path: every distinct value is a cut (incl. the minimum — the
    # missing-vs-present split for one-hot features)
    dense = s.value  # already distinct + sorted + inf-padded
    # quantile path
    ranks = jnp.arange(1, n_cut + 1, dtype=jnp.float32) * (
        total / (n_cut + 1))
    mid = (s.rmin + s.rmax) * 0.5
    idx = jnp.searchsorted(mid, ranks, side="left")
    idx = jnp.clip(idx, 1, jnp.maximum(n_real - 1, 1))
    qv = s.value[idx]
    keep = jnp.concatenate([jnp.array([True]), qv[1:] != qv[:-1]])
    qv = jnp.sort(jnp.where(keep & jnp.isfinite(qv), qv, jnp.inf))
    use_dense = n_real <= n_cut
    out_len = max(n_cut, K)
    dense_p = jnp.full(out_len, jnp.inf).at[:K].set(dense)
    quant_p = jnp.full(out_len, jnp.inf).at[:n_cut].set(qv)
    return jnp.where(use_dense, dense_p, quant_p)[:n_cut]


@functools.partial(jax.jit, static_argnames=("K", "max_bin", "axis_name"))
def _sketch_shard(values, weights, K: int, max_bin: int, axis_name: str):
    """Per-shard: local summaries for all features, all-gather over the
    mesh axis, associative fold, cut proposal.  values: (n_local, F)."""
    summ = jax.vmap(lambda col: local_summary(col, weights, K),
                    in_axes=1, out_axes=0)(values)      # (F, K) fields
    gathered = jax.lax.all_gather(summ, axis_name)       # (n_shard, F, K)
    n_shard = gathered.value.shape[0]
    merge = jax.vmap(lambda a, b: merge_summaries_dev(a, b, K))
    # pairwise tree fold: O(log n_shard) dependent merge stages
    parts = [jax.tree.map(lambda x, r=r: x[r], gathered)
             for r in range(n_shard)]
    while len(parts) > 1:
        nxt = [merge(parts[i], parts[i + 1])
               for i in range(0, len(parts) - 1, 2)]
        if len(parts) % 2:
            nxt.append(parts[-1])
        parts = nxt
    acc = parts[0]
    # host compute_cuts proposes max_bin-1 cuts from its summary arg of
    # max_bin, leaving room for the reserved missing bin (binning.py:73);
    # mirror that so CutMatrix.max_bin stays <= max_bin on both paths
    cuts = jax.vmap(lambda s: propose_cuts_dev(s, max_bin - 1))(acc)
    return cuts, acc


def sketch_cuts_global(mesh, values_dev, weights_dev,
                       max_bin: int = 256, sketch_eps: float = 0.03,
                       sketch_ratio: float = 2.0):
    """Propose cuts from GLOBAL device arrays already row-sharded over
    ``mesh``'s 'data' axis.

    This is the true multi-host entry point: with per-rank split loading
    (:class:`xgboost_tpu.parallel.sharded.ShardedDMatrix`) each process
    contributed only its local rows to ``values_dev``, so no host ever
    materializes a full feature column — the cut proposal happens
    entirely in the mesh (local summaries -> all_gather -> associative
    fold), exactly the SerializeReducer role (quantile.h:587-593).

    values_dev: (N_pad, F) float32, NaN = missing;
    weights_dev: (N_pad,) float32, 0 on padding rows.
    Returns a host CutMatrix (identical on every process — the fold is
    deterministic and the output is replicated).
    """
    from jax.sharding import PartitionSpec as P

    from xgboost_tpu.binning import pack_cuts

    K = max(8, int(sketch_ratio / max(sketch_eps, 1.0 / max_bin)))
    from xgboost_tpu.parallel.mesh import shard_map
    fn = shard_map(
        functools.partial(_sketch_shard, K=K, max_bin=max_bin,
                          axis_name="data"),
        mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False)
    cuts_padded, _ = jax.jit(fn)(values_dev, weights_dev)
    cuts_np = np.asarray(cuts_padded)  # replicated -> host pull is local
    per_feature = [c[np.isfinite(c)].astype(np.float32) for c in cuts_np]
    return pack_cuts(per_feature)


def sketch_cuts_mesh(mesh, values: np.ndarray, weights: np.ndarray | None,
                     max_bin: int = 256, sketch_eps: float = 0.03,
                     sketch_ratio: float = 2.0):
    """Propose cuts for all features with rows sharded over ``mesh``'s
    'data' axis — the dsplit=row cut proposal (every shard sketches only
    its own rows; merge rides the ICI all-gather).

    Returns a host :class:`xgboost_tpu.binning.CutMatrix` (identical on
    every shard — the fold is deterministic).

    Single-controller convenience wrapper: ``values`` here is the full
    dense matrix the controller already holds (the per-shard split
    happens at device-put).  Per-rank split loading goes through
    :func:`sketch_cuts_global` with each process contributing only its
    local rows — same merge, bit-identical cuts.
    """
    n_shard = mesh.devices.size
    N, F = values.shape
    pad = (-N) % n_shard
    # missing/padding marker is +inf, NOT NaN: the sketch treats any
    # non-finite as missing, and in multi-process mode the runtime
    # asserts replicated device_put inputs are value-equal across
    # processes — which NaN can never be (NaN != NaN)
    if np.isnan(values).any():  # avoid a full-matrix copy when dense
        values = np.where(np.isnan(values), np.inf, values)
    if pad:
        values = np.concatenate(
            [values, np.full((pad, F), np.inf, values.dtype)])
        w = np.ones(N + pad, np.float32)
        w[N:] = 0.0
    else:
        w = np.ones(N, np.float32)
    if weights is not None:
        w[:N] = weights
    return sketch_cuts_global(
        mesh, jnp.asarray(values, jnp.float32), jnp.asarray(w),
        max_bin, sketch_eps, sketch_ratio)

"""Cluster job submitters — the rabit submitter scripts' analog.

The reference ships per-scheduler submit glue that starts N workers with
rank/world env vars and a tracker address
(``subtree/rabit/tracker/rabit_mpi.py``, ``rabit_sge.py``,
``rabit_yarn.py`` + the YARN Java client).  Under JAX the tracker is the
``jax.distributed`` coordinator (process 0), so a submitter only needs
to (a) start the same worker command N times on the cluster and (b) let
each worker discover (coordinator, world, rank).  Rank/world come either
from the explicit ``XGBTPU_*`` env contract or from the scheduler's own
variables (``init_worker`` understands OpenMPI/PMI/Slurm/SGE — see
:func:`scheduler_rank`).

Usage (mirrors ``rabit_*.py submit(nworker, cmd)``):

    python -m xgboost_tpu.parallel.submit -n 8 --mode mpi \
        --coord host0:9876 -- python -m xgboost_tpu train.conf

``--mode local`` delegates to the in-tree gang launcher;
``--dry-run`` prints the scheduler command instead of executing it
(what the tests assert — no scheduler lives in CI).
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import tempfile
from typing import List, Optional, Tuple

from xgboost_tpu.parallel.launch import (COORD_ENV, NWORKER_ENV, RANK_ENV,
                                         free_port, launch_local)

# scheduler-provided rank/world variables, in resolution order
_RANK_VARS = ("OMPI_COMM_WORLD_RANK", "PMIX_RANK", "PMI_RANK",
              "SLURM_PROCID")
_WORLD_VARS = ("OMPI_COMM_WORLD_SIZE", "PMI_SIZE", "SLURM_NTASKS")


def scheduler_rank() -> Optional[Tuple[int, int]]:
    """(rank, world) from scheduler env vars, or None.

    SGE array jobs number tasks from 1 (``SGE_TASK_ID``); MPI/Slurm
    ranks start at 0.
    """
    for rv in _RANK_VARS:
        if rv in os.environ:
            rank = int(os.environ[rv])
            for wv in _WORLD_VARS:
                if wv in os.environ:
                    return rank, int(os.environ[wv])
    if "SGE_TASK_ID" in os.environ and "SGE_TASK_LAST" in os.environ:
        return (int(os.environ["SGE_TASK_ID"]) - 1,
                int(os.environ["SGE_TASK_LAST"]))
    return None


def mpi_command(n: int, coord: str, cmd: List[str]) -> List[str]:
    """mpirun line exporting the env contract (rabit_mpi.py role): the
    coordinator address is fixed at submit time; each worker takes its
    rank from OMPI/PMI vars."""
    return (["mpirun", "-n", str(n),
             "-x", f"{COORD_ENV}={coord}",
             "-x", f"{NWORKER_ENV}={n}"] + cmd)


def sge_script(n: int, coord: str, cmd: List[str]) -> str:
    """qsub array-job script text (rabit_sge.py role): task ids 1..N map
    to ranks 0..N-1 via SGE_TASK_ID."""
    quoted = " ".join(shlex.quote(c) for c in cmd)
    return (
        "#!/bin/bash\n"
        f"#$ -t 1-{n}\n"
        "#$ -cwd\n"
        f"export {COORD_ENV}={shlex.quote(coord)}\n"
        f"export {NWORKER_ENV}={n}\n"
        f"export {RANK_ENV}=$((SGE_TASK_ID-1))\n"
        f"exec {quoted}\n")


def slurm_command(n: int, coord: str, cmd: List[str]) -> List[str]:
    """srun line (the modern scheduler the reference predates); ranks
    come from SLURM_PROCID."""
    return (["srun", f"--ntasks={n}",
             f"--export=ALL,{COORD_ENV}={coord},{NWORKER_ENV}={n}"] + cmd)


def submit(n: int, cmd: List[str], mode: str = "local",
           coord: Optional[str] = None, keepalive: bool = False,
           dry_run: bool = False) -> int:
    """Submit ``cmd`` as an ``n``-worker distributed job."""
    if mode == "local":
        if dry_run:
            print(f"[submit] local gang: {n} x {' '.join(cmd)}")
            return 0
        return launch_local(n, cmd, keepalive=keepalive)
    if coord is None:
        # the submit host fronts the coordinator only in mode=mpi when
        # rank 0 lands on this host; schedulers need an explicit --coord
        if mode == "mpi":
            coord = f"{os.uname().nodename}:{free_port()}"
        else:
            raise ValueError(
                f"--mode {mode} needs --coord host:port (the address "
                "where rank 0's jax.distributed coordinator will listen)")
    if mode == "mpi":
        line = mpi_command(n, coord, cmd)
        if dry_run:
            print(" ".join(shlex.quote(c) for c in line))
            return 0
        return subprocess.call(line)
    if mode == "sge":
        script = sge_script(n, coord, cmd)
        if dry_run:
            print(script, end="")
            return 0
        # tmp+rename (XGT003): qsub must never see a torn script — a
        # half-written job file would submit N workers running a
        # truncated command line (no fsync: the scheduler reads it
        # back immediately, durability across a crash is moot)
        from xgboost_tpu.reliability.integrity import atomic_write
        path = os.path.join(tempfile.mkdtemp(prefix="xgtpu-submit-"),
                            "job.sh")
        atomic_write(path, script.encode(), durable=False)
        return subprocess.call(["qsub", path])
    if mode == "slurm":
        line = slurm_command(n, coord, cmd)
        if dry_run:
            print(" ".join(shlex.quote(c) for c in line))
            return 0
        return subprocess.call(line)
    raise ValueError(f"unknown submit mode {mode!r} "
                     "(local | mpi | sge | slurm)")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m xgboost_tpu.parallel.submit",
        description="submit an N-worker distributed job "
                    "(rabit_mpi/sge submitter analog)")
    ap.add_argument("-n", "--nworker", type=int, required=True)
    ap.add_argument("--mode", default="local",
                    choices=("local", "mpi", "sge", "slurm"))
    ap.add_argument("--coord", default=None,
                    help="host:port for the jax.distributed coordinator")
    ap.add_argument("--keepalive", action="store_true")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the scheduler command, do not execute")
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if args.cmd and args.cmd[0] == "--":
        args.cmd = args.cmd[1:]
    if not args.cmd:
        ap.error("missing worker command")
    return submit(args.nworker, args.cmd, mode=args.mode, coord=args.coord,
                  keepalive=args.keepalive, dry_run=args.dry_run)


if __name__ == "__main__":
    sys.exit(main())

"""Row-split data-parallel tree growth (the reference's flagship
distributed mode, ``dsplit=row`` → grow_histmaker, SURVEY.md §2.4).

Each device holds a row shard; per level the local histograms and node
stats are ``psum``-reduced over the mesh ``data`` axis — exactly where
the reference called ``histred.Allreduce``
(``src/tree/updater_histmaker-inl.hpp:343-346``) and ``GetNodeStats``'
allreduce (``updater_basemaker-inl.hpp:266-306``).  After the reduction
every shard computes the identical argmax split (deterministic
tie-break), so trees need no broadcast step — the reference's
TreeSyncher (``updater_sync-inl.hpp:34-49``) is free.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from xgboost_tpu.models.tree import (GrowConfig, grow_tree,
                                     table_lookup)
from xgboost_tpu.parallel.mesh import DATA_AXIS, shard_map


def _psum_data(x):
    return jax.lax.psum(x, DATA_AXIS)


def grow_tree_dp(mesh: Mesh, key, binned, gh, cut_values, n_cuts,
                 cfg: GrowConfig, row_valid, split_finder=None, root=None):
    """Grow one tree with rows sharded over mesh axis 'data'.

    binned: (N, F) with N divisible by mesh size; gh: (N, 2);
    row_valid: (N,) bool marking real (non-padding) rows;
    root: optional (N,) int32 per-row root slot (multi-root trees).
    Returns (tree [replicated], row_leaf (N,) [sharded]).
    """
    def body(key, binned, gh, cut_values, n_cuts, row_valid, root):
        tree, row_leaf, row_val = grow_tree(
            key, binned, gh, cut_values, n_cuts, cfg,
            row_valid, hist_reduce=_psum_data,
            split_finder=split_finder,
            root=root if cfg.n_roots > 1 else None)
        # the leaf value was recorded at parking time, inside the shard
        return tree, row_leaf, row_val

    if root is None:
        root = jnp.zeros(binned.shape[0], jnp.int32)
    # check_vma=False: the Pallas histogram kernel's out_shape carries no
    # vma annotation, and the psum'd tree outputs are replicated anyway
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(), P(), P(DATA_AXIS),
                  P(DATA_AXIS)),
        out_specs=(P(), P(DATA_AXIS), P(DATA_AXIS)),
        check_vma=False,
    )
    return fn(key, binned, gh, cut_values, n_cuts, row_valid, root)


def refresh_tree_dp(mesh: Mesh, tree, binned, gh, split_cfg, max_depth,
                    row_valid):
    """Refresh a tree's stats over row-sharded data: per-shard path
    accumulation + psum (exactly the reference TreeRefresher's lazy
    allreduce of all node stats, updater_refresh-inl.hpp:94-98)."""
    from xgboost_tpu.models.updaters import refresh_tree

    def body(tree, binned, gh, row_valid):
        return refresh_tree(tree, binned, gh, split_cfg, max_depth,
                            row_valid, hist_reduce=_psum_data)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    if row_valid is None:
        row_valid = jnp.ones(binned.shape[0], jnp.bool_)
    return fn(tree, binned, gh, row_valid)


def shard_rows(mesh: Mesh, arr: jax.Array) -> jax.Array:
    """Place an array with rows sharded over the 'data' axis."""
    spec = P(DATA_AXIS, *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))


def pad_rows(n: int, multiple: int) -> int:
    """Rows to add so n divides evenly across the mesh."""
    return (-n) % multiple

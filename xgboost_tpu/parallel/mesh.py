"""Device mesh context for distributed training.

The reference's cluster layer (rabit tracker rendezvous + rank/world,
``subtree/rabit/tracker/rabit_tracker.py:125-309``) collapses to a
``jax.sharding.Mesh``: the JAX runtime owns rendezvous and the mesh
axis name is the communicator.  The flagship mode is row-split data
parallelism over axis ``"data"`` (SURVEY.md §2.4 item 2 → psum over ICI).

Multi-host: build the mesh over ``jax.devices()`` after
``jax.distributed.initialize()`` — same code path, collectives ride
ICI within a slice and DCN across slices.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

DATA_AXIS = "data"

_default_mesh: Optional[Mesh] = None


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes it top-level with ``check_vma``; the jax this
    container bakes in (0.4.x) only has
    ``jax.experimental.shard_map.shard_map`` with the same semantics
    under the older ``check_rep`` name.  Every shard_map in the repo
    goes through here so the mesh path runs LIVE on both (ROADMAP
    container caveat — the forced-multi-CPU-device tests depend on it).
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def mesh_available(min_devices: int = 2) -> bool:
    """True when a live data-parallel mesh of ``min_devices`` can run in
    THIS process: enough devices and a working shard_map (top-level or
    experimental).  The test skipif gate — prefer a live
    forced-multi-CPU-device run over a skip wherever possible."""
    if len(jax.devices()) < min_devices:
        return False
    if hasattr(jax, "shard_map"):
        return True
    try:
        from jax.experimental.shard_map import shard_map as _  # noqa: F401
        return True
    except ImportError:
        return False


def set_mesh(mesh: Optional[Mesh]) -> None:
    """Install a process-wide default mesh for dsplit=row training."""
    global _default_mesh
    _default_mesh = mesh


def get_mesh() -> Optional[Mesh]:
    return _default_mesh


def data_parallel_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the first n (default all) devices, axis 'data'.

    Auto axis types: tree traversal gathers (replicated node tables,
    row-sharded indices) rely on GSPMD propagation, which Explicit mode
    rejects as ambiguous.
    """
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return make_mesh((len(devs),), (DATA_AXIS,), devices=devs)


def make_mesh(shape, names, devices=None) -> Mesh:
    """``jax.make_mesh`` with Auto axis types where the jax version has
    them (older jax predates ``sharding.AxisType`` and is Auto-only —
    passing the kwarg there is a TypeError)."""
    kwargs = {}
    if hasattr(jax.sharding, "AxisType"):
        kwargs["axis_types"] = tuple(
            jax.sharding.AxisType.Auto for _ in names)
    return jax.make_mesh(tuple(shape), tuple(names), devices=devices,
                         **kwargs)

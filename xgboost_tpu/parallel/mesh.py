"""Device mesh context for distributed training.

The reference's cluster layer (rabit tracker rendezvous + rank/world,
``subtree/rabit/tracker/rabit_tracker.py:125-309``) collapses to a
``jax.sharding.Mesh``: the JAX runtime owns rendezvous and the mesh
axis name is the communicator.  The flagship mode is row-split data
parallelism over axis ``"data"`` (SURVEY.md §2.4 item 2 → psum over ICI).

Multi-host: build the mesh over ``jax.devices()`` after
``jax.distributed.initialize()`` — same code path, collectives ride
ICI within a slice and DCN across slices.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

DATA_AXIS = "data"

_default_mesh: Optional[Mesh] = None


def set_mesh(mesh: Optional[Mesh]) -> None:
    """Install a process-wide default mesh for dsplit=row training."""
    global _default_mesh
    _default_mesh = mesh


def get_mesh() -> Optional[Mesh]:
    return _default_mesh


def data_parallel_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the first n (default all) devices, axis 'data'.

    Auto axis types: tree traversal gathers (replicated node tables,
    row-sharded indices) rely on GSPMD propagation, which Explicit mode
    rejects as ambiguous.
    """
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return jax.make_mesh((len(devs),), (DATA_AXIS,), devices=devs,
                         axis_types=(jax.sharding.AxisType.Auto,))

"""Column-split distributed tree growth (``dsplit=col`` — the
reference's DistColMaker, ``src/tree/updater_distcol-inl.hpp``).

Model/feature parallelism: every device holds ALL rows but only a shard
of the features (the reference's per-worker column shard).  The growth
loop is the shared :func:`xgboost_tpu.models.tree.grow_tree`; this module
supplies its three collective hooks:

  - split finder: local best per shard, then all-gather + argmax — the
    analog of the ``Reducer<SplitEntry>`` allreduce with its
    deterministic lowest-feature-id tie-break
    (``distcol-inl.hpp:136-153``, ``param.h:335-405``);
  - router: the winning shard owns the split feature's bin column, so
    row left/right routing is a psum of owner-masked go-left bits — the
    analog of the BitOR bitmap allreduce (``distcol-inl.hpp:115-117``);
  - feature sampler: colsample masks are drawn over the GLOBAL (real)
    feature ids with a shared key so shards agree — the analog of
    broadcasting rank-0's sampled feature list
    (``basemaker-inl.hpp:79-88``).

Every shard ends each level with identical split decisions, so trees are
replicated without a TreeSyncher broadcast (``updater_sync-inl.hpp``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from xgboost_tpu.models.tree import (GrowConfig, SplitDecision,
                                     _sample_features, bin_of_feature,
                                     grow_tree,
                                     table_lookup)
from xgboost_tpu.ops.split import NEG, RT_EPS, find_best_splits

FEAT_AXIS = "feat"


def feature_parallel_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over devices, axis 'feat' (column shards)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    from xgboost_tpu.parallel.mesh import make_mesh
    return make_mesh((len(devs),), (FEAT_AXIS,), devices=devs)


def grow_tree_colsplit(mesh: Mesh, key, binned, gh, cut_values, n_cuts,
                       cfg: GrowConfig, row_valid=None, f_real=None):
    """Grow one tree with features sharded over mesh axis 'feat'.

    binned: (N, F) bin ids with F padded to a multiple of the mesh size
    (padding features have n_cuts == 0 and are never selected);
    gh: (N, 2) replicated; f_real: the unpadded feature count (defaults
    to F).  Returns (tree [replicated], row_leaf (N,), delta (N,) leaf
    contribution) — all replicated.
    """
    n_shard = mesh.shape[FEAT_AXIS]
    N, F = binned.shape
    assert F % n_shard == 0, "pad features to the mesh size first"
    f_local = F // n_shard

    if row_valid is None:
        row_valid = jnp.ones(N, jnp.bool_)
    fn = _colsplit_fn(mesh, cfg, f_local, n_shard,
                      F if f_real is None else int(f_real))
    # collective accounting (obs/comm.py, the report_stats analog):
    # each level all-gathers one SplitDecision per shard per node and
    # psums the (N,) routing bits — count one "allgather" per level
    # with the logical per-level payload (estimate; the launch itself
    # is one fused XLA program, so wall time covers the whole tree)
    from xgboost_tpu.obs import comm
    n_nodes = (1 << cfg.max_depth) - 1
    est_bytes = (cfg.max_depth * n_shard * 24     # SplitDecision fields
                 + n_nodes * 24                   # per-node candidates
                 + cfg.max_depth * N * 4)         # routing-bit psum
    with comm.timed("allgather", nbytes=float(est_bytes),
                    count=cfg.max_depth):
        return fn(key, binned, gh, cut_values, n_cuts, row_valid)


@functools.lru_cache(maxsize=64)
def _colsplit_fn(mesh: Mesh, cfg: GrowConfig, f_local: int, n_shard: int,
                 f_real: int):
    """Build + cache the jitted shard_map'd growth fn per (mesh, config).

    The three hooks are constructed HERE (once per cache key) so their
    identities are stable and grow_tree's jit cache is hit across calls.
    """
    split_finder = functools.partial(_colsplit_split_finder, f_local=f_local)
    router = functools.partial(_colsplit_router, f_local=f_local)
    feat_sampler = functools.partial(_colsplit_feat_sampler, f_local=f_local,
                                     n_shard=n_shard, f_real=f_real)

    def body(key, binned, gh, cut_values, n_cuts, row_valid):
        tree, row_leaf, row_val = grow_tree(
            key, binned, gh, cut_values, n_cuts, cfg, row_valid,
            split_finder=split_finder, router=router,
            feat_sampler=feat_sampler)
        delta = row_val * row_valid.astype(jnp.float32)
        return tree, row_leaf, delta

    # check_vma=False: every shard derives the SAME tree/row outputs from
    # all-gathered split candidates and psum'd routing bits, but the static
    # varying-manifest analysis cannot see through the argmax/gather chain.
    from xgboost_tpu.parallel.mesh import shard_map
    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(None, FEAT_AXIS), P(), P(FEAT_AXIS, None),
                  P(FEAT_AXIS), P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    ))


def _colsplit_split_finder(hist, nst, n_cuts, cut_values, fmask, split_cfg,
                           *, f_local: int) -> SplitDecision:
    """Local best split per shard, merged by all-gather + argmax (the
    SplitEntry allreduce).  Shards are ordered by axis index = ordered by
    global feature id, and argmax takes the FIRST max, so the reference's
    lowest-fid tie-break is preserved."""
    shard = jax.lax.axis_index(FEAT_AXIS)
    local = find_best_splits(hist, nst, n_cuts, split_cfg, fmask)
    thr_local = cut_values[local.feature, local.cut_index]

    gains = jax.lax.all_gather(
        jnp.where(local.valid, local.gain, NEG), FEAT_AXIS)
    gfid = jax.lax.all_gather(shard * f_local + local.feature, FEAT_AXIS)
    cuts_g = jax.lax.all_gather(local.cut_index, FEAT_AXIS)
    dl_g = jax.lax.all_gather(local.default_left, FEAT_AXIS)
    thr_g = jax.lax.all_gather(thr_local, FEAT_AXIS)

    winner = jnp.argmax(gains, axis=0)                    # (n_node,)

    def take(a):
        return jnp.take_along_axis(a, winner[None], axis=0)[0]

    best_gain = take(gains)
    return SplitDecision(
        gain=best_gain, feature=take(gfid), cut_index=take(cuts_g),
        default_left=take(dl_g), threshold=take(thr_g),
        valid=best_gain > RT_EPS, owner=winner.astype(jnp.int32))


def _colsplit_router(best: SplitDecision, node_of_row, binned, *,
                     f_local: int):
    """Owner-shard routing + psum 'bitmap' exchange
    (distcol-inl.hpp:115-117)."""
    shard = jax.lax.axis_index(FEAT_AXIS)
    owner_row = best.owner[node_of_row]
    lf_row = best.feature[node_of_row] - owner_row * f_local
    i_own = owner_row == shard
    b = bin_of_feature(binned, jnp.clip(lf_row, 0, binned.shape[1] - 1))
    dl_row = best.default_left[node_of_row]
    j_row = best.cut_index[node_of_row]
    go_left_local = jnp.where(b == 0, dl_row, b <= j_row + 1)
    return jax.lax.psum(
        (go_left_local & i_own).astype(jnp.int32), FEAT_AXIS) > 0


def _colsplit_feat_sampler(key, rate, binned, *, f_local: int, n_shard: int,
                           f_real: int):
    """Sample a global colsample mask over the REAL features only (so
    padding features can never be the non-empty fallback and results
    match a single-device run over the same feature set), then slice the
    local shard's piece."""
    shard = jax.lax.axis_index(FEAT_AXIS)
    mask_real = _sample_features(key, f_real, rate)
    mask_global = jnp.zeros(f_local * n_shard, jnp.bool_
                            ).at[:f_real].set(mask_real)
    return jax.lax.dynamic_slice(mask_global, (shard * f_local,), (f_local,))


# ------------------------------------------------------- exact column-split

def grow_tree_exact_colsplit(mesh: Mesh, key, X, gh, cfg: GrowConfig,
                             row_valid=None, has_missing: bool = True,
                             rank_t=None, uniq=None, f_real=None):
    """TRUE exact-greedy growth with features sharded over 'feat' — the
    reference's DistColMaker running full exact enumeration on each
    worker's column shard at ANY cardinality
    (``updater_distcol-inl.hpp:136-153`` over ColMaker's scan
    ``updater_colmaker-inl.hpp:362-414``).

    The segment-sorted exact finder (models/colmaker.py) is
    feature-local by construction — its per-level (node, value) sorts
    and prefix scans never mix features — so each shard runs it
    unchanged on its own raw columns; the per-node winners then reduce
    through the same all-gather + argmax as the histogram column split
    (lowest-global-fid tie-break preserved: shards are ordered by axis
    index = global fid block, argmax takes the first max), and row
    routing is the owner-masked psum bitmap with RAW-value comparison
    (``x < thr``) instead of bin comparison.

    X: (N, F) raw values, F padded to a multiple of the mesh size with
    all-NaN columns (they sort into the trash segment and can never
    win); rank_t/uniq: optional (F, N) dense-rank structures
    (build_exact_ranks on the PADDED matrix).  Returns (tree, row_leaf,
    delta), all replicated.
    """
    n_shard = mesh.shape[FEAT_AXIS]
    N, F = X.shape
    assert F % n_shard == 0, "pad features to the mesh size first"
    f_local = F // n_shard
    if row_valid is None:
        row_valid = jnp.ones(N, jnp.bool_)
    fn = _colsplit_exact_fn(mesh, cfg, f_local, n_shard,
                            F if f_real is None else int(f_real),
                            bool(has_missing), rank_t is not None)
    if rank_t is None:
        rank_t = jnp.zeros((F, 0), jnp.int32)   # placeholder, unused
        uniq = jnp.zeros((F, 0), jnp.float32)
    return fn(key, X, gh, row_valid, rank_t, uniq)


@functools.lru_cache(maxsize=64)
def _colsplit_exact_fn(mesh: Mesh, cfg: GrowConfig, f_local: int,
                       n_shard: int, f_real: int, has_missing: bool,
                       ranked: bool):
    """Build + cache the jitted shard_map'd exact growth fn (stable hook
    identities, same pattern as _colsplit_fn)."""
    from xgboost_tpu.models.colmaker import grow_tree_exact

    split_merge = functools.partial(_colsplit_exact_merge, f_local=f_local)
    router = functools.partial(_colsplit_exact_router, f_local=f_local)
    feat_sampler = functools.partial(_colsplit_feat_sampler,
                                     f_local=f_local, n_shard=n_shard,
                                     f_real=f_real)

    def body(key, X, gh, row_valid, rank_t, uniq):
        tree, row_leaf = grow_tree_exact(
            key, X, gh, cfg, row_valid, has_missing=has_missing,
            rank_t=rank_t if ranked else None,
            uniq=uniq if ranked else None,
            split_merge=split_merge, router=router,
            feat_sampler=feat_sampler)
        delta = (table_lookup(tree.leaf_value, row_leaf)
                 * row_valid.astype(jnp.float32))
        return tree, row_leaf, delta

    # check_vma=False for the same reason as _colsplit_fn: every shard
    # derives identical outputs from the merged winners + psum'd bits
    from xgboost_tpu.parallel.mesh import shard_map
    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(None, FEAT_AXIS), P(), P(),
                  P(FEAT_AXIS, None), P(FEAT_AXIS, None)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    ))


def _colsplit_exact_merge(local: SplitDecision, *, f_local: int
                          ) -> SplitDecision:
    """Per-shard exact winners -> global winner by all-gather + argmax
    (the SplitEntry allreduce, distcol-inl.hpp:136-153).  Thresholds
    are already raw midpoints, so no cut table is consulted; left-child
    (G, H) ride along for the grower's terminal-level derivation."""
    shard = jax.lax.axis_index(FEAT_AXIS)
    gains = jax.lax.all_gather(
        jnp.where(local.valid, local.gain, NEG), FEAT_AXIS)
    gfid = jax.lax.all_gather(shard * f_local + local.feature, FEAT_AXIS)
    thr_g = jax.lax.all_gather(local.threshold, FEAT_AXIS)
    dl_g = jax.lax.all_gather(local.default_left, FEAT_AXIS)
    gl_g = jax.lax.all_gather(local.left_g, FEAT_AXIS)
    hl_g = jax.lax.all_gather(local.left_h, FEAT_AXIS)

    winner = jnp.argmax(gains, axis=0)                    # (n_node,)

    def take(a):
        return jnp.take_along_axis(a, winner[None], axis=0)[0]

    best_gain = take(gains)
    return SplitDecision(
        gain=best_gain, feature=take(gfid),
        cut_index=jnp.zeros_like(winner, dtype=jnp.int32),
        default_left=take(dl_g), threshold=take(thr_g),
        valid=best_gain > RT_EPS, owner=winner.astype(jnp.int32),
        left_g=take(gl_g), left_h=take(hl_g))


def _colsplit_exact_router(best: SplitDecision, node_of_row, X, x_missing,
                           *, f_local: int):
    """Owner-shard raw-value routing + psum 'bitmap' exchange
    (distcol-inl.hpp:115-117): only the shard holding the winning
    feature's raw column decides, everyone sums the masked bits."""
    shard = jax.lax.axis_index(FEAT_AXIS)
    owner_row = table_lookup(best.owner, node_of_row)
    lf_row = table_lookup(best.feature, node_of_row) - owner_row * f_local
    i_own = owner_row == shard
    sel = (jnp.arange(f_local, dtype=jnp.int32)[None, :]
           == jnp.clip(lf_row, 0, f_local - 1)[:, None])
    x_row = jnp.where(sel, jnp.nan_to_num(X), 0.0).sum(axis=1)
    miss = (sel & x_missing).any(axis=1)
    thr_row = table_lookup(best.threshold, node_of_row)
    dl_row = table_lookup(best.default_left, node_of_row)
    go_left_local = jnp.where(miss, dl_row, x_row < thr_row)
    return jax.lax.psum(
        (go_left_local & i_own).astype(jnp.int32), FEAT_AXIS) > 0


def pad_features(arr, multiple: int, axis: int, fill=0):
    """Pad the feature axis to a multiple of the mesh size."""
    F = arr.shape[axis]
    pad = (-F) % multiple
    if pad == 0:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, pad)
    return jnp.pad(arr, widths, constant_values=fill)

"""Data loading dispatcher.

Mirrors the reference IO dispatcher (``src/io/io.cpp:13-92``):
``path#cachefile`` suffix parsing, binary-cache sniffing, per-rank cache
names in distributed mode, and sidecar metadata loading.
"""

from __future__ import annotations

import os

import numpy as np


def load_dmatrix_into(dmat, uri: str, silent: bool = True,
                      rank: int = 0, nparts: int = 1) -> None:
    """Populate `dmat` (a DMatrix) from a URI.

    Supported forms (reference io.cpp:20-29):
      - ``file.txt``              — libsvm text
      - ``file.txt#cache``        — libsvm text with binary cache file
      - ``file.npz``              — saved binary DMatrix
      - ``file://...``            — local path in URI form
      - ``scheme://...``          — remote text (s3, gs, hdfs, http,
        abfs, memory, ...), streamed through the first available
        opener: the ``XGBTPU_REMOTE_CAT`` command override, a scheme
        CLI client on PATH (``aws``/``gsutil``/``hdfs``), or an fsspec
        driver (reference io.cpp:32-35 routes these to dmlc-core's
        filesystem layer and errors without a dmlc build; the error
        here names all three seams)
    """
    path, _, cache = uri.partition("#")
    if nparts > 1 and cache:
        cache = f"{cache}.r{rank}-{nparts}"  # per-rank cache (io.cpp:56-61)

    # any scheme-qualified URI is remote (s3/gs/hdfs via CLI clients or
    # fsspec; anything else — http, abfs, memory, ... — via fsspec)
    remote = "://" in path and not path.startswith("file://")
    if path.startswith("file://"):
        # RFC 8089 forms: file:///p, file://localhost/p, %-escapes
        from urllib.parse import unquote, urlparse
        u = urlparse(path)
        if u.netloc not in ("", "localhost"):
            raise ValueError(f"{uri}: file:// URIs must be local "
                             f"(host {u.netloc!r} is not)")
        path = unquote(u.path)
    if remote:
        cache_file = cache + ".npz" if cache else None
        if cache_file and os.path.exists(cache_file):
            # a populated '#cache' skips the download entirely
            _copy_from(dmat, _load_npz(cache_file))
            return
        # stream to a local temp file and run the shared parse/cache
        # path on it (sidecar files are local-only by definition)
        spooled = _fetch_remote(path)
        try:
            _load_local(dmat, spooled, cache, uri, silent, rank, nparts,
                        sidecars=False)
        finally:
            os.unlink(spooled)
        return

    if path == "stdin":
        # text-over-stdin loading (reference io.cpp:32-38 — the Hadoop
        # streaming channel): spool to a temp file for the shared parser
        import sys
        import tempfile
        from xgboost_tpu.data import parse_libsvm
        if os.environ.get("XGBTPU_COORD"):
            raise ValueError(
                "data=stdin cannot be used under the multi-worker "
                "launcher: every worker would race on one inherited "
                "stdin pipe; pass a file path instead")
        # scratch spool, unlinked in the finally below — not a durable
        # destination, so tmp+rename buys nothing here
        # xgtpu: disable=XGT003
        with tempfile.NamedTemporaryFile("wb", suffix=".libsvm",
                                         delete=False) as tf:
            tf.write(sys.stdin.buffer.read())
            spooled = tf.name
        try:
            indptr, indices, values, labels = parse_libsvm(
                spooled, rank, nparts)
        finally:
            os.unlink(spooled)
        dmat.indptr, dmat.indices, dmat.values = indptr, indices, values
        dmat._num_col = int(indices.max()) + 1 if len(indices) else 0
        dmat.info.set_field("label", labels)
        return

    _load_local(dmat, path, cache, uri, silent, rank, nparts)


def _load_local(dmat, path: str, cache: str, uri: str, silent: bool,
                rank: int, nparts: int, sidecars: bool = True) -> None:
    """Shared local-file path: cache check, magic sniffing, parse,
    sidecars, cache write."""
    from xgboost_tpu.data import parse_libsvm, load_meta_sidecars

    cache_file = cache + ".npz" if cache else None
    if cache_file and os.path.exists(cache_file):
        _copy_from(dmat, _load_npz(cache_file))
        return
    if path.endswith(".npz") and os.path.exists(path):
        _copy_from(dmat, _load_npz(path))
        return
    # magic sniffing regardless of suffix (the reference's .buffer
    # convention, io.cpp:36-45): a saved binary cache is a zip container
    if os.path.exists(path):
        with open(path, "rb") as f:
            if f.read(4) == b"PK\x03\x04":
                _copy_from(dmat, _load_npz(path))
                return

    indptr, indices, values, labels = parse_libsvm(path, rank, nparts)
    dmat.indptr, dmat.indices, dmat.values = indptr, indices, values
    dmat._num_col = int(indices.max()) + 1 if len(indices) else 0
    dmat.info.set_field("label", labels)
    if sidecars:
        load_meta_sidecars(dmat, path)
    if cache_file:
        dmat.save_binary(cache_file[:-len(".npz")] + ".npz")
    if not silent:
        print(f"{len(labels)}x{dmat._num_col} matrix with {len(values)} "
              f"entries loaded from {uri}")


def _fetch_remote(uri: str) -> str:
    """Stream a remote text object to a local temp file.

    Opener order (the pluggable seam; reference delegates these schemes
    to dmlc-core's filesystem layer and refuses without a dmlc build,
    io.cpp:32-35):
      1. ``XGBTPU_REMOTE_CAT`` env — custom ``<cmd> <uri>``-to-stdout
         fetcher (also the test seam);
      2. a scheme CLI client on PATH (``aws`` / ``gsutil`` / ``hdfs``);
      3. ``fsspec``, which covers every protocol it has a driver for
         (s3 via s3fs, gs via gcsfs, http, abfs, memory, ...).
    A clear error names all three seams when none applies."""
    import shutil
    import subprocess
    import tempfile

    custom = os.environ.get("XGBTPU_REMOTE_CAT")
    cmd = None
    if custom:
        cmd = custom.split() + [uri]
    elif uri.startswith("s3://") and shutil.which("aws"):
        cmd = ["aws", "s3", "cp", uri, "-"]
    elif uri.startswith("gs://") and shutil.which("gsutil"):
        cmd = ["gsutil", "cat", uri]
    elif uri.startswith("hdfs://") and shutil.which("hdfs"):
        cmd = ["hdfs", "dfs", "-cat", uri]

    # scratch spool for the remote fetch; the caller unlinks it after
    # loading (and the except below unlinks on failure) — not durable
    # xgtpu: disable=XGT003
    with tempfile.NamedTemporaryFile("wb", suffix=".libsvm",
                                     delete=False) as tf:
        try:
            if cmd is not None:
                subprocess.run(cmd, stdout=tf, check=True)
                return tf.name
            try:
                import fsspec
            except ImportError:
                fsspec = None
            if fsspec is not None:
                try:
                    with fsspec.open(uri, "rb") as src:
                        shutil.copyfileobj(src, tf)
                    return tf.name
                except (ImportError, ValueError) as e:
                    # no driver for the scheme (s3fs/gcsfs not
                    # installed) — fall through to the naming error
                    fs_err = f" (fsspec: {e})"
            else:
                fs_err = " (fsspec not installed)"
            scheme = uri.split("://", 1)[0]
            client = {"s3": "aws", "gs": "gsutil", "hdfs": "hdfs"}.get(
                scheme)
            hint = f"`{client}` on PATH, " if client else ""
            raise ValueError(
                f"{uri}: no opener for {scheme}:// — need {hint}an "
                f"fsspec driver for {scheme}, or XGBTPU_REMOTE_CAT set "
                f"to a command that streams the object to stdout"
                f"{fs_err}")
        except BaseException as e:
            # never leak the temp file, whatever the opener raised
            # (botocore/aiohttp/... errors included); other Exceptions
            # are wrapped so callers see one failure type
            os.unlink(tf.name)
            if isinstance(e, ValueError) or not isinstance(e, Exception):
                raise
            raise ValueError(f"fetching {uri} failed: {e}")


def _load_npz(path: str):
    from xgboost_tpu.data import DMatrix
    return DMatrix.load_binary(path)


def _copy_from(dst, src) -> None:
    dst.indptr, dst.indices, dst.values = src.indptr, src.indices, src.values
    dst._num_col = src._num_col
    dst.info = src.info

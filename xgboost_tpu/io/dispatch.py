"""Data loading dispatcher.

Mirrors the reference IO dispatcher (``src/io/io.cpp:13-92``):
``path#cachefile`` suffix parsing, binary-cache sniffing, per-rank cache
names in distributed mode, and sidecar metadata loading.
"""

from __future__ import annotations

import os

import numpy as np


def load_dmatrix_into(dmat, uri: str, silent: bool = True,
                      rank: int = 0, nparts: int = 1) -> None:
    """Populate `dmat` (a DMatrix) from a URI.

    Supported forms (reference io.cpp:20-29):
      - ``file.txt``              — libsvm text
      - ``file.txt#cache``        — libsvm text with binary cache file
      - ``file.npz``              — saved binary DMatrix
    """
    from xgboost_tpu.data import parse_libsvm, load_meta_sidecars

    path, _, cache = uri.partition("#")
    if nparts > 1 and cache:
        cache = f"{cache}.r{rank}-{nparts}"  # per-rank cache (io.cpp:56-61)

    if path == "stdin":
        # text-over-stdin loading (reference io.cpp:32-38 — the Hadoop
        # streaming channel): spool to a temp file for the shared parser
        import sys
        import tempfile
        if os.environ.get("XGBTPU_COORD"):
            raise ValueError(
                "data=stdin cannot be used under the multi-worker "
                "launcher: every worker would race on one inherited "
                "stdin pipe; pass a file path instead")
        with tempfile.NamedTemporaryFile("wb", suffix=".libsvm",
                                         delete=False) as tf:
            tf.write(sys.stdin.buffer.read())
            spooled = tf.name
        try:
            indptr, indices, values, labels = parse_libsvm(
                spooled, rank, nparts)
        finally:
            os.unlink(spooled)
        dmat.indptr, dmat.indices, dmat.values = indptr, indices, values
        dmat._num_col = int(indices.max()) + 1 if len(indices) else 0
        dmat.info.set_field("label", labels)
        return

    cache_file = cache + ".npz" if cache else None
    if cache_file and os.path.exists(cache_file):
        _copy_from(dmat, _load_npz(cache_file))
        return
    if path.endswith(".npz") and os.path.exists(path):
        _copy_from(dmat, _load_npz(path))
        return
    # magic sniffing regardless of suffix (the reference's .buffer
    # convention, io.cpp:36-45): a saved binary cache is a zip container
    if os.path.exists(path):
        with open(path, "rb") as f:
            if f.read(4) == b"PK\x03\x04":
                _copy_from(dmat, _load_npz(path))
                return

    indptr, indices, values, labels = parse_libsvm(path, rank, nparts)
    dmat.indptr, dmat.indices, dmat.values = indptr, indices, values
    dmat._num_col = int(indices.max()) + 1 if len(indices) else 0
    dmat.info.set_field("label", labels)
    load_meta_sidecars(dmat, path)
    if cache_file:
        dmat.save_binary(cache_file[:-len(".npz")] + ".npz")
    if not silent:
        print(f"{len(labels)}x{dmat._num_col} matrix with {len(values)} "
              f"entries loaded from {uri}")


def _load_npz(path: str):
    from xgboost_tpu.data import DMatrix
    return DMatrix.load_binary(path)


def _copy_from(dst, src) -> None:
    dst.indptr, dst.indices, dst.values = src.indptr, src.indices, src.values
    dst._num_col = src._num_col
    dst.info = src.info

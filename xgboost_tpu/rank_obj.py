"""LambdaRank objectives: rank:pairwise, rank:ndcg, rank:map.

Re-implements the reference LambdaRank family
(``src/learner/objective-inl.hpp:274-570``): per-group random pair
sampling between label buckets (:323-344), logistic pairwise gradients
with hessian doubling (:352-363), NDCG delta weights
(``LambdaRankObjNDCG::GetLambdaWeight`` :435-480) and MAP delta weights
(``LambdaRankObjMAP`` :483-570), plus ``num_pairsample`` /
``fix_list_weight`` scaling.

Pair sampling is host-side per round (numpy RNG seeded by iteration —
the reference seeds per (iter, thread), :302-304); gradient math is
vectorized numpy over all sampled pairs.  Groups are typically small, so
this stays off-device; the resulting (N, 1, 2) gradient tensor feeds the
device tree grower like any other objective.
"""

from __future__ import annotations

import numpy as np

from xgboost_tpu.objectives import Objective

_EPS = 1e-16


class LambdaRankObj(Objective):
    default_metric = "map"

    def __init__(self, name: str):
        self.name = name
        self.kind = name.split(":")[1]  # pairwise | ndcg | map
        self.num_pairsample = 1
        self.fix_list_weight = 0.0
        # "device": pair sampling + delta weights fully on device
        # (rank_device.py — no per-round host transfer, fused-scan
        # eligible); "host": the reference-faithful numpy path below
        self.rank_impl = "device"
        self.seed = 0  # folds into the pair-sampling PRNGs
        if self.kind == "ndcg":
            self.default_metric = "ndcg"

    @property
    def needs_host_margin(self) -> bool:
        # host pair sampling reads the full margin each round
        return self.rank_impl == "host"

    def set_param(self, name, value):
        if name == "num_pairsample":
            self.num_pairsample = int(value)
        elif name == "fix_list_weight":
            self.fix_list_weight = float(value)
        elif name == "rank_impl":
            if value not in ("device", "host"):
                raise ValueError("rank_impl must be 'device' or 'host'")
            self.rank_impl = value
        elif name == "seed":
            self.seed = int(value)

    # ------------------------------------------------------ device path
    @staticmethod
    def _prep(info, n_pad: int):
        """Static per-dataset structures, cached ON THE INFO (shared by
        every Booster training on this matrix; cleared by set_field)."""
        from xgboost_tpu.rank_device import build_prep
        key = ("rank_prep", n_pad)
        if key not in info._dev_cache:
            labels = np.asarray(info.label)
            gptr = (np.asarray(info.group_ptr) if info.group_ptr is not None
                    else np.array([0, len(labels)], np.int64))
            info._dev_cache[key] = build_prep(labels, gptr, n_pad)
        return info._dev_cache[key]

    def _pad_tag(self, pad_prep):
        """The one cache-key construction for padded-gradient closures
        (fused closure and its jitted per-round wrapper derive from it;
        a second hand-built copy would drift)."""
        return ("rank_fused_pad", self.kind, self.num_pairsample,
                float(self.fix_list_weight), self.seed,
                pad_prep.G, pad_prep.L, pad_prep.n_tail)

    def _device_gradient(self, margin, info, iteration, n_rows,
                         pad_prep=None):
        import jax
        import jax.numpy as jnp
        if pad_prep is not None:
            # the fused closure doubles as the per-round jit unit (the
            # prep's shapes/maps are static only through a closure)
            base = self.fused_grad(info, pad_prep=pad_prep)
            tag = ("rank_pad_jit",) + self._pad_tag(pad_prep)
            if tag not in info._dev_cache:
                info._dev_cache[tag] = jax.jit(
                    lambda m, it: base(m, None, None, it))
            return info._dev_cache[tag](jnp.asarray(margin),
                                        jnp.int32(iteration))
        from xgboost_tpu.rank_device import rank_gradient
        prep = self._prep(info, n_rows)
        key = jax.random.fold_in(
            jax.random.PRNGKey(4177 + self.seed), iteration)
        gh = rank_gradient(jnp.asarray(margin)[:, 0], key, prep, self.kind,
                           self.num_pairsample, float(self.fix_list_weight))
        return gh[:, None, :]

    def fused_grad(self, info=None, pad_prep=None):
        """Device rank gradients are pure in (margin, iteration) given
        the static per-dataset prep — fused-scan eligible.  The closure
        is cached ON THE INFO: its identity is a jit static argument of
        the fused scan, and a per-Booster closure would force a full
        ~60 s re-trace for every new Booster on the same data.

        ``pad_prep`` (a rank_device.PadRankPrep) selects the
        group-padded gradient — passed by the learner for entries it
        laid out padded (the entry and the prep share one layout)."""
        if self.rank_impl != "device" or info is None:
            return None
        import jax
        kind = self.kind
        nps = self.num_pairsample
        flw = float(self.fix_list_weight)
        seed = self.seed
        if pad_prep is not None:
            from xgboost_tpu.rank_device import rank_gradient_padded
            key_tag = self._pad_tag(pad_prep)
            if key_tag in info._dev_cache:
                return info._dev_cache[key_tag]

            def f(margin, label, weight, iteration):
                key = jax.random.fold_in(
                    jax.random.PRNGKey(4177 + seed), iteration)
                gh = rank_gradient_padded(margin[:, 0], key, pad_prep,
                                          kind, nps, flw)
                return gh[:, None, :]

            info._dev_cache[key_tag] = f
            return f
        from xgboost_tpu.rank_device import rank_gradient
        key_tag = ("rank_fused", kind, nps, flw, self.seed)
        if key_tag in info._dev_cache:
            return info._dev_cache[key_tag]
        prep_fn = self._prep

        def f(margin, label, weight, iteration):
            # prep is built host-side at TRACE time (margin.shape is
            # static there) and enters the jaxpr as constants
            prep = prep_fn(info, margin.shape[0])
            key = jax.random.fold_in(
                jax.random.PRNGKey(4177 + seed), iteration)
            gh = rank_gradient(margin[:, 0], key, prep, kind, nps, flw)
            return gh[:, None, :]

        info._dev_cache[key_tag] = f
        return f

    def get_gradient(self, margin, info, iteration, n_rows,
                     pad_prep=None):
        if self.rank_impl == "device":
            return self._device_gradient(margin, info, iteration, n_rows,
                                         pad_prep)
        import jax.numpy as jnp
        preds = np.asarray(margin)[:, 0]
        labels = np.asarray(info.label)
        if info.group_ptr is None:
            gptr = np.array([0, len(labels)], dtype=np.int64)
        else:
            gptr = np.asarray(info.group_ptr, dtype=np.int64)
        # padded (distributed) rows may extend past the last group; they are
        # group-less and receive zero gradient
        assert gptr[-1] <= len(labels), \
            "group structure not consistent with #rows"
        rng = np.random.RandomState(
            iteration * 1111 + 17 + self.seed * 7919)
        grad = np.zeros(len(labels), dtype=np.float64)
        hess = np.zeros(len(labels), dtype=np.float64)
        for k in range(len(gptr) - 1):
            s, e = int(gptr[k]), int(gptr[k + 1])
            self._group_gradient(preds[s:e], labels[s:e], rng,
                                 grad[s:e], hess[s:e])
        gh = np.stack([grad, hess], axis=-1).astype(np.float32)[:, None, :]
        return jnp.asarray(gh)

    # ------------------------------------------------------------------
    def _group_gradient(self, preds, labels, rng, out_g, out_h):
        n = len(preds)
        if n < 2:
            return
        order = np.argsort(-preds, kind="stable")  # sorted by pred desc
        slab = labels[order]                        # labels in pred order
        # rec: positions (into sorted list) ordered by label desc
        lorder = np.argsort(-slab, kind="stable")
        lsorted = slab[lorder]
        # bucket boundaries of equal label
        starts = np.concatenate(
            [[0], np.nonzero(lsorted[1:] != lsorted[:-1])[0] + 1, [n]])
        pos_list, neg_list = [], []
        for bi in range(len(starts) - 1):
            i, j = starts[bi], starts[bi + 1]
            nleft, nright = i, n - j
            if nleft + nright == 0:
                continue
            size = (j - i) * self.num_pairsample
            pid = np.tile(np.arange(i, j), self.num_pairsample)
            ridx = (rng.random_sample(size) * (nleft + nright)).astype(np.int64)
            # partner above the bucket (higher label) -> partner is pos
            hi = ridx < nleft
            pos_list.append(np.where(hi, ridx, pid))
            neg_list.append(np.where(hi, pid, ridx + (j - i)))
        if not pos_list:
            return
        # indices are into the label-sorted view; map to pred-sorted positions
        p_pos = lorder[np.concatenate(pos_list)]
        p_neg = lorder[np.concatenate(neg_list)]
        w = self._lambda_weight(slab, p_pos, p_neg)
        scale = 1.0 / self.num_pairsample
        if self.fix_list_weight != 0.0:
            scale *= self.fix_list_weight / n
        w = w * scale
        spreds = preds[order]
        p = 1.0 / (1.0 + np.exp(-(spreds[p_pos] - spreds[p_neg])))
        g = (p - 1.0) * w
        h = np.maximum(p * (1.0 - p), _EPS) * 2.0 * w
        rindex = order  # sorted position -> original row
        np.add.at(out_g, rindex[p_pos], g)
        np.add.at(out_g, rindex[p_neg], -g)
        np.add.at(out_h, rindex[p_pos], h)
        np.add.at(out_h, rindex[p_neg], h)

    def _lambda_weight(self, slab, p_pos, p_neg):
        """Pair weights given positions in the pred-sorted list."""
        if self.kind == "pairwise":
            return np.ones(len(p_pos))
        if self.kind == "ndcg":
            rel = slab.astype(np.int64)
            idcg_rel = np.sort(rel)[::-1]
            disc = 1.0 / np.log(np.arange(len(slab)) + 2.0)
            idcg = np.sum((2.0 ** idcg_rel - 1.0) * disc)
            if idcg == 0.0:
                return np.zeros(len(p_pos))
            pos_loginv = 1.0 / np.log(p_pos + 2.0)
            neg_loginv = 1.0 / np.log(p_neg + 2.0)
            pg = 2.0 ** rel[p_pos] - 1.0
            ng = 2.0 ** rel[p_neg] - 1.0
            original = pg * pos_loginv + ng * neg_loginv
            changed = ng * pos_loginv + pg * neg_loginv
            return np.abs((original - changed) / idcg)
        # MAP (reference GetMAPStats/GetLambdaMAP, :483-570)
        hit = (slab > 0).astype(np.float64)
        hits = np.cumsum(hit)
        inv_i = 1.0 / np.arange(1, len(slab) + 1)
        acc1 = np.cumsum(hit * hits * inv_i)          # ap_acc
        acc2 = np.cumsum(hit * (hits - 1.0) * inv_i)  # ap_acc_miss
        acc3 = np.cumsum(hit * (hits + 1.0) * inv_i)  # ap_acc_add
        total_hits = hits[-1]
        if total_hits == 0:
            return np.zeros(len(p_pos))
        i1 = np.minimum(p_pos, p_neg)
        i2 = np.maximum(p_pos, p_neg)
        lab1 = (slab[i1] > 0).astype(np.float64)
        lab2 = (slab[i2] > 0).astype(np.float64)
        original = acc1[i2] - np.where(i1 > 0, acc1[np.maximum(i1 - 1, 0)], 0.0)
        ch_insert = (acc3[np.maximum(i2 - 1, 0)] - acc3[i1]
                     + (hits[i1] + 1.0) / (i1 + 1))
        ch_remove = (acc2[np.maximum(i2 - 1, 0)] - acc2[i1]
                     + hits[i2] / (i2 + 1))
        changed = np.where(lab1 < lab2, ch_insert, ch_remove)
        delta = np.abs((changed - original) / total_hits)
        delta[lab1 == lab2] = 0.0
        delta[i1 == i2] = 0.0
        return delta

"""GBTree: gradient-boosted tree ensemble booster.

The reference's ``GBTree`` (``src/gbm/gbtree-inl.hpp``): per-class tree
groups (:102-121), ``num_parallel_tree`` boosted-random-forest mode
(:393-396), prediction buffers keyed by leaf positions (:258-303), and
model commit per boosting round.  Here trees are fixed-shape tensor
stacks; the prediction "buffer" is an incrementally maintained margin
per cached DMatrix, updated from grow-time leaf positions — the same
fast path as the reference's ``GetLeafPosition`` shortcut
(``updater_distcol-inl.hpp:40-42``).
"""

from __future__ import annotations

import functools
import os
import sys
import time
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from xgboost_tpu.binning import CutMatrix, _rank0
from xgboost_tpu.config import TrainParam
from xgboost_tpu.models.tree import (GrowConfig, TreeArrays, grow_tree,
                                     predict_leaf_binned,
                                     predict_margin_binned,
                                     predict_margin_fused, table_lookup,
                                     tree_capacity)
from xgboost_tpu.ops.split import SplitConfig


_WARNED: set = set()


def _warn_once(key: str) -> bool:
    if key in _WARNED:
        return False
    _WARNED.add(key)
    return True


def make_grow_config(p: TrainParam, n_bin: int) -> GrowConfig:
    split = SplitConfig(
        reg_lambda=p.reg_lambda, reg_alpha=p.reg_alpha,
        max_delta_step=p.max_delta_step, min_child_weight=p.min_child_weight,
        gamma=p.gamma, eta=p.eta, default_direction=p.default_direction)
    # Histogram subtraction: OFF, env-gated rather than a config param.
    # Measured on v5e (PROFILE.md round 3): the MXU one-hot kernel's
    # cost is per-row-tile, so subtraction only pays with row
    # compaction — and XLA scatter/gather compaction costs 18-60 ms per
    # level at 1M rows, an order of magnitude more than the ~5 ms/level
    # it saves.  XGBTPU_HIST_SUBTRACTION=1 keeps the A/B reachable
    # (numerics tested equal; tests/test_updaters.py); a
    # hist_subtraction=... train param lands in extras and warns.
    hs = os.environ.get("XGBTPU_HIST_SUBTRACTION", "0") == "1"
    if ("hist_subtraction" in getattr(p, "extras", {})
            and int(getattr(p, "silent", 0)) == 0
            and _warn_once("hist_subtraction") and _rank0()):
        print("[config] hist_subtraction is no longer a parameter "
              "(measured ~10x slower on TPU; PROFILE.md round 3) — "
              "ignored.  Set env XGBTPU_HIST_SUBTRACTION=1 to force "
              "the subtraction path for kernel A/Bs.", file=sys.stderr)
    return GrowConfig(split=split, max_depth=p.max_depth, n_bin=n_bin,
                      subsample=p.subsample,
                      colsample_bytree=p.colsample_bytree,
                      colsample_bylevel=p.colsample_bylevel,
                      hist_precision=p.hist_precision,
                      hist_subtraction=bool(hs),
                      n_roots=max(1, p.num_roots))


@functools.partial(jax.jit, static_argnames=("t",))
def _unstack_trees(stacked, t: int):
    """Slice a (T, ...) tree stack into a tuple of per-tree pytrees in
    ONE device launch.  Doing this as T x n_fields eager ops costs a
    dispatch each — through a tunnel-attached TPU that serialized into
    hundreds of ms per boosting round (measured; PROFILE.md)."""
    return tuple(jax.tree.map(lambda x: x[i], stacked) for i in range(t))


@functools.partial(jax.jit, static_argnames=("t",))
def _unstack_lane_flats(stacked, t: int):
    """Slice the lane axis of a (L, n_rounds, K*npar, ...) gang-scan
    tree output into per-lane FLAT (n_rounds*K*npar, ...) stacks, all
    in ONE device launch.  The flatten rides inside the same program:
    reshaping eagerly per lane costs a dispatch per lane per tree field
    and dominated the stacked cycle (tools/bench_lanes.py)."""
    flat = jax.tree.map(
        lambda x: x.reshape((x.shape[0], -1) + x.shape[3:]), stacked)
    return tuple(jax.tree.map(lambda x: x[i], flat) for i in range(t))


def _scan_rounds_impl(binned, margin, label, weight, base_key,
                      first_iteration, cut_values, n_cuts, row_valid,
                      binned_t, eval_binned, eval_margins, *,
                      n_rounds: int, K: int,
                      npar: int, cfg: GrowConfig, split_finder, grad_fn,
                      mesh, eval_is_train, etransform, pred_chunk: int,
                      hist_reduce=None):
    """``lax.scan`` over whole boosting rounds (one device launch for
    n_rounds x K x npar trees).  Module-level so the jit cache is shared
    across Booster instances: all static arguments (cfg, grad_fn,
    split_finder, etransform) carry stable identities.

    Device-resident eval (segmented round fusion): ``eval_binned``
    carries one binned matrix per non-train watchlist set and the
    corresponding ``eval_margins`` ride the scan carry; each round adds
    the round's tree contributions through the SAME
    ``predict_margin_binned`` expression the per-round margin sync uses
    (same ``pred_chunk``), then applies ``etransform``
    (Objective.eval_transform) — so the per-round transformed outputs
    the scan stacks are bit-identical to what the per-round eval path
    would have pulled, with zero host dispatches between rounds.
    ``eval_is_train`` marks watchlist slots that ARE the training
    matrix: those read the grow-time margin directly (the per-round
    path's prediction-buffer shortcut) instead of re-traversing.

    Returns ``(final margin (N, K), final eval margins,
    stacked trees (n_rounds, K*npar, ...),
    per-round transformed eval outputs (one (n_rounds, N_e, K) per
    watchlist slot))``.
    """
    T_pr = K * npar
    group_pr = jnp.asarray([j // npar for j in range(T_pr)], jnp.int32)

    def grow_one(tkey, gh2):
        if mesh is not None:
            from xgboost_tpu.parallel.dp import grow_tree_dp
            rv = (row_valid if row_valid is not None
                  else jnp.ones(binned.shape[0], jnp.bool_))
            tree, row_leaf, d = grow_tree_dp(
                mesh, tkey, binned, gh2, cut_values, n_cuts, cfg, rv,
                split_finder=split_finder)
        else:
            tree, row_leaf, d = grow_tree(
                tkey, binned, gh2, cut_values, n_cuts, cfg, row_valid,
                hist_reduce=hist_reduce,
                split_finder=split_finder, binned_t=binned_t)
        if row_valid is not None:
            d = d * row_valid.astype(d.dtype)
        return tree, d

    def body(carry, i):
        margin, emargins = carry
        key = jax.random.fold_in(base_key, i)
        gh = grad_fn(margin, label, weight, i)           # (N, K, 2)
        if T_pr > 1:
            # ensemble axis vmapped: the batched shared-onehot histogram
            # kernel + broadcast-compare lookups make this the fast path
            # (same per-tree keys as the sequential loop — bit-matched)
            tkeys = jnp.stack([jax.random.fold_in(key, j)
                               for j in range(T_pr)])
            gh_t = jnp.take(gh, jnp.asarray(
                [j // npar for j in range(T_pr)], jnp.int32),
                axis=1).transpose(1, 0, 2)               # (T, N, 2)
            stacked, ds = jax.vmap(grow_one)(tkeys, gh_t)
            delta = jnp.zeros_like(margin)
            for j in range(T_pr):
                delta = delta.at[:, j // npar].add(ds[j])
            margin = margin + delta
        else:
            tree, d = grow_one(jax.random.fold_in(key, 0), gh[:, 0, :])
            stacked = jax.tree.map(lambda x: x[None], tree)
            margin = margin + d[:, None]
        eouts, new_em = [], []
        ei = 0
        for is_train in eval_is_train:
            if is_train:
                eouts.append(etransform(margin))
                continue
            em = (predict_margin_binned(
                stacked, group_pr, eval_binned[ei],
                jnp.zeros((), jnp.float32), cfg.max_depth, K,
                root=None, n_roots=cfg.n_roots,
                tree_chunk=pred_chunk) + emargins[ei])
            new_em.append(em)
            eouts.append(etransform(em))
            ei += 1
        return (margin, tuple(new_em)), (stacked, tuple(eouts))

    iters = first_iteration + jnp.arange(n_rounds)
    (margin, eval_margins), (stacks, eouts) = jax.lax.scan(
        body, (margin, eval_margins), iters)
    return margin, eval_margins, stacks, eouts


def _scan_rounds_mesh_impl(binned, margin, label, weight, base_key,
                           first_iteration, cut_values, n_cuts, row_valid,
                           binned_t, eval_binned, eval_margins, *,
                           n_rounds: int, K: int,
                           npar: int, cfg: GrowConfig, split_finder,
                           grad_fn, mesh, eval_is_train, etransform,
                           pred_chunk: int):
    """The K-round scan under ONE ``shard_map`` over the 'data' axis.

    Where :func:`_scan_rounds_impl` with ``mesh`` nests a per-tree
    ``grow_tree_dp`` shard_map INSIDE the scan (a shard_map entry/exit
    per tree-growth step, and GSPMD left to infer the sharding of the
    margin/eval carries between them), this wraps the WHOLE scan body
    in a single shard_map: rows stay shard-resident for the entire
    segment, the per-level histogram/node-stat psums
    (``dp._psum_data`` via grow_tree's ``hist_reduce`` seam) are the
    ONLY collectives in the program, watchlist eval margins accumulate
    per shard, and the host is contacted exactly once per segment.
    Tree stacks replicate for free — after each level's psum every
    shard computes the identical argmax split (the reference's
    TreeSyncher no-op, updater_sync-inl.hpp:34-49).

    Gradients must be rowwise (reg/softmax ``fused_grad``): the
    LambdaRank pad path needs global group structure, so its mesh runs
    keep the nested-``grow_tree_dp`` scan (update_many routes by
    ``entry.rank_pad_prep``).  Same per-round fold_in keys as every
    other boost path — with an exactly-associative histogram mode
    (``hist_precision=fixed``) the model bytes are invariant to the
    mesh device count (tests/test_mesh_fused.py).
    """
    from jax.sharding import PartitionSpec as P
    from xgboost_tpu.parallel.dp import _psum_data
    from xgboost_tpu.parallel.mesh import DATA_AXIS, shard_map

    D = P(DATA_AXIS)
    R = P()

    def body(binned, margin, label, weight, base_key, first_iteration,
             cut_values, n_cuts, row_valid, eval_binned, eval_margins):
        return _scan_rounds_impl(
            binned, margin, label, weight, base_key, first_iteration,
            cut_values, n_cuts, row_valid, None, eval_binned,
            eval_margins, n_rounds=n_rounds, K=K, npar=npar, cfg=cfg,
            split_finder=split_finder, grad_fn=grad_fn, mesh=None,
            eval_is_train=eval_is_train, etransform=etransform,
            pred_chunk=pred_chunk, hist_reduce=_psum_data)

    # check_vma=False + out_specs P() for the tree stacks: replicated
    # by the psum'd split argmax (the grow_tree_dp convention).  The
    # per-round transformed eval outputs stack rounds on axis 0 with
    # rows still sharded on axis 1.
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(D, D, D, D, R, R, R, R, D, D, D),
        out_specs=(D, D, R, P(None, DATA_AXIS)),
        check_vma=False)
    return fn(binned, margin, label, weight, base_key, first_iteration,
              cut_values, n_cuts, row_valid, eval_binned, eval_margins)


# Jit wrappings of the round-scan implementations: the donating
# variants hand the margin (arg 1) and eval-margin (arg 11) carries'
# buffers to XLA so segment k+1 updates segment k's output in place —
# no per-segment device copy of the O(N*K) state.  CPU ignores donation
# (with a UserWarning per call), so callers pick the wrapper by backend
# (do_boost_fused; XGBTPU_FUSED_DONATE overrides for A/Bs).  The
# ``_mesh`` pair compiles the whole-scan shard_map (mesh-fused
# training); ``_scan_rounds`` keeps ``mesh`` for the legacy
# nested-grow_tree_dp scan (rank objectives).
_SCAN_STATIC = ("n_rounds", "K", "npar", "cfg", "split_finder",
                "grad_fn", "mesh", "eval_is_train", "etransform",
                "pred_chunk")
_scan_rounds = functools.partial(
    jax.jit,
    static_argnames=_SCAN_STATIC + ("hist_reduce",))(_scan_rounds_impl)
_scan_rounds_donated = functools.partial(
    jax.jit, static_argnames=_SCAN_STATIC + ("hist_reduce",),
    donate_argnums=(1, 11))(_scan_rounds_impl)
_scan_rounds_mesh = functools.partial(
    jax.jit, static_argnames=_SCAN_STATIC)(_scan_rounds_mesh_impl)
_scan_rounds_mesh_donated = functools.partial(
    jax.jit, static_argnames=_SCAN_STATIC,
    donate_argnums=(1, 11))(_scan_rounds_mesh_impl)


def _scan_rounds_lanes_impl(binned, margin, label, weight, base_key,
                            first_iteration, cut_values, n_cuts,
                            row_valid, *, n_rounds: int, K: int,
                            npar: int, cfg: GrowConfig, split_finder,
                            grad_fn, pred_chunk: int):
    """Lane-stacked round scan: ``jax.vmap`` of :func:`_scan_rounds_impl`
    over a leading LANE axis — L same-shape tenant boosters advance
    ``n_rounds`` rounds in ONE device dispatch (PIPELINE.md
    "Gang-batched lanes").  Every operand carries the lane axis:
    (L, N, F) bins, (L, N, K) margins/labels, (L,) first iterations,
    (L, 2) RNG keys, (L, F, W) cut values, (L, N) row-validity masks.
    Inactive pad rows/lanes are all-False ``row_valid`` — grow_tree
    zeroes their gradients and parks them at ``pos = -1`` (the
    histogram's existing inactive-row convention), so a pad lane grows
    degenerate zero trees the host discards and a padded row never
    touches a real lane's sums.  Watchlist eval stays HOST-side
    (per-tenant gating needs per-tenant metrics anyway), so the eval
    carry is empty.  ``first_iteration`` is dynamic and per-lane:
    tenants at different incumbent rounds share one compiled dispatch.

    Returns ``(final margins (L, N, K),
    stacked trees (L, n_rounds, K*npar, ...))``.
    """
    def one(binned, margin, label, weight, base_key, first_iteration,
            cut_values, n_cuts, row_valid):
        m, _, stacks, _ = _scan_rounds_impl(
            binned, margin, label, weight, base_key, first_iteration,
            cut_values, n_cuts, row_valid, None, (), (),
            n_rounds=n_rounds, K=K, npar=npar, cfg=cfg,
            split_finder=split_finder, grad_fn=grad_fn, mesh=None,
            eval_is_train=(), etransform=None, pred_chunk=pred_chunk)
        return m, stacks

    return jax.vmap(one)(binned, margin, label, weight, base_key,
                         first_iteration, cut_values, n_cuts, row_valid)


_LANE_STATIC = ("n_rounds", "K", "npar", "cfg", "split_finder",
                "grad_fn", "pred_chunk")
_scan_rounds_lanes = functools.partial(
    jax.jit, static_argnames=_LANE_STATIC)(_scan_rounds_lanes_impl)
_scan_rounds_lanes_donated = functools.partial(
    jax.jit, static_argnames=_LANE_STATIC,
    donate_argnums=(1,))(_scan_rounds_lanes_impl)


class GBTree:
    """Tree ensemble state + boosting step (reference IGradBooster: DoBoost /
    Predict / PredictLeaf / DumpModel, src/gbm/gbm.h:19-125)."""

    def __init__(self, param: TrainParam, cuts: CutMatrix):
        self.param = param
        self.cuts = cuts
        self.cfg = make_grow_config(param, cuts.max_bin)
        # TRUE exact-greedy mode (models/colmaker.py): bin-free raw-value
        # pipeline.  Covers single-controller AND dsplit=col (the
        # DistColMaker analog runs the same finder per feature shard —
        # colsplit.grow_tree_exact_colsplit); only dsplit=row keeps the
        # quantized form (the reference switches away from exact there,
        # learner-inl.hpp:91-93)
        from xgboost_tpu.models.updaters import parse_updaters
        self.exact_raw = ("grow_colmaker" in parse_updaters(param.updater)
                          and param.dsplit != "row")
        self._split_finder_cache = None  # stable identity (jit static arg)
        self._trees_list: List[TreeArrays] = []  # materialized per-tree pytrees
        # stacked trees not yet sliced into _trees_list (fused rounds /
        # model load keep the ensemble stacked; slicing T trees eagerly
        # costs a T-output jit per distinct T and duplicates the stack).
        # Held as a LIST of flat (t_i, ...) stacks so absorbing a scan
        # segment is a pure host append — concatenation is deferred to
        # the first _stack()/trees read (the gang-batched lane driver
        # absorbs N tenants per dispatch; N*leaves tiny device concats
        # per segment would swamp the stacked scan it just saved)
        self._pending: Optional[Tuple[List[TreeArrays], int]] = None
        self.tree_group: List[int] = []
        self._stack_cache: Optional[Tuple[int, TreeArrays, jax.Array]] = None
        self.cut_values_dev = jnp.asarray(cuts.cut_values)
        self.n_cuts_dev = jnp.asarray(cuts.n_cuts)
        # PRNGKey(seed), built once: a stable OBJECT, not just a stable
        # value — the lane-stacking driver's steady-bucket carry keys on
        # identity, and a per-cycle PRNGKey would be one device dispatch
        # per lane per cycle for a constant
        self._base_key_cache: Optional[jax.Array] = None
        self._col_pad_cache = None  # (n_shard, cut_values, n_cuts)
        # (kept_ids, cut_values, n_cuts, kept_dev) of the EMA-FS
        # feature screen (do_boost_fused feature_screen=); rebuilding
        # the screened cut arrays every segment would be wasted traffic
        self._screen_cut_cache = None
        # chunked tree-parallel traversal width (models/tree.py); 0/1 =
        # the sequential scan baseline; -1 auto = 32 on TPU, scan on
        # CPU (the batched compare-select kernel loses to the scan's
        # cache locality there — tools/predict_microbench.py,
        # PROFILE.md round 6).  The env override is the A/B seam.
        env_chunk = os.environ.get("XGBTPU_PREDICT_TREE_CHUNK")
        if env_chunk not in (None, ""):
            self.pred_chunk = max(0, int(env_chunk))
        else:
            pc = int(param.predict_tree_chunk)
            if pc < 0:
                pc = 32 if jax.default_backend() == "tpu" else 0
            self.pred_chunk = pc

    @property
    def trees(self) -> List[TreeArrays]:
        """Per-tree pytree list; materializes any stacked pending trees
        on first access (prediction/save after fused training go through
        the stack cache and never pay this)."""
        if self._pending is not None:
            flats, t = self._pending
            self._pending = None
            flat = flats[0] if len(flats) == 1 else jax.tree.map(
                lambda *xs: jnp.concatenate(xs), *flats)
            self._trees_list.extend(_unstack_trees(flat, t))
        return self._trees_list

    def base_key(self) -> jax.Array:
        """The booster's root ``PRNGKey(seed)`` (cached; see __init__)."""
        if self._base_key_cache is None:
            self._base_key_cache = jax.random.PRNGKey(self.param.seed)
        return self._base_key_cache

    def col_arrays(self, n_shard: int):
        """Cut arrays feature-padded to the column mesh (cached: padding
        the same arrays every boosting round is wasted HBM traffic)."""
        if self._col_pad_cache is None or self._col_pad_cache[0] != n_shard:
            from xgboost_tpu.parallel.colsplit import pad_features
            self._col_pad_cache = (
                n_shard,
                pad_features(self.cut_values_dev, n_shard, axis=0,
                             fill=jnp.inf),
                pad_features(self.n_cuts_dev, n_shard, axis=0))
        return self._col_pad_cache[1], self._col_pad_cache[2]

    def _split_finder(self):
        """The pluggable split finder: skmaker's 3-way sketch selection
        when updater=grow_skmaker, else None (= histogram argmax).
        Cached so the jitted growers see a stable static identity."""
        if self._split_finder_cache is None:
            from xgboost_tpu.models.updaters import parse_updaters
            if "grow_skmaker" in parse_updaters(self.param.updater):
                from xgboost_tpu.models.skmaker import skmaker_split_finder
                K = max(4, int(self.param.sketch_ratio
                               / max(self.param.sketch_eps, 1e-6)))
                self._split_finder_cache = skmaker_split_finder(
                    min(K, self.cfg.n_bin))
            else:
                self._split_finder_cache = False
        return self._split_finder_cache or None

    def rebind_cuts(self, cuts: CutMatrix) -> None:
        """Swap the quantile cut matrix under the live ensemble — the
        online cut-refresh seam (xgboost_tpu.stream): every node's
        ``cut_index`` is re-derived from its RAW ``threshold`` in the
        new per-feature cut row, so future BINNED training routes rows
        through the exact same "v < threshold" boundaries while fresh
        splits draw from drift-tracking cuts.  The swap is EXACT when
        every live threshold appears in its feature's new row — callers
        build the new cuts as (sketch proposal ∪ live thresholds,
        ``stream.drift.propose_refreshed_cuts``); a missing threshold
        raises ValueError with the model untouched."""
        cv = np.asarray(cuts.cut_values)
        nc = np.asarray(cuts.n_cuts)
        if self.num_trees:
            stack, group = self._stack(0)
            feat = np.asarray(stack.feature)          # (T, n_nodes)
            thr = np.asarray(stack.threshold)
            ci = np.array(stack.cut_index)
            m = feat >= 0
            if m.any():
                f = feat[m]
                th = thr[m]
                if int(f.max()) >= cv.shape[0]:
                    raise ValueError(
                        f"rebind_cuts: model splits feature {int(f.max())}"
                        f" but the new cuts cover only {cv.shape[0]}")
                rows = cv[f]                          # (M, max_cuts)
                idx = (rows < th[:, None]).sum(axis=1)
                at = rows[np.arange(len(f)),
                          np.minimum(idx, rows.shape[1] - 1)]
                ok = (idx < nc[f]) & (at == th)
                if not ok.all():
                    bad = int(f[~ok][0])
                    raise ValueError(
                        f"rebind_cuts: live split threshold "
                        f"{float(th[~ok][0])!r} of feature {bad} is "
                        "absent from the new cuts — refreshed cuts must "
                        "include every live threshold")
                ci[m] = idx
            stack = stack._replace(
                cut_index=jnp.asarray(ci, jnp.int32))
            T = int(stack.feature.shape[0])
            self._trees_list = []
            self._pending = ([stack], T)
            self._stack_cache = (T, stack, group)
        self.cuts = cuts
        self.cfg = make_grow_config(self.param, cuts.max_bin)
        self.cut_values_dev = jnp.asarray(cuts.cut_values)
        self.n_cuts_dev = jnp.asarray(cuts.n_cuts)
        self._col_pad_cache = None
        self._screen_cut_cache = None

    def _comm_bytes(self, n_feat: int, mesh=None) -> float:
        """Logical HISTOGRAM-allreduce payload estimate per tree-growth
        launch (the report_stats bytes analog, obs/comm.py): each level
        reduces per-node (F, n_bin, 2) f32 histogram partials and the
        node count doubles per level.  0 when no row mesh is active —
        single-chip runs reduce nothing, and column split never
        allreduces histograms (its SplitDecision gathers are accounted
        by colsplit.py itself as "allgather").  An estimate of what the
        reference would have shipped over rabit — ICI wire bytes are
        not observable host-side."""
        if mesh is None:
            return 0.0
        return float(((1 << self.cfg.max_depth) - 1)
                     * n_feat * self.cfg.n_bin * 2 * 4)

    @property
    def num_trees(self) -> int:
        return len(self._trees_list) + (
            self._pending[1] if self._pending is not None else 0)

    @property
    def num_boosted_rounds(self) -> int:
        k = max(1, self.param.num_output_group) * max(
            1, self.param.num_parallel_tree)
        return self.num_trees // k

    # ---------------------------------------------------------------- boost
    def do_boost(self, binned: jax.Array, gh: jax.Array, key: jax.Array,
                 row_valid: Optional[jax.Array] = None,
                 mesh=None, col_mesh=None,
                 root: Optional[jax.Array] = None,
                 exact_has_missing: bool = True,
                 exact_ranks=None,
                 binned_t: Optional[jax.Array] = None
                 ) -> Tuple[List[TreeArrays], jax.Array]:
        """One boosting round: grows num_output_group × num_parallel_tree
        trees (reference BoostNewTrees, gbtree-inl.hpp:238-273), then runs
        the prune updater if configured (reference updater pipeline
        "grow_histmaker,prune", gbtree-inl.hpp:218-236).

        gh: (N, K, 2).  Returns (new_trees, leaf_contrib (N, K) margin delta)
        computed from grow-time leaf positions — the prediction-buffer fast
        path (gbtree-inl.hpp:258-303).  With `mesh`, rows are sharded over
        the 'data' axis and histograms psum-reduced (SURVEY.md §5.8); with
        `col_mesh`, features are sharded over 'feat' (DistColMaker).
        """
        from xgboost_tpu.models.updaters import parse_updaters, prune_tree

        do_prune = ("prune" in parse_updaters(self.param.updater)
                    and self.param.gamma > 0.0)
        K = max(1, self.param.num_output_group)
        npar = max(1, self.param.num_parallel_tree)
        new_trees: List[TreeArrays] = []
        deltas = []
        from xgboost_tpu.parallel import mock
        import os
        # ensemble parallelism (SURVEY.md §2.4.5): all class-group x
        # parallel trees of the round grow in ONE vmapped launch.  The
        # vmapped grower beats pipelined sequential launches on TPU
        # (70 vs 85 ms on 6-class 200k) now that (a) jax.vmap of the
        # level histogram dispatches to the tree-batched shared-onehot
        # kernel via custom_vmap (ops/histogram.py) and (b) the per-row
        # small-table lookups batch as broadcast-compare selects instead
        # of ~12 ms kCustom gathers (tree.table_lookup; PROFILE.md).
        # XGBTPU_SEQ_BOOST=1 restores sequential launches.
        if root is not None and (col_mesh is not None
                                 or self.cfg.n_roots <= 1):
            raise NotImplementedError(
                "root_index needs num_roots > 1 (and dsplit != col): set "
                "num_roots to the number of tree roots")
        if self.exact_raw:
            return self._do_boost_exact(binned, gh, key, row_valid,
                                        do_prune, K, npar,
                                        exact_has_missing, exact_ranks,
                                        col_mesh=col_mesh)
        if (col_mesh is None and K * npar > 1
                and not os.environ.get("XGBTPU_SEQ_BOOST")):
            return self._do_boost_vmapped(binned, gh, key, row_valid, mesh,
                                          K, npar, do_prune, root)
        from xgboost_tpu.obs import comm
        comm_nbytes = self._comm_bytes(binned.shape[1], mesh)
        for k in range(K):
            delta_k = None
            for t in range(npar):
                # one "seqno" per tree-growth launch (the collective unit:
                # psum histograms / split reduce happen inside); the seam
                # also counts it into the per-round collective stats, and
                # the timed() wrapper below adds the launch wall seconds
                mock.collective(nbytes=comm_nbytes)
                tkey = jax.random.fold_in(key, k * npar + t)
                _t_launch = time.perf_counter()
                if col_mesh is not None:
                    if self._split_finder() is not None:
                        raise NotImplementedError(
                            "updater=grow_skmaker is not supported under "
                            "dsplit=col (the column-split grower reduces "
                            "SplitEntry tuples, not summaries)")
                    from xgboost_tpu.parallel.colsplit import (
                        grow_tree_colsplit, pad_features)
                    n_shard = col_mesh.devices.size
                    cv, nc = self.col_arrays(n_shard)
                    if binned.shape[1] % n_shard:  # caller didn't pre-pad
                        binned = pad_features(binned, n_shard, axis=1)
                    tree, row_leaf, d = grow_tree_colsplit(
                        col_mesh, tkey, binned, gh[:, k, :], cv, nc,
                        self.cfg, row_valid,
                        f_real=self.cuts.num_feature)
                elif mesh is not None:
                    from xgboost_tpu.parallel.dp import grow_tree_dp
                    rv = row_valid if row_valid is not None else \
                        jnp.ones(binned.shape[0], jnp.bool_)
                    tree, row_leaf, d = grow_tree_dp(
                        mesh, tkey, binned, gh[:, k, :], self.cut_values_dev,
                        self.n_cuts_dev, self.cfg, rv,
                        split_finder=self._split_finder(), root=root)
                else:
                    tree, row_leaf, d = grow_tree(
                        tkey, binned, gh[:, k, :], self.cut_values_dev,
                        self.n_cuts_dev, self.cfg, row_valid,
                        split_finder=self._split_finder(), root=root,
                        binned_t=binned_t)
                # host-side launch wall time of the collective unit the
                # seam counted above (count=0: no double count).  Under
                # column split the launch is already timed inside
                # grow_tree_colsplit as "allgather" — adding it here too
                # would double the total comm seconds.
                if col_mesh is None:
                    comm.record("allreduce", count=0,
                                seconds=time.perf_counter() - _t_launch)
                if do_prune:
                    tree, resolve = prune_tree(tree, self.param.gamma,
                                               self.cfg.n_roots)
                    d = table_lookup(tree.leaf_value[jnp.asarray(resolve)],
                                     row_leaf)
                if row_valid is not None:
                    # padding rows land on node 0, which carries the root's
                    # would-be leaf weight; zero their delta so their cached
                    # margin stays at the entry's (zero-padded) base value
                    d = d * row_valid.astype(d.dtype)
                new_trees.append(tree)
                self.trees.append(tree)
                self.tree_group.append(k)
                delta_k = d if delta_k is None else delta_k + d
            deltas.append(delta_k)
        self._stack_cache = None
        return new_trees, jnp.stack(deltas, axis=1)

    def _do_boost_exact(self, X, gh, key, row_valid, do_prune: bool,
                        K: int, npar: int, has_missing: bool = True,
                        exact_ranks=None, col_mesh=None):
        """Exact-greedy round: sequential per-tree growth (the exact
        scans don't share a one-hot, so there is nothing to batch).
        With ``col_mesh``, each shard scans its own raw columns and
        winners reduce over the mesh — TRUE exact column split at any
        cardinality (colsplit.grow_tree_exact_colsplit)."""
        from xgboost_tpu.models.colmaker import grow_tree_exact
        from xgboost_tpu.models.updaters import prune_tree
        from xgboost_tpu.parallel import mock
        if self.cfg.n_roots > 1:
            raise NotImplementedError(
                "num_roots > 1 is not supported by the exact grower")
        new_trees: List[TreeArrays] = []
        deltas = []
        from xgboost_tpu.obs import comm
        for k in range(K):
            delta_k = None
            for t in range(npar):
                # exact mode reduces SplitEntry tuples + routing
                # bitmaps, not histograms: count the launch, skip the
                # payload estimate
                mock.collective()
                tkey = jax.random.fold_in(key, k * npar + t)
                _t_launch = time.perf_counter()
                rk, uq = exact_ranks if exact_ranks is not None \
                    else (None, None)
                if col_mesh is not None:
                    from xgboost_tpu.parallel.colsplit import \
                        grow_tree_exact_colsplit
                    tree, row_leaf, _ = grow_tree_exact_colsplit(
                        col_mesh, tkey, X, gh[:, k, :], self.cfg,
                        row_valid, has_missing=has_missing,
                        rank_t=rk, uniq=uq,
                        f_real=self.cuts.num_feature)
                else:
                    tree, row_leaf = grow_tree_exact(
                        tkey, X, gh[:, k, :], self.cfg, row_valid,
                        has_missing=has_missing, rank_t=rk, uniq=uq)
                comm.record("allreduce", count=0,
                            seconds=time.perf_counter() - _t_launch)
                if do_prune:
                    tree, resolve = prune_tree(tree, self.param.gamma)
                    d = table_lookup(tree.leaf_value[jnp.asarray(resolve)],
                                     row_leaf)
                else:
                    d = table_lookup(tree.leaf_value, row_leaf)
                if row_valid is not None:
                    d = d * row_valid.astype(d.dtype)
                new_trees.append(tree)
                self.trees.append(tree)
                self.tree_group.append(k)
                delta_k = d if delta_k is None else delta_k + d
            deltas.append(delta_k)
        self._stack_cache = None
        return new_trees, jnp.stack(deltas, axis=1)

    def _do_boost_vmapped(self, binned, gh, key, row_valid, mesh,
                          K: int, npar: int, do_prune: bool, root=None):
        """Grow the round's K*npar trees in a single vmapped launch
        (reference: one tree per class group per round,
        gbtree-inl.hpp:104-117, num_parallel_tree :247-253 — here the
        ensemble axis is a batch axis over the same histograms kernel).

        Bit-matches the sequential path: per-tree keys, subsampling and
        histograms are identical; only the launch is batched.
        """
        from xgboost_tpu.models.updaters import prune_tree
        from xgboost_tpu.parallel import mock
        # keep the seqno space identical to the sequential path (one per
        # tree) so mock fault coordinates fire regardless of backend; a
        # hit kills the round before the batched launch, which recovery
        # treats the same as a mid-round death (partial state discarded).
        # The comm stats inherit the same count space (one logical
        # allreduce per tree, even though the launch is batched).
        from xgboost_tpu.obs import comm
        comm_nbytes = self._comm_bytes(binned.shape[1], mesh)
        for _ in range(K * npar):
            mock.collective(nbytes=comm_nbytes)
        _t_launch = time.perf_counter()

        T = K * npar
        keys = jnp.stack([jax.random.fold_in(key, i) for i in range(T)])
        kk = jnp.asarray([i // npar for i in range(T)], jnp.int32)
        gh_t = jnp.take(gh, kk, axis=1).transpose(1, 0, 2)   # (T, N, 2)

        if mesh is not None:
            from xgboost_tpu.parallel.dp import grow_tree_dp
            rv = row_valid if row_valid is not None else \
                jnp.ones(binned.shape[0], jnp.bool_)

            def one(tkey, gh2):
                return grow_tree_dp(mesh, tkey, binned, gh2,
                                    self.cut_values_dev, self.n_cuts_dev,
                                    self.cfg, rv,
                                    split_finder=self._split_finder(),
                                    root=root)
            stacked, row_leafs, ds = jax.vmap(one)(keys, gh_t)
        else:
            def one(tkey, gh2):
                return grow_tree(tkey, binned, gh2, self.cut_values_dev,
                                 self.n_cuts_dev, self.cfg, row_valid,
                                 split_finder=self._split_finder(),
                                 root=root)
            stacked, row_leafs, ds = jax.vmap(one)(keys, gh_t)
        comm.record("allreduce", count=0,
                    seconds=time.perf_counter() - _t_launch)

        new_trees = list(_unstack_trees(stacked, T))
        if do_prune:
            # pruning is host-side per tree; the delta re-gather stays
            # eager (prune runs only when gamma > 0)
            deltas = jnp.zeros((binned.shape[0], K), jnp.float32)
            for i in range(T):
                tree, resolve = prune_tree(new_trees[i], self.param.gamma,
                                           self.cfg.n_roots)
                d = table_lookup(tree.leaf_value[jnp.asarray(resolve)],
                                 row_leafs[i])
                if row_valid is not None:
                    d = d * row_valid.astype(d.dtype)
                new_trees[i] = tree
                deltas = deltas.at[:, i // npar].add(d)
        else:
            deltas = jnp.zeros((binned.shape[0], K), jnp.float32)
            for i in range(T):
                d = ds[i]
                if row_valid is not None:
                    d = d * row_valid.astype(d.dtype)
                deltas = deltas.at[:, i // npar].add(d)
        for i, tree in enumerate(new_trees):
            self.trees.append(tree)
            self.tree_group.append(i // npar)
        self._stack_cache = None
        return new_trees, deltas

    # ------------------------------------------------------------ fused boost
    def do_boost_fused(self, binned, margin, info, grad_fn,
                       first_iteration: int, n_rounds: int,
                       row_valid=None, mesh=None, binned_t=None,
                       eval_binned=(), eval_margins=(),
                       eval_is_train=(), etransform=None, donate=None,
                       rowwise_grad: bool = True, feature_screen=None):
        """Scan ``n_rounds`` whole boosting rounds in ONE device launch.

        Per-round host dispatch (gradient launch + growth launch + margin
        update) costs ~2-3 ms each through a tunnel-attached TPU
        (PROFILE.md); folding the round loop into ``lax.scan`` removes it
        entirely and lets XLA pipeline rounds back-to-back.  The round
        body replays the sequential path exactly — same per-round
        ``fold_in`` keys, same kernels — so the resulting model
        bit-matches ``do_boost`` called ``n_rounds`` times (tested).

        The reference has no analog (its round loop is inherently
        host-side, ``xgboost_main.cpp:183-217``); this is the TPU-native
        shape of "the round loop is itself a compiled program".

        Restrictions (callers fall back to per-round ``do_boost``):
        no pruning (``gamma > 0`` pruning is a host-side pass), no
        refresh, no column split, and a jittable gradient function
        (standard reg/softmax objectives).  Fault injection IS
        compatible: the per-round injector coordinates replay host-side
        BEFORE the segment dispatches (same round/seqno space as the
        per-round path), so a simulated death or stall fires at a
        segment boundary and resume from the checkpoint ring replays
        the whole segment bit-identically.

        Args:
          margin: (N, K) current margins (device).
          info: MetaInfo supplying device-cached label/weight.
          grad_fn: pure ``(margin, label, weight, iteration) -> (N, K, 2)``
            gradient with stable identity (Objective.fused_grad).
          row_valid: optional (N,) bool mask of real rows.
          mesh: optional data-parallel mesh (rows sharded over 'data').
          rowwise_grad: ``grad_fn`` is a pure per-row map (standard
            reg/softmax fused gradients) — with ``mesh`` this selects
            the whole-scan shard_map driver
            (:func:`_scan_rounds_mesh_impl`); group-structured
            gradients (LambdaRank pad path) keep the legacy
            nested-``grow_tree_dp`` scan.
          eval_binned / eval_margins / eval_is_train / etransform:
            device-resident watchlist evaluation (see
            :func:`_scan_rounds_impl`) — per-round transformed eval
            outputs come back stacked, one launch for the whole segment.
          donate: donate the margin/eval-margin carries to XLA (None =
            auto: on for non-CPU backends, where donation is honored;
            env XGBTPU_FUSED_DONATE=0/1 overrides).
          feature_screen: optional ascending FULL-space feature ids the
            caller screened ``binned``/``eval_binned`` down to (EMA-FS,
            xgboost_tpu.stream): the scan grows trees over the screened
            (C, N, F_kept) working set using matching screened cut
            arrays, and grown trees' feature ids are remapped back to
            the full space before they join the ensemble — model bytes
            and prediction never see the screen.

        Returns ``(final margin (N, K), final eval margins tuple,
        stacked per-round transformed eval outputs tuple)``; grown
        trees are appended.
        """
        K = max(1, self.param.num_output_group)
        npar = max(1, self.param.num_parallel_tree)
        label = info.label_dev()
        weight = info.weight_dev(margin.shape[0])
        if donate is None:
            env = os.environ.get("XGBTPU_FUSED_DONATE")
            if env not in (None, ""):
                donate = env == "1"
            else:
                donate = jax.default_backend() != "cpu"
        mesh_scan = mesh is not None and rowwise_grad
        # the fused scan still performs the per-round collectives; keep
        # the comm/seqno count space identical to the per-round path by
        # replaying one injector-seam entry per tree-growth step BEFORE
        # the dispatch (an armed die/stall fires here, at the segment
        # boundary — the checkpoint ring then replays the segment).
        # The mesh-fused driver counts its REAL collectives: one
        # histogram psum per level per tree into the xgbtpu_comm_psum_*
        # families (max_depth per growth step; the terminal level's
        # node stats derive from the parent's split — no reduction).
        # Single-device/legacy launches keep the per-round path's
        # logical "allreduce" accounting; NOTHING charges the dispatch
        # wall time to a collective family — that wall time is device
        # compute and belongs to xgbtpu_train_dispatch_seconds alone.
        from xgboost_tpu.obs import span, training_metrics
        from xgboost_tpu.parallel import mock
        cut_vals, cut_ns = self.cut_values_dev, self.n_cuts_dev
        kept_dev = None
        if feature_screen is not None:
            kept = tuple(int(i) for i in feature_screen)
            cache = self._screen_cut_cache
            if cache is None or cache[0] != kept:
                kidx = jnp.asarray(kept, jnp.int32)
                cache = (kept, jnp.take(self.cut_values_dev, kidx, axis=0),
                         jnp.take(self.n_cuts_dev, kidx), kidx)
                self._screen_cut_cache = cache
            _, cut_vals, cut_ns, kept_dev = cache
        comm_nbytes = self._comm_bytes(binned.shape[1], mesh)
        for r in range(n_rounds):
            mock.begin_round(first_iteration + r)
            for _ in range(K * npar):
                if mesh_scan:
                    mock.collective("psum", nbytes=comm_nbytes,
                                    count=self.cfg.max_depth)
                else:
                    mock.collective(nbytes=comm_nbytes)
        if mesh_scan:
            scan = _scan_rounds_mesh_donated if donate \
                else _scan_rounds_mesh
        else:
            scan = _scan_rounds_donated if donate else _scan_rounds
        with span("train.dispatch", first_round=first_iteration,
                  n_rounds=n_rounds, donated=bool(donate),
                  mesh_fused=bool(mesh_scan)):
            _t_launch = time.perf_counter()
            margin_f, emargins_f, stacks, eouts = scan(
                binned, margin, label, weight,
                self.base_key(),
                jnp.int32(first_iteration), cut_vals,
                cut_ns, row_valid, binned_t,
                tuple(eval_binned), tuple(eval_margins),
                n_rounds=n_rounds, K=K, npar=npar, cfg=self.cfg,
                split_finder=self._split_finder(), grad_fn=grad_fn,
                mesh=mesh, eval_is_train=tuple(eval_is_train),
                etransform=etransform, pred_chunk=self.pred_chunk)
            # block at the segment boundary: the driver pulls eval lines
            # / checkpoint bytes from this dispatch next, and the
            # histogram must record device wall time, not async dispatch
            jax.block_until_ready(margin_f)
            _dt = time.perf_counter() - _t_launch
        tm = training_metrics()
        tm.dispatch_seconds.observe(_dt)
        tm.rounds_per_dispatch.set(float(n_rounds))
        # flatten (n_rounds, K*npar, ...) -> (T_new, ...) and install the
        # full-ensemble stack cache directly: prediction then reuses the
        # scan's own output instead of re-stacking T per-tree slices
        flat = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]),
                            stacks)
        if kept_dev is not None:
            # grown trees speak the SCREENED feature space; remap split
            # ids back to the full space before anything concatenates,
            # persists or predicts (thresholds/cut indices already match
            # the full space: screened rows are whole full-space rows)
            f = flat.feature
            flat = flat._replace(feature=jnp.where(
                f >= 0,
                jnp.take(kept_dev,
                         jnp.clip(f, 0, kept_dev.shape[0] - 1)),
                f))
        self._append_flat_trees(flat, n_rounds)
        return margin_f, emargins_f, eouts

    def _append_flat_trees(self, flat, n_rounds: int) -> None:
        """Append a flattened ``(n_rounds*K*npar, ...)`` tree stack grown
        by a fused or lane-stacked scan: a pure host-side list append —
        zero device dispatches.  Concatenation into the full-ensemble
        stack is deferred to the next :meth:`_stack` read (one concat
        per leaf, however many segments accumulated).  The gang-batched
        lane driver absorbs N tenants per dispatch; eager per-lane
        concat + cache rebuild here used to cost ~25 tiny device ops
        per lane and swamped the stacked scan it had just saved
        (tools/bench_lanes.py)."""
        K = max(1, self.param.num_output_group)
        npar = max(1, self.param.num_parallel_tree)
        group_new = [j // npar for _ in range(n_rounds)
                     for j in range(K * npar)]
        T_new = n_rounds * K * npar
        # keep the new trees STACKED (ADVICE r2: eager unstack compiles a
        # T-output program per distinct T and duplicates the cached
        # stack); the trees property slices lazily if anything needs
        # per-tree objects
        if self._pending is not None:
            flats, old_t = self._pending
            flats.append(flat)
            self._pending = (flats, old_t + T_new)
        elif self._trees_list:
            # per-tree objects already materialized (paged/refresh
            # paths): fold them back into the pending list so _stack()
            # never re-slices
            self._pending = ([jax.tree.map(lambda *xs: jnp.stack(xs),
                                           *self._trees_list), flat],
                             len(self._trees_list) + T_new)
            self._trees_list = []
        else:
            self._pending = ([flat], T_new)
        self.tree_group.extend(group_new)
        self._stack_cache = None

    def absorb_round_stacks(self, flat, n_rounds: int) -> None:
        """Install one lane's flattened ``(n_rounds*K*npar, ...)`` tree
        stack as this booster's newest trees — the lane-stacked
        driver's per-tenant unpack (pipeline/lanes.py): the gang
        dispatch grew every lane's trees in one launch and
        ``_unstack_lane_flats`` pre-flattened the round axis device-
        side; each tenant absorbs its own slice exactly as
        :meth:`do_boost_fused` would have (a pure host append)."""
        self._append_flat_trees(flat, n_rounds)

    # ----------------------------------------------------------- paged boost
    def do_boost_paged(self, dmat, gh, key: jax.Array,
                       mesh=None) -> jax.Array:
        """One boosting round over an external-memory matrix: histograms
        accumulate batch-by-batch (SURVEY.md §5.7); gradients, margins
        and deltas are O(N) and stay DEVICE-side (host round trips cost
        seconds on tunnel-attached chips).  With ``mesh``, each batch
        additionally shards over the 'data' axis with psum'd partials
        (distributed external memory).
        gh: (N, K, 2).  Returns the (N, K) margin delta (device)."""
        from xgboost_tpu.external import _paged_leaf_delta, grow_tree_paged
        from xgboost_tpu.models.updaters import parse_updaters, prune_tree

        if self.cfg.n_roots > 1:
            raise NotImplementedError(
                "num_roots > 1 is not supported on external-memory "
                "matrices (root_index routing is in-memory only)")
        do_prune = ("prune" in parse_updaters(self.param.updater)
                    and self.param.gamma > 0.0)
        K = max(1, self.param.num_output_group)
        npar = max(1, self.param.num_parallel_tree)
        from xgboost_tpu.obs import comm
        from xgboost_tpu.parallel import mock
        gh = jnp.asarray(gh)
        comm_nbytes = self._comm_bytes(dmat.num_col, mesh)
        deltas = jnp.zeros((dmat.num_row, K), jnp.float32)
        for k in range(K):
            for t in range(npar):
                mock.collective(nbytes=comm_nbytes)
                tkey = jax.random.fold_in(key, k * npar + t)
                _t_launch = time.perf_counter()
                tree = grow_tree_paged(tkey, dmat, gh[:, k, :],
                                       self.cut_values_dev, self.n_cuts_dev,
                                       self.cfg, mesh=mesh,
                                       split_finder=self._split_finder())
                comm.record("allreduce", count=0,
                            seconds=time.perf_counter() - _t_launch)
                if do_prune:
                    tree, _ = prune_tree(tree, self.param.gamma)
                d_k = jnp.concatenate(
                    [_paged_leaf_delta(tree, batch, self.cfg.max_depth)
                     for _, batch in dmat.device_batches()])
                deltas = deltas.at[:, k].add(d_k)
                self.trees.append(tree)
                self.tree_group.append(k)
        self._stack_cache = None
        return deltas

    # --------------------------------------------------------------- refresh
    def do_refresh(self, binned: jax.Array, gh: jax.Array,
                   row_valid: Optional[jax.Array] = None, mesh=None,
                   root: Optional[jax.Array] = None) -> None:
        """Refresh all trees' stats/leaf values on (new) data — the
        reference's ``updater=refresh`` continued-training mode
        (updater_refresh-inl.hpp:19-151)."""
        from xgboost_tpu.models.updaters import refresh_tree

        if mesh is not None:
            from xgboost_tpu.parallel.dp import refresh_tree_dp
            if root is not None:
                raise NotImplementedError(
                    "refresh with root_index under dsplit=row is not "
                    "wired; refresh single-device or drop root_index")
        for i, tree in enumerate(self.trees):
            k = self.tree_group[i]
            if mesh is not None:
                self.trees[i] = refresh_tree_dp(
                    mesh, tree, binned, gh[:, k, :], self.cfg.split,
                    self.cfg.max_depth, row_valid)
            else:
                self.trees[i] = refresh_tree(
                    tree, binned, gh[:, k, :], self.cfg.split,
                    self.cfg.max_depth, row_valid,
                    root=root, n_roots=self.cfg.n_roots)
        self._stack_cache = None

    # -------------------------------------------------------------- predict
    def _stack(self, ntree_limit: int = 0):
        """Stack trees (optionally first ntree_limit) into (T, ...) arrays.

        ``ntree_limit`` is CLAMPED to [0, num_trees] rather than
        validated: a hot-reloaded smaller model can race a stale request
        parameter (serving registry swap), and the reference likewise
        treats out-of-range limits as "all trees"."""
        T = self.num_trees if ntree_limit <= 0 else min(
            int(ntree_limit), self.num_trees)
        if self._stack_cache is not None and self._stack_cache[0] == T:
            return self._stack_cache[1], self._stack_cache[2]
        assert T > 0, "model is empty"
        if self._pending is not None and T == self.num_trees:
            # full-ensemble read with pending flat segments: concat the
            # segments directly (one op per leaf) instead of slicing T
            # per-tree pytrees and re-stacking them.  Collapse the
            # pending list so repeated appends stay O(segments-since-
            # last-read), not O(all-segments-ever).
            parts = ([jax.tree.map(lambda *xs: jnp.stack(xs),
                                   *self._trees_list)]
                     if self._trees_list else [])
            parts.extend(self._pending[0])
            stack = parts[0] if len(parts) == 1 else jax.tree.map(
                lambda *xs: jnp.concatenate(xs), *parts)
            if not self._trees_list:
                self._pending = ([stack], T)
        else:
            stack = jax.tree.map(lambda *xs: jnp.stack(xs),
                                 *self.trees[:T])
        group = jnp.asarray(self.tree_group[:T], dtype=jnp.int32)
        self._stack_cache = (T, stack, group)
        return stack, group

    def predict_margin(self, binned: jax.Array, base: jax.Array,
                       ntree_limit: int = 0,
                       root: Optional[jax.Array] = None) -> jax.Array:
        stack, group = self._stack(ntree_limit)
        K = max(1, self.param.num_output_group)
        if self.exact_raw:
            from xgboost_tpu.models.colmaker import predict_margin_raw
            return predict_margin_raw(stack, group, binned, base,
                                      self.cfg.max_depth, K)
        return predict_margin_binned(
            stack, group, binned, base, self.cfg.max_depth, K,
            root=root, n_roots=self.cfg.n_roots,
            tree_chunk=self.pred_chunk)

    def predict_margin_fused(self, X: jax.Array, base: jax.Array,
                             ntree_limit: int = 0,
                             root: Optional[jax.Array] = None) -> jax.Array:
        """Margins straight from RAW f32 feature rows (NaN = missing):
        the fused quantize+traverse program (models/tree.py, round 7).
        Bit-identical to ``predict_margin(bin_dense_device(X, cuts), ...)``
        — the quantize sub-graph is the same function.  ``X`` must be
        width-matched to the model's cut matrix (callers NaN-pad)."""
        if self.exact_raw:
            raise NotImplementedError(
                "exact-mode models route on raw values already; the "
                "fused quantize+traverse applies to binned models only")
        stack, group = self._stack(ntree_limit)
        K = max(1, self.param.num_output_group)
        return predict_margin_fused(
            stack, group, X, self.cut_values_dev, base,
            self.cfg.max_depth, K, root=root, n_roots=self.cfg.n_roots,
            tree_chunk=self.pred_chunk)

    def predict_incremental(self, binned: jax.Array, margin: jax.Array,
                            new_trees: List[TreeArrays],
                            first_group: int = 0,
                            root: Optional[jax.Array] = None) -> jax.Array:
        """Add the contribution of freshly grown trees to a cached margin
        (fixed shapes per round -> single compilation).  An empty
        ``new_trees`` is a no-op (a stale caller can observe zero fresh
        trees when racing a model swap)."""
        if not new_trees:
            return margin
        K = max(1, self.param.num_output_group)
        npar = max(1, self.param.num_parallel_tree)
        stack = jax.tree.map(lambda *xs: jnp.stack(xs), *new_trees)
        group = jnp.asarray(
            [first_group + i // npar for i in range(len(new_trees))],
            dtype=jnp.int32)
        if self.exact_raw:
            from xgboost_tpu.models.colmaker import predict_margin_raw
            return predict_margin_raw(
                stack, group, binned, jnp.zeros((), jnp.float32),
                self.cfg.max_depth, K) + margin
        return predict_margin_binned(
            stack, group, binned, jnp.zeros((), jnp.float32),
            self.cfg.max_depth, K,
            root=root, n_roots=self.cfg.n_roots,
            tree_chunk=self.pred_chunk) + margin

    def predict_leaf(self, binned: jax.Array, ntree_limit: int = 0,
                     root: Optional[jax.Array] = None) -> jax.Array:
        stack, _ = self._stack(ntree_limit)
        if self.exact_raw:
            from xgboost_tpu.models.colmaker import traverse_raw

            def body(_, tree):
                return None, traverse_raw(tree, binned, self.cfg.max_depth)
            _, leaves = jax.lax.scan(body, None, stack)
            return leaves.T
        return predict_leaf_binned(stack, binned, self.cfg.max_depth,
                                   root=root, n_roots=self.cfg.n_roots,
                                   tree_chunk=self.pred_chunk)

    # ------------------------------------------------------------ serialize
    def get_state(self) -> dict:
        stack, group = self._stack(0)
        state = {f"tree_{f}": np.asarray(getattr(stack, f))
                 for f in TreeArrays._fields}
        state["tree_group_arr"] = np.asarray(group)
        state["cut_values"] = self.cuts.cut_values
        state["cut_n"] = self.cuts.n_cuts
        return state

    @classmethod
    def from_state(cls, param: TrainParam, state: dict) -> "GBTree":
        cuts = CutMatrix(state["cut_values"], state["cut_n"])
        gbt = cls(param, cuts)
        stack = TreeArrays(**{f: jnp.asarray(state[f"tree_{f}"])
                              for f in TreeArrays._fields})
        T = stack.feature.shape[0]
        # stay stacked: prediction/save go through the stack cache; only
        # dump/refresh/prune-style per-tree access slices lazily
        gbt._pending = ([stack], T)
        gbt.tree_group = [int(g) for g in state["tree_group_arr"]]
        gbt._stack_cache = (T, stack,
                            jnp.asarray(state["tree_group_arr"],
                                        dtype=jnp.int32))
        return gbt

"""TRUE exact-greedy tree growth (reference ColMaker) at ANY cardinality.

The reference's exact updater scans each feature's sorted column per
node, evaluating a split between every pair of distinct values
(``updater_colmaker-inl.hpp:362-414``).  Round 2 realized exact mode as
"cuts at every distinct value" through the histogram grower, capped at
``max_exact_bin`` — silently approximate past the cap (VERDICT r2
item 5).  Round 3 made it truly exact but materialized ~10
``(N, n_node)`` f32 intermediates per (feature, level) — 417 ms/level
of scan traffic alone at 250k x 28 (tools/exact_microbench.py), i.e.
slower per row than the reference's single CPU thread (VERDICT r3).
This is the round-4 *segment-sorted* formulation:

  - Per level, ONE batched ``lax.sort`` keyed ``(node, value)`` puts
    every feature's rows in node-major, value-ascending order directly
    from row space (gradients ride as sort payloads — no gathers, no
    static per-dataset sort structures).  Missing (NaN) and retired
    rows key to a trash segment past the last node.
  - Per-node running (G, H) prefix sums are then one GLOBAL cumsum
    minus a per-segment base — and the global cumsum runs as a blocked
    triangular matmul on the MXU (~1 ms vs ~9 ms for XLA's native
    log-depth scan at (28, 250k); tools/exact_microbench.py).
  - Split candidates live between ADJACENT slots of the same segment
    with distinct values — the node-local midpoint threshold
    (reference ``(fvalue + e.last_fvalue) * 0.5``) is adjacent-slot
    math instead of round 3's (N, n_node) cummax/cummin dance — plus
    the reference's end-of-scan present-vs-missing candidates from the
    per-segment totals.  Routing compares RAW values (``x < thr``), so
    grown trees reproduce the reference's partitions split-for-split
    at any cardinality.

Exact mode is bin-free end to end: training data, margins and
prediction all use raw values (:func:`traverse_raw`).  Cost is
O(N log^2 N) bitonic sort + O(N) scan work per (feature, level),
batched over features in single XLA ops.  Single-controller only (the
running sums are order-dependent; the reference's distributed exact
mode is the column-split DistColMaker, which this framework provides
separately).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from xgboost_tpu.models.tree import (GrowConfig, TreeArrays, apply_level,
                                     empty_tree, table_lookup)
from xgboost_tpu.ops.histogram import node_stats
from xgboost_tpu.ops.split import NEG, RT_EPS, calc_gain


def build_exact_ranks(X):
    """Static per-dataset dense-rank structures for the single-key sort
    path (host-side, once per training matrix).

    Per feature, rows are ranked by DISTINCT value: equal values share
    a rank, so rank adjacency == value distinctness and the per-level
    sort can use ONE packed int32 key ``(node << ceil(log2 N)) | rank``
    instead of the two-key (node, value) sort (3 sort operands instead
    of 4; measured ~25% faster at (28, 250k) on v5e).  Thresholds are
    recovered at winner slots only, from the distinct-value table.

    X: (N, F) float32, NaN = missing.  Returns host arrays
    (rank_t (F, N) int32, uniq (F, N) f32 distinct values per feature
    padded with +inf).
    """
    import numpy as np
    vals = np.ascontiguousarray(X.T, dtype=np.float32)     # (F, N)
    F, N = vals.shape
    order = np.argsort(vals, axis=1, kind="stable")        # NaN last
    sv = np.take_along_axis(vals, order, axis=1)
    fin = ~np.isnan(sv)
    newd = np.empty((F, N), bool)
    newd[:, 0] = fin[:, 0]
    newd[:, 1:] = (sv[:, 1:] > sv[:, :-1]) & fin[:, 1:]
    dr = np.cumsum(newd, axis=1) - 1                       # dense rank
    np.clip(dr, 0, None, out=dr)
    rank_t = np.empty((F, N), np.int32)
    np.put_along_axis(rank_t, order, dr.astype(np.int32), axis=1)
    uniq = np.full((F, N), np.inf, np.float32)
    # NaN slots write +inf at N-1, which no real rank reaches when any
    # NaN exists (n_uniq <= N - n_nan); all-finite features have no
    # NaN slots — either way no distinct value is clobbered
    np.put_along_axis(uniq, np.where(fin, dr, N - 1),
                      np.where(fin, sv, np.inf), axis=1)
    return rank_t, uniq


def _blocked_cumsum(x: jax.Array, block: int = 512) -> jax.Array:
    """Inclusive cumsum along axis 1 as per-block triangular matmuls
    (MXU) + a small cross-block cumsum.  XLA's native cumsum lowers to
    a log-depth multi-pass scan (~9 ms for (28, 250k) f32 on v5e); the
    blocked form runs in well under 1 ms (tools/exact_microbench.py).
    HIGHEST precision keeps the prefix sums f32-accurate.

    The ENTIRE per-feature computation — triangular dot, block sums,
    cross-block base, add — runs inside one ``lax.map`` body over
    features, NOT as F-batched ops: batched accumulation order varies
    with the batch size (measured 4e-5 drift between F=13 and F=2
    slices on CPU; 2.4e-4 on TPU when only the dot was mapped and the
    block-sum/cumsum stayed batched), which would make per-shard
    column-split results diverge from the single-device run.  The map
    body has a fixed (nb, block) shape regardless of F, so a feature's
    prefix sums are bitwise identical however the features are sharded
    — verified on BOTH backends; the exact column split's bit-match
    guarantee rests on it (round 5).  Cost: same MXU work, F
    sequential dispatches inside one compiled loop (measured
    kernel-neutral at (28, 250k) on v5e)."""
    F, N = x.shape
    nb = -(-N // block)
    xb = jnp.pad(x, ((0, 0), (0, nb * block - N))).reshape(F, nb, block)
    tri = jnp.triu(jnp.ones((block, block), x.dtype))

    def per_feature(xf):                          # (nb, block)
        w = jnp.dot(xf, tri, precision=jax.lax.Precision.HIGHEST)
        s = xf.sum(axis=1)
        base = jnp.cumsum(s) - s                  # exclusive, (nb,)
        return w + base[:, None]

    return jax.lax.map(per_feature, xb).reshape(F, nb * block)[:, :N]


def _default_exact_router(best, node_of_row, X, x_missing):
    """Row go-left by raw-value comparison when all features are local
    (reference model.h:555-566)."""
    F = X.shape[1]
    f_row = table_lookup(best.feature, node_of_row)
    thr_row = table_lookup(best.threshold, node_of_row)
    dl_row = table_lookup(best.default_left, node_of_row)
    sel = (jnp.arange(F, dtype=jnp.int32)[None, :]
           == jnp.maximum(f_row, 0)[:, None])
    x_row = jnp.where(sel, X, 0.0).sum(axis=1)
    miss = (sel & x_missing).any(axis=1)
    return jnp.where(miss, dl_row, x_row < thr_row)


@functools.partial(jax.jit, static_argnames=(
    "cfg", "has_missing", "split_merge", "router", "feat_sampler"))
def grow_tree_exact(key: jax.Array, X: jax.Array, gh: jax.Array,
                    cfg: GrowConfig,
                    row_valid: Optional[jax.Array] = None,
                    has_missing: bool = True,
                    rank_t: Optional[jax.Array] = None,
                    uniq: Optional[jax.Array] = None,
                    split_merge=None, router=None, feat_sampler=None
                    ) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree by exact enumeration.

    X: (N, F) raw values (NaN = missing); gh: (N, 2).
    ``has_missing=False`` (a per-dataset static fact the caller
    establishes host-side) elides the default-left scan and the
    present-vs-missing end-of-scan candidates — the reference's dense
    fast path (colmaker's backward scan is a no-op without missing).
    ``rank_t``/``uniq`` (from :func:`build_exact_ranks`) enable the
    faster single-key sort; without them the finder falls back to the
    two-key (node, value) sort.

    The three hooks are the column-split collective seams (the same
    protocol as :func:`xgboost_tpu.models.tree.grow_tree`'s; supplied
    by ``parallel/colsplit.grow_tree_exact_colsplit`` — the
    DistColMaker analog, ``updater_distcol-inl.hpp:136-153``):
    ``split_merge(local_best)`` reduces per-shard winners to the global
    one; ``router(best, node_of_row, X, x_missing)`` resolves row
    routing when the winning feature may live on another shard;
    ``feat_sampler(key, rate, X)`` draws colsample masks shards agree
    on.  Defaults are the single-device identities.
    Returns (tree, row_leaf) like :func:`grow_tree`.
    """
    N, F = X.shape
    D = cfg.max_depth
    xt = X.T                                         # (F, N) sort key
    miss_t = jnp.isnan(xt)

    from xgboost_tpu.models.tree import _sample_features
    if router is None:
        router = _default_exact_router
    if feat_sampler is None:
        feat_sampler = (lambda k, rate, Xl:
                        _sample_features(k, Xl.shape[1], rate))

    key_rows, key_ftree, key_flevel = jax.random.split(key, 3)
    gh_used = gh
    if cfg.subsample < 1.0:
        keep = jax.random.uniform(key_rows, (N,)) < cfg.subsample
        gh_used = gh * keep[:, None].astype(gh.dtype)
    if row_valid is not None:
        gh_used = gh_used * row_valid[:, None].astype(gh.dtype)

    fmask_tree = feat_sampler(key_ftree, cfg.colsample_bytree, X)

    tree = empty_tree(D)
    pos = jnp.zeros(N, jnp.int32)
    if row_valid is not None:
        pos = jnp.where(row_valid, pos, -1)
    row_leaf = jnp.zeros(N, jnp.int32)
    x_missing = jnp.isnan(X)

    for depth in range(D + 1):
        n_node = 1 << depth
        base = n_node - 1
        nst = node_stats(gh_used, pos, n_node)          # (n_node, 2)

        if depth == D:
            make_leaf = jnp.ones(n_node, jnp.bool_)
            best = None
        else:
            fmask = fmask_tree
            if cfg.colsample_bylevel < 1.0:
                fmask = fmask & feat_sampler(
                    jax.random.fold_in(key_flevel, depth),
                    cfg.colsample_bylevel, X)
            best = _find_exact_splits(xt, miss_t, gh_used, pos, nst,
                                      n_node, fmask, cfg.split,
                                      has_missing, rank_t, uniq)
            if split_merge is not None:
                best = split_merge(best)
            can_try = nst[:, 1] >= 2.0 * cfg.split.min_child_weight
            do_split = best.valid & can_try
            make_leaf = ~do_split

        tree = apply_level(tree, depth, nst, best, make_leaf, cfg.split)

        active = pos >= 0
        node_of_row = jnp.clip(pos, 0, n_node - 1)
        row_is_leaf = active & table_lookup(make_leaf, node_of_row)
        row_leaf = jnp.where(row_is_leaf, base + pos, row_leaf)
        if best is not None:
            go_left = router(best, node_of_row, X, x_missing)
            new_pos = 2 * pos + (~go_left).astype(jnp.int32)
            pos = jnp.where(active & ~row_is_leaf, new_pos, -1)

    return tree, row_leaf


def _find_exact_splits(xt, miss_t, gh_used, pos, nst, n_node: int,
                       fmask, scfg, has_missing: bool = True,
                       rank_t=None, uniq=None):
    """Best split per node via the segment-sorted scan: one batched
    (node, value) sort per level, O(N) segmented prefix work after.

    xt: (F, N) raw values (NaN = missing); miss_t: (F, N) bool;
    gh_used: (N, 2); pos: (N,) node of each row (-1 = retired);
    rank_t/uniq: optional dense-rank structures (build_exact_ranks)
    enabling the single-packed-key sort."""
    from xgboost_tpu.models.tree import SplitDecision

    N = gh_used.shape[0]
    F = xt.shape[0]
    M = n_node
    ids = jnp.arange(M, dtype=jnp.int32)
    G_tot, H_tot = nst[:, 0], nst[:, 1]
    root_gain = calc_gain(G_tot, H_tot, scfg)           # (M,)

    # rank packing only when (node, rank) fits an int32 (falls back to
    # the two-key sort for huge N x deep trees)
    shift = max(1, int(N - 1).bit_length())
    ranked = rank_t is not None and (M + 1) * (1 << shift) < 2 ** 31

    # (node, value)-sort each feature's rows, gradients as payloads.
    # Missing and retired rows key to trash segment M; subsampled-out
    # rows keep their node (zero gh — same boundary semantics as the
    # reference, whose scan visits them with zeroed gpair).  Unstable
    # sort: ties only occur between equal values of one node, where
    # any order yields the same boundary prefixes (stable would add an
    # internal iota tiebreak: measured 25.1 -> 21.5 ms at (28, 250k)).
    # NaN exclusion applies even with has_missing=False: the flag
    # elides the default-left scan + end-of-scan candidates, but the
    # column split pads shards with all-NaN columns that must still
    # sort into the trash segment (the mask is free when no NaN
    # exists — miss_t is all-False)
    keep = (pos >= 0)[None, :] & ~miss_t
    key1 = jnp.broadcast_to(jnp.where(keep, pos[None, :], M),
                            (F, N)).astype(jnp.int32)
    g_b = jnp.broadcast_to(gh_used[None, :, 0], (F, N))
    h_b = jnp.broadcast_to(gh_used[None, :, 1], (F, N))
    if ranked:
        packed = (key1 << shift) | rank_t
        key_ps, g_s, h_s = jax.lax.sort((packed, g_b, h_b),
                                        dimension=1, num_keys=1,
                                        is_stable=False)
        key_s = key_ps >> shift
        rank_s = key_ps & ((1 << shift) - 1)
        vs = None
    else:
        key_s, vs, g_s, h_s = jax.lax.sort((key1, xt, g_b, h_b),
                                           dimension=1, num_keys=2,
                                           is_stable=False)

    # segment offsets (F, M+1): segment m = slots [offs[m], offs[m+1])
    offs = jax.vmap(lambda k: jnp.searchsorted(
        k, jnp.arange(M + 1, dtype=k.dtype), side="left"))(key_s)
    seg_lo, seg_hi = offs[:, :M], offs[:, 1:]
    has_fin = seg_hi > seg_lo                           # (F, M)

    # global inclusive prefix sums (MXU blocked cumsum); per-node
    # prefixes are cg - base[node] + cbar * count, per-node finite
    # totals are the exclusive-cumsum difference across the segment.
    # MEAN-CENTERING: summing raw values would make a late segment's
    # prefix a small difference of large cumsums (f32 ulp at the
    # GLOBAL mass — notably bad for hessians, which are all-positive
    # so the cumsum grows monotonically).  Centering by the global
    # mean turns the cumsum into a near-zero-mean walk; the exact
    # identity prefix = centered_prefix + mean * count restores the
    # value with error that scales with the NODE's own mass (the
    # count is the small within-segment count).  The reference keeps
    # f64 node accumulators (updater_colmaker-inl.hpp ThreadEntry
    # TStats) — this is the f32-native equivalent.
    cbar_g = jnp.mean(g_s, axis=1, keepdims=True)       # (F, 1)
    cbar_h = jnp.mean(h_s, axis=1, keepdims=True)
    cg = _blocked_cumsum(g_s - cbar_g)
    ch = _blocked_cumsum(h_s - cbar_h)
    cgp = jnp.pad(cg, ((0, 0), (1, 0)))                 # exclusive at i
    chp = jnp.pad(ch, ((0, 0), (1, 0)))
    base_g = jnp.take_along_axis(cgp, seg_lo, axis=1)   # (F, M)
    base_h = jnp.take_along_axis(chp, seg_lo, axis=1)
    cnt_f = (seg_hi - seg_lo).astype(jnp.float32)
    Gf = (jnp.take_along_axis(cgp, seg_hi, axis=1) - base_g
          + cbar_g * cnt_f)
    Hf = (jnp.take_along_axis(chp, seg_hi, axis=1) - base_h
          + cbar_h * cnt_f)
    Gmiss = G_tot[None, :] - Gf                         # per-feature!
    Hmiss = H_tot[None, :] - Hf

    def lut(tab):
        # (F, M) table by key_s (F, N) -> (F, N); broadcast-compare
        # select (trash slots -> 0), fused by XLA into a streamed
        # reduce — never a materialized (F, N, M) array.  Multiple
        # luts share the compare via CSE (measured: 6 luts cost 6.7 ms
        # together at (28, 250k, 64), not 6 x 4.6)
        return jnp.where(key_s[:, :, None] == ids[None, None, :],
                         tab[:, None, :], 0.0).sum(axis=2)

    # within-segment inclusive count for the mean-centering identity
    n_in = (jnp.arange(N, dtype=jnp.float32)[None, :] + 1.0
            - lut(seg_lo.astype(jnp.float32)))
    GL_dr = cg - lut(base_g) + cbar_g * n_in
    HL_dr = ch - lut(base_h) + cbar_h * n_in
    gtot_s = lut(jnp.broadcast_to(G_tot[None, :], (F, M)))
    htot_s = lut(jnp.broadcast_to(H_tot[None, :], (F, M)))
    if has_missing:
        GL_dl = GL_dr + lut(Gmiss)
        HL_dl = HL_dr + lut(Hmiss)

    # candidate boundary AFTER slot i: next slot in the same segment
    # with a strictly greater value (reference enumerates between
    # distinct adjacent values, colmaker-inl.hpp:380-388); threshold is
    # the node-local midpoint (fvalue + last_fvalue) * 0.5 — adjacent
    # slots of the segment ARE the node-local neighbors
    nxt_k = jnp.concatenate([key_s[:, 1:],
                             jnp.full((F, 1), M, jnp.int32)], axis=1)
    if ranked:
        # rank adjacency == value distinctness (dense ranks); the
        # midpoint itself is recovered at winner slots only, from the
        # distinct-value table
        nxt_r = jnp.concatenate([rank_s[:, 1:],
                                 jnp.zeros((F, 1), jnp.int32)], axis=1)
        bnd = (key_s < M) & (nxt_k == key_s) & (nxt_r != rank_s)
        thr_s = None
    else:
        nxt_v = jnp.concatenate([vs[:, 1:], jnp.full((F, 1), jnp.nan,
                                                     vs.dtype)], axis=1)
        bnd = (key_s < M) & (nxt_k == key_s) & (nxt_v > vs)
        # zero non-candidate slots: all-missing features would
        # otherwise leave NaN midpoints that poison the final one-hot
        # contraction (0 * NaN) even for UNCHOSEN features
        thr_s = jnp.where(bnd, 0.5 * (vs + nxt_v), 0.0)

    def side_gain(GL, HL):
        # NOTE: the per-node root_gain term is argmax-invariant within
        # a segment, so it is NOT subtracted per slot — the winner's
        # gain is completed after extraction (saves one lut stream)
        GR = gtot_s - GL
        HR = htot_s - HL
        ok = (bnd & (HL >= scfg.min_child_weight)
              & (HR >= scfg.min_child_weight))
        lg = calc_gain(GL, HL, scfg) + calc_gain(GR, HR, scfg)
        return jnp.where(ok, lg, NEG)

    lg_dr = side_gain(GL_dr, HL_dr)                     # (F, N)
    if has_missing:
        lg_dl = side_gain(GL_dl, HL_dl)
        if scfg.default_direction == 1:                 # forced left
            lg_dr = jnp.full_like(lg_dr, NEG)
        elif scfg.default_direction == 2:               # forced right
            lg_dl = jnp.full_like(lg_dl, NEG)
        lg = jnp.maximum(lg_dr, lg_dl)                  # dr wins ties
    else:
        # without missing values both scan directions see identical
        # stats (the reference's backward scan finds the same splits);
        # default right wins the tie, as in the reference — unless the
        # user FORCED left, which must still be stored for data that
        # has missing values at predict time
        lg = lg_dr

    # per-node argmax over the node's contiguous slot range (single
    # streamed (F, N, M) reduce; winner attributes come from small
    # (F, M)-sized take_along_axis gathers afterwards)
    bi = jnp.argmax(jnp.where(key_s[:, :, None] == ids[None, None, :],
                              lg[:, :, None], NEG), axis=1)  # (F, M)
    in_seg = jnp.take_along_axis(key_s, bi, axis=1) == ids[None, :]
    bg_raw = jnp.take_along_axis(lg, bi, axis=1)
    ok_w = in_seg & (bg_raw > NEG)
    bg = jnp.where(ok_w, bg_raw - root_gain[None, :], NEG)
    if ranked:
        # winner midpoint from the distinct-value table: ranks at the
        # winning slot and the next slot of its segment
        r0 = jnp.take_along_axis(rank_s, bi, axis=1)
        r1 = jnp.take_along_axis(rank_s, jnp.minimum(bi + 1, N - 1),
                                 axis=1)
        v0 = jnp.take_along_axis(uniq, r0, axis=1)
        v1 = jnp.take_along_axis(uniq, r1, axis=1)
        b_thr = jnp.where(ok_w, 0.5 * (v0 + v1), 0.0)
    else:
        b_thr = jnp.take_along_axis(thr_s, bi, axis=1)
    if has_missing:
        dl_slot = lg_dl > lg_dr
        b_dl = jnp.take_along_axis(dl_slot, bi, axis=1)
        b_gl = jnp.take_along_axis(jnp.where(dl_slot, GL_dl, GL_dr),
                                   bi, axis=1)
        b_hl = jnp.take_along_axis(jnp.where(dl_slot, HL_dl, HL_dr),
                                   bi, axis=1)
    else:
        b_dl = jnp.full((F, M), scfg.default_direction == 1, jnp.bool_)
        b_gl = jnp.take_along_axis(GL_dr, bi, axis=1)
        b_hl = jnp.take_along_axis(HL_dr, bi, axis=1)

    if has_missing:
        # END-OF-SCAN candidates: split PRESENT vs MISSING (the
        # reference proposes these after each directional scan — the
        # only possible split on presence-only one-hot columns, where
        # all finite node values are equal and no boundary exists).
        # dr: all finite left, missing right (thr just above the
        # node's max value); dl: missing left, all finite right (thr
        # just below the min).  mcw filtering kills the empty-side
        # cases.  (Without missing values these candidates reduce to
        # the trivial everything-vs-nothing split with zero gain —
        # elided on the dense fast path.)
        if ranked:
            rr_hi = jnp.take_along_axis(rank_s,
                                        jnp.maximum(seg_hi - 1, 0),
                                        axis=1)
            rr_lo = jnp.take_along_axis(rank_s,
                                        jnp.minimum(seg_lo, N - 1),
                                        axis=1)
            a_max = jnp.where(has_fin, jnp.take_along_axis(
                uniq, rr_hi, axis=1), 0.0)              # (F, M)
            a_min = jnp.where(has_fin, jnp.take_along_axis(
                uniq, rr_lo, axis=1), 0.0)
        else:
            a_max = jnp.where(has_fin, jnp.take_along_axis(
                vs, jnp.maximum(seg_hi - 1, 0), axis=1), 0.0)
            a_min = jnp.where(has_fin, jnp.take_along_axis(
                vs, jnp.minimum(seg_lo, N - 1), axis=1), 0.0)
        eps_hi = jnp.maximum(jnp.abs(a_max) * 1e-6, 1e-6)
        eps_lo = jnp.maximum(jnp.abs(a_min) * 1e-6, 1e-6)

        def end_gain(GL, HL):
            GR = G_tot[None, :] - GL
            HR = H_tot[None, :] - HL
            ok = (has_fin & (HL >= scfg.min_child_weight)
                  & (HR >= scfg.min_child_weight))
            lgv = (calc_gain(GL, HL, scfg) + calc_gain(GR, HR, scfg)
                   - root_gain[None, :])
            return jnp.where(ok, lgv, NEG)

        g_end_dr = end_gain(Gf, Hf)       # present left, missing right
        g_end_dl = end_gain(Gmiss, Hmiss)  # missing left, present right
        if scfg.default_direction == 1:
            g_end_dr = jnp.full_like(g_end_dr, NEG)
        elif scfg.default_direction == 2:
            g_end_dl = jnp.full_like(g_end_dl, NEG)

        cand_g = jnp.stack([bg, g_end_dr, g_end_dl])    # (3, F, M)
        pick = jnp.argmax(cand_g, axis=0)  # boundary wins ties, dr<dl
        bg = cand_g.max(axis=0)
        b_thr = jnp.where(
            pick == 0, b_thr,
            jnp.where(pick == 1,
                      jnp.where(has_fin, a_max + eps_hi, 0.0),
                      jnp.where(has_fin, a_min - eps_lo, 0.0)))
        b_dl = jnp.where(pick == 0, b_dl, pick == 2)
        b_gl = jnp.where(pick == 0, b_gl,
                         jnp.where(pick == 1, Gf, Gmiss))
        b_hl = jnp.where(pick == 0, b_hl,
                         jnp.where(pick == 1, Hf, Hmiss))

    # (F, M) gains; feature mask + argmax with lowest-fid tie-break
    gains = jnp.where(fmask[:, None], bg, NEG)
    bf = jnp.argmax(gains, axis=0)                      # (M,)
    bgain = gains.max(axis=0)
    self_pick = jax.nn.one_hot(bf, F, dtype=jnp.float32).T
    thr = (self_pick * b_thr).sum(axis=0)
    dl = (self_pick * b_dl.astype(jnp.float32)).sum(axis=0) > 0.5
    gl = (self_pick * b_gl).sum(axis=0)
    hl = (self_pick * b_hl).sum(axis=0)
    valid = bgain > RT_EPS
    return SplitDecision(bgain, bf.astype(jnp.int32),
                         jnp.zeros(M, jnp.int32), dl, thr, valid,
                         jnp.zeros(M, jnp.int32), gl, hl)


# ---------------------------------------------------------------- traversal

def traverse_raw(tree: TreeArrays, X: jax.Array, max_depth: int):
    """Leaf per row by RAW value comparison (exact-mode trees store
    midpoint thresholds; bins don't exist in this pipeline)."""
    node = jnp.zeros_like(X[:, 0], dtype=jnp.int32)
    F = X.shape[1]
    f_ids = jnp.arange(F, dtype=jnp.int32)
    miss_x = jnp.isnan(X)
    for _ in range(max_depth):
        f = table_lookup(tree.feature, node)
        leaf = table_lookup(tree.is_leaf, node) | (f < 0)
        sel = f_ids[None, :] == jnp.maximum(f, 0)[:, None]
        xv = jnp.where(sel, jnp.nan_to_num(X), 0.0).sum(axis=1)
        xm = (sel & miss_x).any(axis=1)
        go_left = jnp.where(xm, table_lookup(tree.default_left, node),
                            xv < table_lookup(tree.threshold, node))
        nxt = jnp.where(go_left, 2 * node + 1, 2 * node + 2)
        node = jnp.where(leaf, node, nxt)
    return node


@functools.partial(jax.jit, static_argnames=("max_depth", "n_group"))
def predict_margin_raw(stack: TreeArrays, tree_group: jax.Array,
                       X: jax.Array, base: jax.Array, max_depth: int,
                       n_group: int) -> jax.Array:
    """Raw-value ensemble prediction (exact-mode counterpart of
    predict_margin_binned)."""
    N = X.shape[0]

    def body(margin, tg):
        tree, group = tg
        leaf = traverse_raw(tree, X, max_depth)
        contrib = table_lookup(tree.leaf_value, leaf)
        return margin + contrib[:, None] * jax.nn.one_hot(
            group, n_group, dtype=margin.dtype), None

    margin0 = jnp.broadcast_to(base, (N, n_group)).astype(jnp.float32)
    margin, _ = jax.lax.scan(body, margin0, (stack, tree_group))
    return margin

"""TRUE exact-greedy tree growth (reference ColMaker) at ANY cardinality.

The reference's exact updater scans each feature's sorted column per
node, evaluating a split between every pair of distinct values
(``updater_colmaker-inl.hpp:362-414``).  Round 2 realized exact mode as
"cuts at every distinct value" through the histogram grower, capped at
``max_exact_bin`` — silently approximate past the cap (VERDICT r2
item 5).  This module is the uncapped TPU-native exact algorithm:

  - The sort order of every feature column is STATIC (computed once per
    dataset, host-side): ``order[f]`` lists row ids by ascending value,
    missing (NaN) rows last.
  - Per level, a ``lax.scan`` over features computes, in sorted order,
    per-node running (G, H) prefix sums as a cumsum of the one-hot
    node-assignment times gradients — the vectorized equivalent of the
    reference's sequential scan — and evaluates the gain at every
    distinct-value boundary for both missing directions.
  - The split threshold is the MIDPOINT of the adjacent distinct values
    (reference ``(fvalue + e.last_fvalue) * 0.5``), and routing compares
    RAW values (``x < threshold``), so grown trees reproduce the
    reference's partitions split-for-split at any cardinality.

Exact mode is bin-free end to end: training data, margins and
prediction all use raw values (:func:`traverse_raw`).  Cost is
O(N x nodes) per (feature, level) — the same asymptotics as the
reference's per-feature scans, vectorized over nodes and rows.
Single-controller only (the running sums are order-dependent; the
reference's distributed exact mode is the column-split DistColMaker,
which this framework provides separately).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from xgboost_tpu.models.tree import (GrowConfig, TreeArrays, apply_level,
                                     empty_tree, table_lookup)
from xgboost_tpu.ops.histogram import node_stats
from xgboost_tpu.ops.split import NEG, RT_EPS, calc_gain


def build_exact_data(X: np.ndarray):
    """Static per-dataset structures for the exact grower.

    X: (N, F) raw float32, NaN = missing.  Returns host arrays
    (vals_sorted (F, N) with NaN->+inf sorted last, order (F, N) int32,
    n_finite (F,) int32).
    """
    N, F = X.shape
    vals = np.where(np.isnan(X), np.inf, X).astype(np.float32)
    order = np.argsort(vals, axis=0, kind="stable").astype(np.int32)  # (N, F)
    vals_sorted = np.take_along_axis(vals, order, axis=0)
    n_finite = (np.isfinite(vals_sorted).sum(axis=0)).astype(np.int32)
    return vals_sorted.T.copy(), order.T.copy(), n_finite


@functools.partial(jax.jit, static_argnames=("cfg",))
def grow_tree_exact(key: jax.Array, X: jax.Array, vals_sorted: jax.Array,
                    order: jax.Array, n_finite: jax.Array, gh: jax.Array,
                    cfg: GrowConfig,
                    row_valid: Optional[jax.Array] = None
                    ) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree by exact enumeration.

    X: (N, F) raw values (NaN = missing) — used for routing;
    vals_sorted/order: (F, N) static sort structures; gh: (N, 2).
    Returns (tree, row_leaf) like :func:`grow_tree`.
    """
    N, F = X.shape
    D = cfg.max_depth

    key_rows, key_ftree, key_flevel = jax.random.split(key, 3)
    gh_used = gh
    if cfg.subsample < 1.0:
        keep = jax.random.uniform(key_rows, (N,)) < cfg.subsample
        gh_used = gh * keep[:, None].astype(gh.dtype)
    if row_valid is not None:
        gh_used = gh_used * row_valid[:, None].astype(gh.dtype)

    from xgboost_tpu.models.tree import _sample_features
    fmask_tree = _sample_features(key_ftree, F, cfg.colsample_bytree)

    tree = empty_tree(D)
    pos = jnp.zeros(N, jnp.int32)
    if row_valid is not None:
        pos = jnp.where(row_valid, pos, -1)
    row_leaf = jnp.zeros(N, jnp.int32)
    x_missing = jnp.isnan(X)

    for depth in range(D + 1):
        n_node = 1 << depth
        base = n_node - 1
        nst = node_stats(gh_used, pos, n_node)          # (n_node, 2)

        if depth == D:
            make_leaf = jnp.ones(n_node, jnp.bool_)
            best = None
        else:
            fmask = fmask_tree
            if cfg.colsample_bylevel < 1.0:
                fmask = fmask & _sample_features(
                    jax.random.fold_in(key_flevel, depth), F,
                    cfg.colsample_bylevel)
            best = _find_exact_splits(vals_sorted, order, n_finite,
                                      gh_used, pos, nst, n_node, fmask,
                                      cfg.split)
            can_try = nst[:, 1] >= 2.0 * cfg.split.min_child_weight
            do_split = best.valid & can_try
            make_leaf = ~do_split

        tree = apply_level(tree, depth, nst, best, make_leaf, cfg.split)

        active = pos >= 0
        node_of_row = jnp.clip(pos, 0, n_node - 1)
        row_is_leaf = active & table_lookup(make_leaf, node_of_row)
        row_leaf = jnp.where(row_is_leaf, base + pos, row_leaf)
        if best is not None:
            f_row = table_lookup(best.feature, node_of_row)
            thr_row = table_lookup(best.threshold, node_of_row)
            dl_row = table_lookup(best.default_left, node_of_row)
            # raw-value routing (reference model.h:555-566)
            x_row = jnp.where(
                jnp.arange(F, dtype=jnp.int32)[None, :]
                == jnp.maximum(f_row, 0)[:, None], X, 0.0).sum(axis=1)
            miss = jnp.where(
                jnp.arange(F, dtype=jnp.int32)[None, :]
                == jnp.maximum(f_row, 0)[:, None],
                x_missing, False).any(axis=1)
            go_left = jnp.where(miss, dl_row, x_row < thr_row)
            new_pos = 2 * pos + (~go_left).astype(jnp.int32)
            pos = jnp.where(active & ~row_is_leaf, new_pos, -1)

    return tree, row_leaf


def _find_exact_splits(vals_sorted, order, n_finite, gh_used, pos, nst,
                       n_node: int, fmask, scfg):
    """Best split per node via sorted forward scans, vectorized over
    nodes; lax.scan over features keeps one (N, n_node) working set."""
    from xgboost_tpu.models.tree import SplitDecision

    N = gh_used.shape[0]
    M = n_node
    G_tot, H_tot = nst[:, 0], nst[:, 1]
    root_gain = calc_gain(G_tot, H_tot, scfg)           # (M,)

    def one_feature(carry, finputs):
        vs, od, nf = finputs                            # (N,), (N,), ()
        gh_s = gh_used[od]                              # (N, 2) sorted
        node_s = pos[od]                                # (N,)
        onehot = (node_s[:, None]
                  == jnp.arange(M, dtype=jnp.int32)[None, :])
        oh = onehot.astype(jnp.float32)
        cg = jnp.cumsum(oh * gh_s[:, 0:1], axis=0)      # (N, M) GL incl. i
        ch = jnp.cumsum(oh * gh_s[:, 1:2], axis=0)
        # finite (present-value) totals per node; missing mass = total -
        # finite  (missing rows sort last: slots >= nf)
        fin = (jnp.arange(N) < nf)[:, None]
        # per-node finite sums = cumsum at the last finite slot:
        idx_last = jnp.maximum(nf - 1, 0)
        Gf = jnp.where(nf > 0, cg[idx_last], 0.0)       # (M,)
        Hf = jnp.where(nf > 0, ch[idx_last], 0.0)
        Gmiss = G_tot - Gf
        Hmiss = H_tot - Hf

        # candidate boundary AFTER sorted slot i: valid when the next
        # FINITE value is strictly greater (reference enumerates between
        # distinct adjacent values, colmaker-inl.hpp:380-388)
        nxt = jnp.concatenate([vs[1:], jnp.full(1, jnp.inf)])
        boundary = fin[:, 0] & jnp.isfinite(nxt) & (nxt > vs)

        # default RIGHT: left = finite prefix;  default LEFT: left +=
        # missing mass (reference's backward scan equivalent)
        GL_dr, HL_dr = cg, ch
        GL_dl, HL_dl = cg + Gmiss[None, :], ch + Hmiss[None, :]
        # every distinct-value boundary is a candidate for EVERY node
        # (its per-node prefix sums are cg/ch at that slot); masking to
        # the boundary row's own node would starve nodes whose rows
        # don't sit on boundaries (e.g. 0/1 features: one boundary row).
        # The threshold must be the NODE-LOCAL midpoint (reference
        # (fvalue + last_fvalue) * 0.5): running max of node values up
        # to the slot, and first node value strictly after it.
        vm = jnp.where(onehot & fin, vs[:, None], -jnp.inf)
        a_run = jax.lax.cummax(vm, axis=0)               # (N, M)
        bm = jnp.where(onehot & fin, vs[:, None], jnp.inf)
        b_rev = jax.lax.cummin(bm, axis=0, reverse=True)
        b_next = jnp.concatenate(
            [b_rev[1:], jnp.full((1, M), jnp.inf)], axis=0)
        # candidate needs node rows on BOTH sides among finite values
        # (the reference's node-local scan never proposes otherwise)
        ok_b = (boundary[:, None] & jnp.isfinite(a_run)
                & jnp.isfinite(b_next))
        thr_nm = jnp.where(ok_b, (a_run + b_next) * 0.5, 0.0)

        def side_gain(GL, HL):
            GR = G_tot[None, :] - GL
            HR = H_tot[None, :] - HL
            ok = (ok_b & (HL >= scfg.min_child_weight)
                  & (HR >= scfg.min_child_weight))
            lg = (calc_gain(GL, HL, scfg) + calc_gain(GR, HR, scfg)
                  - root_gain[None, :])
            return jnp.where(ok, lg, NEG)

        lg_dr = side_gain(GL_dr, HL_dr)                 # (N, M)
        lg_dl = side_gain(GL_dl, HL_dl)
        if scfg.default_direction == 1:                 # forced left
            lg_dr = jnp.full_like(lg_dr, NEG)
        elif scfg.default_direction == 2:               # forced right
            lg_dl = jnp.full_like(lg_dl, NEG)
        lg = jnp.maximum(lg_dr, lg_dl)                  # dr wins ties
        bi = jnp.argmax(lg, axis=0)                     # (M,) best slot
        bg = lg.max(axis=0)
        sel = jax.nn.one_hot(bi, N, dtype=jnp.float32).T  # (N, M)
        b_thr = (sel * thr_nm).sum(axis=0)
        b_dl = ((sel * lg_dl).sum(axis=0)
                > (sel * lg_dr).sum(axis=0))
        b_gl = (sel * jnp.where(b_dl[None, :], GL_dl, GL_dr)).sum(axis=0)
        b_hl = (sel * jnp.where(b_dl[None, :], HL_dl, HL_dr)).sum(axis=0)

        # END-OF-SCAN candidates: split PRESENT vs MISSING (the
        # reference proposes these after each directional scan — the
        # only possible split on presence-only one-hot columns, where
        # all finite node values are equal and no boundary exists).
        # dr: all finite left, missing right (thr just above the node's
        # max value); dl: missing left, all finite right (thr just
        # below the min).  mcw filtering kills the empty-side cases.
        a_max = a_run[-1]                                # (M,) node max
        a_min = b_rev[0]                                 # (M,) node min
        has_fin = jnp.isfinite(a_max)
        eps_hi = jnp.maximum(jnp.abs(a_max) * 1e-6, 1e-6)
        eps_lo = jnp.maximum(jnp.abs(a_min) * 1e-6, 1e-6)

        def end_gain(GL, HL):
            GR = G_tot - GL
            HR = H_tot - HL
            ok = (has_fin & (HL >= scfg.min_child_weight)
                  & (HR >= scfg.min_child_weight))
            lgv = (calc_gain(GL, HL, scfg) + calc_gain(GR, HR, scfg)
                   - root_gain)
            return jnp.where(ok, lgv, NEG)

        g_end_dr = end_gain(Gf, Hf)           # present left, missing right
        g_end_dl = end_gain(Gmiss, Hmiss)     # missing left, present right
        if scfg.default_direction == 1:
            g_end_dr = jnp.full_like(g_end_dr, NEG)
        elif scfg.default_direction == 2:
            g_end_dl = jnp.full_like(g_end_dl, NEG)

        cand_g = jnp.stack([bg, g_end_dr, g_end_dl])     # (3, M)
        pick = jnp.argmax(cand_g, axis=0)      # boundary wins ties, dr<dl
        bg = cand_g.max(axis=0)
        b_thr = jnp.where(pick == 0, b_thr,
                          jnp.where(pick == 1,
                                    jnp.where(has_fin, a_max + eps_hi, 0.0),
                                    jnp.where(has_fin, a_min - eps_lo, 0.0)))
        b_dl = jnp.where(pick == 0, b_dl, pick == 2)
        b_gl = jnp.where(pick == 0, b_gl,
                         jnp.where(pick == 1, Gf, Gmiss))
        b_hl = jnp.where(pick == 0, b_hl,
                         jnp.where(pick == 1, Hf, Hmiss))
        return carry, (bg, b_thr, b_dl, b_gl, b_hl)

    _, (gains, thrs, dls, gls, hls) = jax.lax.scan(
        one_feature, 0, (vals_sorted, order, n_finite))
    # gains: (F, M); feature mask + argmax with lowest-fid tie-break
    gains = jnp.where(fmask[:, None], gains, NEG)
    bf = jnp.argmax(gains, axis=0)                      # (M,)
    bgain = gains.max(axis=0)
    self_pick = jax.nn.one_hot(bf, gains.shape[0], dtype=jnp.float32).T
    thr = (self_pick * thrs).sum(axis=0)
    dl = (self_pick * dls.astype(jnp.float32)).sum(axis=0) > 0.5
    gl = (self_pick * gls).sum(axis=0)
    hl = (self_pick * hls).sum(axis=0)
    valid = bgain > RT_EPS
    return SplitDecision(bgain, bf.astype(jnp.int32),
                         jnp.zeros(M, jnp.int32), dl, thr, valid,
                         jnp.zeros(M, jnp.int32), gl, hl)


# ---------------------------------------------------------------- traversal

def traverse_raw(tree: TreeArrays, X: jax.Array, max_depth: int):
    """Leaf per row by RAW value comparison (exact-mode trees store
    midpoint thresholds; bins don't exist in this pipeline)."""
    node = jnp.zeros_like(X[:, 0], dtype=jnp.int32)
    F = X.shape[1]
    f_ids = jnp.arange(F, dtype=jnp.int32)
    miss_x = jnp.isnan(X)
    for _ in range(max_depth):
        f = table_lookup(tree.feature, node)
        leaf = table_lookup(tree.is_leaf, node) | (f < 0)
        sel = f_ids[None, :] == jnp.maximum(f, 0)[:, None]
        xv = jnp.where(sel, jnp.nan_to_num(X), 0.0).sum(axis=1)
        xm = (sel & miss_x).any(axis=1)
        go_left = jnp.where(xm, table_lookup(tree.default_left, node),
                            xv < table_lookup(tree.threshold, node))
        nxt = jnp.where(go_left, 2 * node + 1, 2 * node + 2)
        node = jnp.where(leaf, node, nxt)
    return node


@functools.partial(jax.jit, static_argnames=("max_depth", "n_group"))
def predict_margin_raw(stack: TreeArrays, tree_group: jax.Array,
                       X: jax.Array, base: jax.Array, max_depth: int,
                       n_group: int) -> jax.Array:
    """Raw-value ensemble prediction (exact-mode counterpart of
    predict_margin_binned)."""
    N = X.shape[0]

    def body(margin, tg):
        tree, group = tg
        leaf = traverse_raw(tree, X, max_depth)
        contrib = table_lookup(tree.leaf_value, leaf)
        return margin + contrib[:, None] * jax.nn.one_hot(
            group, n_group, dtype=margin.dtype), None

    margin0 = jnp.broadcast_to(base, (N, n_group)).astype(jnp.float32)
    margin, _ = jax.lax.scan(body, margin0, (stack, tree_group))
    return margin

"""GBLinear: elastic-net linear booster via shotgun coordinate descent.

Re-implements the reference ``GBLinear`` (``src/gbm/gblinear-inl.hpp``):
per-round bias Newton step (``CalcDeltaBias``, :224-227) followed by
per-feature elastic-net coordinate updates (``CalcDelta`` soft threshold,
:213-225), with ``num_output_group`` weight columns for multiclass.

TPU-native shape: the reference's shotgun CD runs features in parallel
OMP threads over a SHARED gradient vector that absorbs each thread's
updates as they land (:76-105 — Shotgun/Bradley et al.), so correlated
features see each other's progress.  A fully-synchronous Jacobi step
(all features against the same stale residual) loses that property and
DIVERGES on strongly correlated features.  Here one boosting round is a
jitted ``lax.scan`` over feature blocks: within a block, deltas are
computed in parallel (MXU reductions); between blocks the residual
gradient is updated exactly (``g += h * X_b @ delta_b`` — the same
algebra as the reference's in-place ``p.grad += p.hess * v * dw``).
Block size 1 (the default) is exact sequential coordinate descent;
larger blocks trade shotgun-style parallelism for the (bounded)
correlation risk the reference accepts.  Missing entries contribute 0,
matching the reference's sparse column iteration.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from xgboost_tpu.config import TrainParam
from xgboost_tpu.data import DMatrix


@functools.partial(jax.jit, static_argnames=(
    "eta", "lam", "alpha", "lam_bias", "block", "axis_name"))
def _linear_boost_step(X, gh, weight, bias, eta, lam, alpha, lam_bias,
                       block=1, axis_name=None):
    """One round of bias + block-sequential coordinate updates.

    X: (N, F) dense (0 = missing); gh: (N, K, 2); weight: (F, K); bias: (K,).
    With ``axis_name`` (dsplit=row: rows sharded over a mesh axis), every
    row reduction — the bias sums and each block's ``Gf``/``Hf`` — psums
    over the axis, exactly where the reference would allreduce
    (gblinear-inl.hpp:45-106 runs on the local shard; the distributed
    completion is VERDICT r2 item 10).  The residual update stays
    shard-local (rows only see their own delta effect).
    """
    red = (lambda x: jax.lax.psum(x, axis_name)) if axis_name else \
        (lambda x: x)
    g, h = gh[..., 0], gh[..., 1]            # (N, K)
    # bias step (CalcDeltaBias)
    sum_g, sum_h = red(g.sum(axis=0)), red(h.sum(axis=0))
    dbias = eta * (-(sum_g + lam_bias * bias) / (sum_h + lam_bias + 1e-12))
    bias = bias + dbias
    g = g + h * dbias[None, :]               # remove bias effect (ref :66-73)

    F = X.shape[1]
    bf = max(1, min(block, F))
    n_blocks = -(-F // bf)
    f_pad = n_blocks * bf
    if f_pad != F:
        X = jnp.pad(X, ((0, 0), (0, f_pad - F)))
        weight = jnp.pad(weight, ((0, f_pad - F), (0, 0)))

    def body(carry, b):
        g, weight = carry
        Xb = jax.lax.dynamic_slice_in_dim(X, b * bf, bf, 1)       # (N, bf)
        wb = jax.lax.dynamic_slice_in_dim(weight, b * bf, bf, 0)  # (bf, K)
        Gf = red(Xb.T @ g)                   # (bf, K)
        Hf = red((Xb * Xb).T @ h)
        # CalcDelta elastic-net step (ref :213-225)
        tmp = wb - (Gf + lam * wb) / (Hf + lam)
        pos = -(Gf + lam * wb + alpha) / (Hf + lam)
        neg = -(Gf + lam * wb - alpha) / (Hf + lam)
        delta = jnp.where(tmp >= 0, jnp.maximum(pos, -wb),
                          jnp.minimum(neg, -wb))
        delta = jnp.where(Hf < 1e-5, 0.0, eta * delta)
        weight = jax.lax.dynamic_update_slice_in_dim(
            weight, wb + delta, b * bf, 0)
        # exact residual propagation to later blocks (ref :96-99)
        g = g + h * (Xb @ delta)
        return (g, weight), None

    (g, weight), _ = jax.lax.scan(body, (g, weight),
                                  jnp.arange(n_blocks))
    return weight[:F], bias


@functools.lru_cache(maxsize=None)
def _linear_boost_step_dp_fn(mesh, eta, lam, alpha, lam_bias, block):
    """Compiled row-sharded boosting step, cached per (mesh, params) so
    per-round calls hit the jit cache instead of re-tracing (meshes are
    hashable; floats come in already-coerced)."""
    from jax.sharding import PartitionSpec as P
    from xgboost_tpu.parallel.mesh import shard_map
    fn = shard_map(
        functools.partial(
            _linear_boost_step.__wrapped__, eta=eta, lam=lam, alpha=alpha,
            lam_bias=lam_bias, block=block, axis_name="data"),
        mesh=mesh, in_specs=(P("data"), P("data"), P(), P()),
        out_specs=(P(), P()), check_vma=False)
    return jax.jit(fn)


def _linear_boost_step_dp(mesh, X, gh, weight, bias, eta, lam, alpha,
                          lam_bias, block=1):
    """Row-sharded boosting round: X/gh sharded over 'data', weight/bias
    replicated; reductions psum over the mesh (bit-matches single-device
    up to reduction order)."""
    return _linear_boost_step_dp_fn(mesh, eta, lam, alpha, lam_bias,
                                    block)(X, gh, weight, bias)


@jax.jit
def _linear_predict(X, weight, bias, base):
    return base + bias[None, :] + X @ weight


class GBLinear:
    """Linear booster state (reference gblinear-inl.hpp Model, :228-278)."""

    def __init__(self, param: TrainParam, num_feature: int):
        self.param = param
        self.num_feature = num_feature
        K = max(1, param.num_output_group)
        self.weight = jnp.zeros((num_feature, K), jnp.float32)
        self.bias = jnp.zeros((K,), jnp.float32)
        self.version = 0  # boosting rounds applied

    @property
    def num_boosted_rounds(self) -> int:
        return self.version

    def host_matrix(self, dmat: DMatrix) -> np.ndarray:
        """Dense (N, F) host matrix, 0 for missing entries."""
        X = dmat.to_dense(missing=np.nan)
        if X.shape[1] < self.num_feature:
            X = np.pad(X, ((0, 0), (0, self.num_feature - X.shape[1])),
                       constant_values=np.nan)
        return np.nan_to_num(X[:, :self.num_feature], nan=0.0)

    def device_matrix(self, dmat: DMatrix) -> jax.Array:
        return jnp.asarray(self.host_matrix(dmat))

    def do_boost(self, X: jax.Array, gh: jax.Array, info=None,
                 mesh=None) -> None:
        if mesh is not None:
            self.weight, self.bias = _linear_boost_step_dp(
                mesh, X, gh, self.weight, self.bias,
                float(self.param.eta), float(self.param.reg_lambda),
                float(self.param.reg_alpha), float(self.param.lambda_bias),
                block=max(1, self.param.linear_block))
        else:
            self.weight, self.bias = _linear_boost_step(
                X, gh, self.weight, self.bias,
                float(self.param.eta), float(self.param.reg_lambda),
                float(self.param.reg_alpha), float(self.param.lambda_bias),
                block=max(1, self.param.linear_block))
        self.version += 1

    def predict_margin(self, X: jax.Array, base, ntree_limit: int = 0,
                       root=None):
        # root (multi-root trees) has no meaning for a linear model
        return _linear_predict(X, self.weight, self.bias,
                               jnp.asarray(base, jnp.float32))

    def predict_leaf(self, X, ntree_limit: int = 0, root=None):
        raise ValueError("pred_leaf is not defined for the gblinear booster")

    # ------------------------------------------------------------ serialize
    def get_state(self) -> dict:
        return {"linear_weight": np.asarray(self.weight),
                "linear_bias": np.asarray(self.bias),
                "linear_version": np.int64(self.version)}

    @classmethod
    def from_state(cls, param: TrainParam, state: dict) -> "GBLinear":
        w = state["linear_weight"]
        m = cls(param, w.shape[0])
        m.weight = jnp.asarray(w)
        m.bias = jnp.asarray(state["linear_bias"])
        m.version = int(state.get("linear_version", 1))
        return m

    def dump_text(self) -> str:
        """Text dump (reference GBLinear::DumpModel, gblinear-inl.hpp:127-142)."""
        lines = ["bias:"]
        lines += [f"{float(b):g}" for b in np.asarray(self.bias)]
        lines.append("weight:")
        for row in np.asarray(self.weight):
            lines += [f"{float(v):g}" for v in row]
        return "\n".join(lines) + "\n"

"""Tree updaters beyond growth: prune, refresh, and the updater registry.

The reference exposes seven pluggable ``IUpdater`` names
(``src/tree/updater.cpp:18-31``).  Their TPU-native mapping:

  - ``grow_colmaker``  — exact greedy: realized as histogram growth with
    cuts at EVERY distinct feature value (partition-equivalent to the
    sorted-column scan of ``updater_colmaker-inl.hpp:362-414``).
  - ``grow_histmaker`` — quantile-binned histogram growth (the default;
    ``updater_histmaker-inl.hpp``).
  - ``grow_skmaker``   — per-node 3-way (pos-grad/neg-grad/hess)
    quantile-sketch split selection (:mod:`xgboost_tpu.models.skmaker`;
    ``updater_skmaker-inl.hpp:133-374``), plugged into the grower's
    split_finder seam; classically paired with ``refresh``.
  - ``prune``          — bottom-up post-prune of splits with
    loss_chg < min_split_loss (``updater_prune-inl.hpp:42-72``).
  - ``refresh``        — recompute node stats/leaf values by streaming
    (new) data through the existing trees
    (``updater_refresh-inl.hpp:19-151``).
  - ``distcol``        — column-split distributed growth
    (:mod:`xgboost_tpu.parallel.colsplit`;
    ``updater_distcol-inl.hpp``).
  - ``sync``           — broadcast trees from rank 0
    (``updater_sync-inl.hpp:34-49``); a no-op here because every shard
    computes identical trees from psum-reduced statistics.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from xgboost_tpu.models.tree import (TreeArrays, bin_of_feature,
                                     root_level, table_lookup)
from xgboost_tpu.ops.split import SplitConfig, calc_gain, calc_weight

KNOWN_UPDATERS = ("grow_colmaker", "grow_histmaker", "grow_skmaker",
                  "prune", "refresh", "distcol", "sync")


def parse_updaters(updater: str) -> Tuple[str, ...]:
    seq = tuple(u.strip() for u in updater.split(",") if u.strip())
    for u in seq:
        if u not in KNOWN_UPDATERS:
            raise ValueError(f"unknown updater {u!r} (known: {KNOWN_UPDATERS})")
    return seq


# ------------------------------------------------------------------- prune
def prune_tree(tree: TreeArrays, gamma: float,
               n_roots: int = 1) -> Tuple[TreeArrays, np.ndarray]:
    """Bottom-up post-prune (reference TreePruner::TryPruneLeaf,
    updater_prune-inl.hpp:42-72): a split node whose children are both
    leaves and whose loss_chg < gamma becomes a leaf, recursively.

    Host-side numpy — trees are tiny.  Returns (pruned tree,
    resolve[n_nodes] mapping every node to its surviving self-or-ancestor
    leaf so grow-time row->leaf assignments can be re-targeted).
    """
    feature = np.asarray(tree.feature).copy()
    is_leaf = np.asarray(tree.is_leaf).copy()
    gain = np.asarray(tree.gain).copy()
    n = feature.shape[0]

    def leaf_like(c: int) -> bool:
        return c >= n or is_leaf[c] or feature[c] < 0

    # deepest-first sweep = recursion order of the reference
    for nid in range(n - 1, -1, -1):
        if is_leaf[nid] or feature[nid] < 0:
            continue
        left, right = 2 * nid + 1, 2 * nid + 2
        if leaf_like(left) and leaf_like(right) and gain[nid] < gamma:
            is_leaf[nid] = True
            feature[nid] = -1
            gain[nid] = 0.0

    resolve = np.arange(n, dtype=np.int32)
    # top-down: a node under a pruned ancestor resolves to that ancestor.
    # Multi-root trees: nodes ABOVE the root-slot level are synthetic
    # (never-split placeholders) — root slots must not resolve into them.
    start_real = (1 << root_level(n_roots)) - 1
    for nid in range(1, n):
        parent = (nid - 1) // 2
        if parent < start_real:
            continue
        if is_leaf[resolve[parent]] or feature[resolve[parent]] < 0:
            resolve[nid] = resolve[parent]

    pruned = tree._replace(
        feature=jnp.asarray(feature),
        is_leaf=jnp.asarray(is_leaf),
        gain=jnp.asarray(gain),
    )
    return pruned, resolve


# ----------------------------------------------------------------- refresh
@functools.partial(jax.jit, static_argnames=("cfg", "max_depth",
                                             "hist_reduce", "n_roots"))
def refresh_tree(tree: TreeArrays, binned: jax.Array, gh: jax.Array,
                 cfg: SplitConfig, max_depth: int,
                 row_valid: Optional[jax.Array] = None,
                 hist_reduce: Callable[[jax.Array], jax.Array] = None,
                 root: Optional[jax.Array] = None, n_roots: int = 1
                 ) -> TreeArrays:
    """Recompute one tree's node stats + leaf values from (new) data
    (reference TreeRefresher, updater_refresh-inl.hpp:19-151: stream rows
    through the tree accumulating GradStats at every node on the path,
    allreduce, then refresh leaf values and loss_chg).

    Structure (features/thresholds) is untouched; leaf_value, sum_hess
    and gain are refreshed.  The gradients gh must be computed against
    the margin EXCLUDING this tree (the reference refreshes trees one by
    one, subtracting each tree's contribution first) — the caller handles
    that; for the common single-refresh-pass use the full-model margin is
    the reference's behavior too (it refreshes all trees against the
    current prediction).
    """
    red = hist_reduce if hist_reduce is not None else (lambda x: x)
    n_nodes = tree.n_nodes
    gh_used = gh
    if row_valid is not None:
        gh_used = gh_used * row_valid[:, None].astype(gh.dtype)

    # accumulate (G, H) at every node on each row's root->leaf path;
    # multi-root trees always offset into the root-slot level (root=None
    # = slot 0, matching growth and traversal)
    node = jnp.zeros_like(binned[:, 0], dtype=jnp.int32)
    if n_roots > 1:
        node = node + (1 << root_level(n_roots)) - 1
        if root is not None:
            node = node + jnp.clip(root.astype(jnp.int32), 0, n_roots - 1)
    acc = jnp.zeros((n_nodes, 2), jnp.float32)
    for _ in range(max_depth + 1):
        acc = acc.at[node].add(gh_used)
        f = table_lookup(tree.feature, node)
        leaf = table_lookup(tree.is_leaf, node) | (f < 0)
        b = bin_of_feature(binned, jnp.maximum(f, 0))
        go_left = jnp.where(b == 0, table_lookup(tree.default_left, node),
                            b <= table_lookup(tree.cut_index, node) + 1)
        node = jnp.where(leaf, node, jnp.where(go_left, 2 * node + 1,
                                               2 * node + 2))
        # a row parked at a leaf has contributed at every path node
        # including the leaf itself; zero it out for later iterations
        gh_used = jnp.where(leaf[:, None], 0.0, gh_used)
    acc = red(acc)

    G, H = acc[:, 0], acc[:, 1]
    new_weight = calc_weight(G, H, cfg) * cfg.eta
    # refreshed loss_chg for split nodes: gain(L) + gain(R) - gain(self)
    left = jnp.arange(n_nodes) * 2 + 1
    right = left + 1
    GL = jnp.where(left < n_nodes, G[jnp.clip(left, 0, n_nodes - 1)], 0.0)
    HL = jnp.where(left < n_nodes, H[jnp.clip(left, 0, n_nodes - 1)], 0.0)
    GR = jnp.where(right < n_nodes, G[jnp.clip(right, 0, n_nodes - 1)], 0.0)
    HR = jnp.where(right < n_nodes, H[jnp.clip(right, 0, n_nodes - 1)], 0.0)
    split_gain = (calc_gain(GL, HL, cfg) + calc_gain(GR, HR, cfg)
                  - calc_gain(G, H, cfg))
    is_split = (~tree.is_leaf) & (tree.feature >= 0)
    return tree._replace(
        leaf_value=new_weight,
        sum_hess=H,
        gain=jnp.where(is_split, split_gain, 0.0),
    )

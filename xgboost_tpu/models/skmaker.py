"""SketchMaker: per-node 3-way quantile-sketch split finding.

The reference's ``grow_skmaker`` (``src/tree/updater_skmaker-inl.hpp``)
sketches positive-gradient, negative-gradient and hessian mass per
node x feature (:133-172), allreduces the pruned summaries (:254-264),
and picks splits by querying the merged summaries (:314-374) — a
LOSSIER but smaller-payload alternative to full histograms, classically
followed by ``refresh`` for exact stats.

TPU-native realization: the level histogram (already the product of the
fast Pallas kernel) is compressed per (node, feature) into three
``parallel/sketch_device.py``-style padded summaries of K slots each
(K = sketch_ratio / sketch_eps << n_bins), and the split is chosen by
rank queries at the hessian summary's support values:

    GL(v) = rank_pos(<= v) - rank_neg(<= v)      HL(v) = rank_hess(<= v)

Deviations from the reference, by design: summaries are built from the
binned histogram (binning is this framework's global quantization), and
in dsplit=row mode the histogram psum happens before compression — the
pre-reduction summary merge (rabit ``SerializeReducer``) exists as
``parallel/sketch_device.merge_summaries_dev`` and is exercised by the
distributed cut proposal.  Leaf weights still come from exact node
stats, so ``refresh`` is optional rather than required.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from xgboost_tpu.models.tree import SplitDecision
from xgboost_tpu.ops.split import NEG, RT_EPS, calc_gain


def _compress_row(mass: jax.Array, K: int):
    """One (B,) per-bin nonnegative mass -> padded K-entry summary.

    Bin ids are the values (already sorted, already distinct), so the
    summary is (value=bin, rank_next=cumulative mass <= bin) pruned to
    K entries by even-rank selection (SetPrune semantics on exact
    per-value masses).  Returns (values (K,), rank_next (K,)); padding
    value = B (above every real bin), rank_next = total.
    """
    B = mass.shape[0]
    cum = jnp.cumsum(mass)                       # rank_next per bin
    total = cum[-1]
    present = mass > 0
    n_real = jnp.sum(present)
    # order present bins first (stable: by ~present then bin id)
    order = jnp.argsort(~present, stable=True)
    vals = order.astype(jnp.float32)
    ranks = cum[order]
    # even-rank interior selection + extremes: K-2 interior picks so
    # the summary carries the full K configured slots
    k = jnp.arange(1, max(K - 1, 1), dtype=jnp.float32)
    target = k * (total / max(K - 1, 1))
    mid = ranks - mass[order] * 0.5              # midpoint rank of entry
    mid = jnp.where(jnp.arange(B) < n_real, mid, jnp.inf)
    sel = jnp.clip(jnp.searchsorted(mid, target, side="left"),
                   0, jnp.maximum(n_real - 1, 0))
    sel = jnp.concatenate([jnp.zeros(1, sel.dtype), sel,
                           jnp.maximum(n_real - 1, 0)[None]])
    sv, sr = vals[sel], ranks[sel]
    keep = jnp.concatenate([jnp.array([True]), sv[1:] != sv[:-1]])
    keep &= n_real > 0
    sv = jnp.where(keep, sv, jnp.float32(B))
    sr = jnp.where(keep, sr, total)
    order2 = jnp.argsort(sv, stable=True)
    return sv[order2], sr[order2], total


def _rank_at(values: jax.Array, rank_next: jax.Array, q: jax.Array):
    """Mass <= q from a compressed summary (conservative: the last
    retained entry at or below q)."""
    idx = jnp.searchsorted(values, q, side="right") - 1
    safe = jnp.clip(idx, 0, values.shape[0] - 1)
    return jnp.where(idx < 0, 0.0, rank_next[safe])


@functools.lru_cache(maxsize=None)
def skmaker_split_finder(K: int):
    """Build a ``grow_tree`` split_finder implementing skmaker.

    K: summary size per (node, feature, kind) — the reference's
    max_sketch_size = sketch_ratio / sketch_eps.

    Memoized so equal K yields a stable function identity: the finder is
    a jit static argument of the growers (and of the fused round scan),
    so identity stability is what makes their compile caches shared
    across Booster instances.
    """

    def finder(hist, nst, n_cuts, cut_values, fmask, split_cfg):
        M, F, B, _ = hist.shape
        g = hist[..., 0]
        h = hist[..., 1]
        pos_m = jnp.maximum(g, 0.0)
        neg_m = jnp.maximum(-g, 0.0)

        def compress(mass):                       # (M, F, B) -> summaries
            return jax.vmap(jax.vmap(lambda r: _compress_row(r, K)))(mass)

        pv, pr, _ = compress(pos_m)
        nv, nr, _ = compress(neg_m)
        hv, hr, htot = compress(h)                # (M, F, K) each

        # candidates: the hessian summary's support values (bin ids);
        # exclude the missing bin 0 as a boundary by flooring at bin 1
        cand = jnp.clip(hv, 1.0, float(B))        # (M, F, K)

        def left_mass(vals, ranks, c):
            le = _rank_at(vals, ranks, c)         # mass <= c incl. bin 0
            at0 = _rank_at(vals, ranks, jnp.float32(0.0))
            return le - at0                       # exclude missing mass

        q = jax.vmap(jax.vmap(jax.vmap(
            lambda c, pvv, prr, nvv, nrr, hvv, hrr: (
                left_mass(pvv, prr, c) - left_mass(nvv, nrr, c),
                left_mass(hvv, hrr, c)),
            in_axes=(0, None, None, None, None, None, None))))
        GL_excl, HL_excl = q(cand, pv, pr, nv, nr, hv, hr)  # (M, F, K)

        G, H = nst[:, 0], nst[:, 1]
        g0 = _rank_at_batch(pv, pr, 0.0) - _rank_at_batch(nv, nr, 0.0)
        h0 = _rank_at_batch(hv, hr, 0.0)          # missing-bin mass (M, F)

        # default right: missing joins the right child
        GL_dr, HL_dr = GL_excl, HL_excl
        GL_dl = GL_excl + g0[..., None]
        HL_dl = HL_excl + h0[..., None]
        left = jnp.stack([jnp.stack([GL_dr, HL_dr], -1),
                          jnp.stack([GL_dl, HL_dl], -1)], 3)  # (M,F,K,2,2)
        right = jnp.stack([G, H], -1)[:, None, None, None, :] - left
        GLs, HLs = left[..., 0], left[..., 1]
        GRs, HRs = right[..., 0], right[..., 1]
        root_gain = calc_gain(G, H, split_cfg)
        loss_chg = (calc_gain(GLs, HLs, split_cfg)
                    + calc_gain(GRs, HRs, split_cfg)
                    - root_gain[:, None, None, None])
        ok = (HLs >= split_cfg.min_child_weight) \
            & (HRs >= split_cfg.min_child_weight)
        # candidate bin b splits {<=b | >b}: a real boundary needs
        # b <= n_cuts[f]  (bins 1..n_cuts+1; j = b-1 must be < n_cuts)
        ok &= (cand[..., None] <= n_cuts[None, :, None, None])
        if fmask is not None:
            ok &= fmask[None, :, None, None]
        # forced missing-value direction (reference default_direction;
        # same masking as ops/split.find_best_splits)
        if split_cfg.default_direction == 1:    # forced left
            ok &= jnp.array([False, True])[None, None, None, :]
        elif split_cfg.default_direction == 2:  # forced right
            ok &= jnp.array([True, False])[None, None, None, :]
        loss_chg = jnp.where(ok, loss_chg, NEG)

        Kc = cand.shape[-1]                       # actual summary slots
        flat = loss_chg.reshape(M, F * Kc * 2)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        feature = (best // (Kc * 2)).astype(jnp.int32)
        kidx = ((best // 2) % Kc).astype(jnp.int32)
        default_left = (best % 2).astype(jnp.bool_)
        bsel = cand.reshape(M, F * Kc)[
            jnp.arange(M), feature * Kc + kidx].astype(jnp.int32)
        cut_index = jnp.maximum(bsel - 1, 0)      # left iff bin <= j+1 = b
        thr = cut_values[feature, jnp.clip(cut_index, 0,
                                           cut_values.shape[1] - 1)]
        return SplitDecision(best_gain, feature, cut_index, default_left,
                             thr, best_gain > RT_EPS,
                             jnp.zeros_like(feature))

    return finder


def _rank_at_batch(vals, ranks, q):
    """(M, F, K) summaries queried at scalar q -> (M, F)."""
    return jax.vmap(jax.vmap(
        lambda v, r: _rank_at(v, r, jnp.float32(q))))(vals, ranks)

"""Struct-of-arrays regression trees: growth and traversal.

Replaces the reference's pointer-y ``TreeModel``/``RegTree``
(``src/tree/model.h:26-567``) with fixed-shape tensors: a tree of
``max_depth`` D occupies a perfect binary layout of ``2**(D+1)-1`` nodes
(node g has children 2g+1 / 2g+2), each field its own array.  Growth is
level-by-level — the strategy of the reference's histogram updaters
(``updater_histmaker-inl.hpp:124-147``) — with every level one
histogram + argmax + partition step on device.

The ``hist_reduce`` hook is the collective seam: single-chip it is the
identity; the data-parallel path passes ``lax.psum`` over the mesh axis,
which is exactly where the reference called ``rabit`` Allreduce
(``histmaker-inl.hpp:343-346``; SURVEY.md §5.8).
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from xgboost_tpu.ops.histogram import (build_level_histogram,
                                       dequantize_hist, node_stats,
                                       stats_from_histogram)
from xgboost_tpu.ops.split import SplitConfig, calc_weight, find_best_splits


class TreeArrays(NamedTuple):
    """One regression tree (or a (T, ...) stack of them)."""
    feature: jax.Array       # (n_nodes,) int32, -1 if leaf/unused
    cut_index: jax.Array     # (n_nodes,) int32
    threshold: jax.Array     # (n_nodes,) f32 — raw-value cut (v < thr -> left)
    default_left: jax.Array  # (n_nodes,) bool
    is_leaf: jax.Array       # (n_nodes,) bool
    leaf_value: jax.Array    # (n_nodes,) f32 (eta-scaled)
    gain: jax.Array          # (n_nodes,) f32 loss_chg of the split (stat)
    sum_hess: jax.Array      # (n_nodes,) f32 node hessian sum (stat)

    @property
    def n_nodes(self) -> int:
        return self.feature.shape[-1]


class GrowConfig(NamedTuple):
    """Static configuration of the growth kernel."""
    split: SplitConfig
    max_depth: int
    n_bin: int               # histogram bins B (incl. missing bin 0)
    subsample: float = 1.0
    colsample_bytree: float = 1.0
    colsample_bylevel: float = 1.0
    hist_precision: str = "auto"  # auto | fp32 | bf16 | int8 | fixed
    # (named TrainParam; "fixed" = int32 fixed-point scatter — bitwise
    # deterministic across any data-mesh size, ops/histogram.FIXED_SCALE)
    # histogram subtraction: per parent, build only the SMALLER child's
    # histogram over row-compacted buffers and derive the sibling as
    # parent - small (the reference builds every node's histogram,
    # histmaker-inl.hpp:296-348; subtraction is the classic hist-method
    # optimization).  Dense TPU tiles process masked rows at full cost,
    # so the win requires the row compaction this flag also enables.
    hist_subtraction: bool = False
    # multi-root trees (reference TreeParam num_roots, data.h root_index):
    # the top ceil(log2 n_roots) levels of the perfect layout are root
    # slots; row i enters at node (2**d0 - 1) + root_index[i], matching
    # RegTree::GetLeafIndex(feat, root_id) semantics (model.h:534-543)
    n_roots: int = 1


class SplitDecision(NamedTuple):
    """Per-node chosen split for one level (hook-neutral: `feature` is in
    whatever id space the finder uses — local on one chip, global under
    column sharding — and `owner` names the shard holding the feature)."""
    gain: jax.Array          # (n_node,) f32
    feature: jax.Array       # (n_node,) int32
    cut_index: jax.Array     # (n_node,) int32
    default_left: jax.Array  # (n_node,) bool
    threshold: jax.Array     # (n_node,) f32 raw cut value
    valid: jax.Array         # (n_node,) bool
    owner: jax.Array         # (n_node,) int32 shard owning the feature
    # optional left-child (G, H) of the chosen split — finders that
    # provide them let the grower derive child node stats (terminal
    # level) instead of running a node_stats pass over all rows
    left_g: jax.Array = None
    left_h: jax.Array = None


def _wrap_best(best, cut_values) -> "SplitDecision":
    """BestSplit -> single-shard SplitDecision (threshold gather, local
    owner) — the one construction both histogram layouts share."""
    thr = cut_values[best.feature, best.cut_index]
    return SplitDecision(best.gain, best.feature, best.cut_index,
                         best.default_left, thr, best.valid,
                         jnp.zeros_like(best.feature),
                         best.left_g, best.left_h)


def _default_split_finder(hist, nst, n_cuts, cut_values, fmask, split_cfg):
    """Single-shard split finding: all features are local."""
    return _wrap_best(find_best_splits(hist, nst, n_cuts, split_cfg,
                                       fmask), cut_values)


def _onehot_select(table: jax.Array, idx: jax.Array) -> jax.Array:
    """``table[..., idx]`` via broadcast-compare (no gather): table
    (..., M) indexed by idx (..., N) -> (..., N); M is small."""
    M = table.shape[-1]
    ids = jnp.arange(M, dtype=jnp.int32)
    sel = idx[..., :, None] == ids
    tb = table[..., None, :]
    if table.dtype == jnp.bool_:
        return (sel & tb).any(axis=-1)
    return jnp.where(sel, tb, jnp.zeros((), table.dtype)).sum(axis=-1)


from jax.custom_batching import custom_vmap  # noqa: E402 (used below)


@custom_vmap
def table_lookup(table: jax.Array, idx: jax.Array) -> jax.Array:
    """Per-row lookup in a small per-node table: ``table[idx]``.

    Broadcast-compare select, NOT a gather: measured on v5e (round 3,
    1M rows), XLA's dynamic gather costs 0.6-7.5 ms per launch for
    16-1023-entry tables while the O(N*M) compare-select fuses to
    0.05-0.9 ms — gathers only win past ~1024 entries (deep trees),
    where the fallback below applies.  The vmap rule (ensemble axis of
    vmapped growth) makes the same choice for batched lookups.
    """
    if table.shape[-1] > 1024:
        return table[idx]
    return _onehot_select(table, idx)


@table_lookup.def_vmap
def _table_lookup_vmap(axis_size, in_batched, table, idx):
    tb, ib = in_batched
    table_b = table if tb else jnp.broadcast_to(
        table, (axis_size,) + table.shape)
    idx_b = idx if ib else jnp.broadcast_to(idx, (axis_size,) + idx.shape)
    if table_b.shape[-1] > 1024:
        # the O(N*M) compare stops paying for big tables (deep trees,
        # CPU backends); the batched gather is the lesser evil there
        return jnp.take_along_axis(table_b, idx_b, axis=-1), True
    return _onehot_select(table_b, idx_b), True


def bin_of_feature(binned: jax.Array, f_row: jax.Array) -> jax.Array:
    """Per-row bin id of a per-row feature: ``binned[r, f_row[r]]``.

    Selected with a broadcast compare + masked sum over (N, F) instead of
    ``take_along_axis``: dynamic lane gathers serialize on TPU (~16 ms per
    level at 1M x 28) while this is a fused VPU pass (~1 ms).  Out-of-range
    ``f_row`` yields bin 0 (missing)."""
    f_ids = jnp.arange(binned.shape[1], dtype=jnp.int32)
    sel = f_ids[None, :] == f_row[:, None]               # (N, F)
    return jnp.where(sel, binned.astype(jnp.int32), 0).sum(axis=1)


def _default_router(best: SplitDecision, node_of_row, binned):
    """Row go-left decision when the split feature's bins are local.

    The (n_node,)-table lookups are cheap in-graph when unbatched (a
    gather-free MXU formulation measured no faster end-to-end), but
    catastrophic as vmap-batched gathers — :func:`table_lookup` picks
    the right lowering per context.  Only `take_along_axis`-style
    dynamic LANE gathers always serialize on TPU, hence the
    broadcast-compare :func:`bin_of_feature`.
    """
    f_row = table_lookup(best.feature, node_of_row)
    j_row = table_lookup(best.cut_index, node_of_row)
    dl_row = table_lookup(best.default_left, node_of_row)
    b = bin_of_feature(binned, f_row)
    return jnp.where(b == 0, dl_row, b <= j_row + 1)


def _default_feat_sampler(key, rate, binned):
    return _sample_features(key, binned.shape[1], rate)


def _subtracted_level_hist(binned, gh_used, pos, n_node: int, cfg,
                           red, hist_parent, parent_split):
    """Level histogram via subtraction + row compaction.

    Per parent, only the child with FEWER rows is built; the sibling is
    ``parent - small``.  The built rows are compacted into a static
    N/2-row buffer so the histogram kernel touches ~half the rows per
    level (sum over parents of min(left, right) <= N/2).  Distributed:
    the small-child choice comes from psum'd counts, so every shard
    builds the same children; a shard whose LOCAL small-child rows
    overflow the buffer flips ALL shards to the plain full build
    (lax.cond on a psum'd flag — collective-safe).
    """
    from xgboost_tpu.ops.histogram import dequantize_hist, node_stats

    N, F = binned.shape
    B = cfg.n_bin
    # per-child ACTIVE-row counts (global under `red`): hessians can
    # mislead on weighted data and the N/2 capacity bound is on rows
    ones2 = jnp.broadcast_to(
        (pos >= 0)[:, None].astype(jnp.float32), (N, 2))
    counts = red(node_stats(ones2, pos, n_node))[:, 0]       # (n_node,)
    small_is_left = counts[0::2] <= counts[1::2]
    is_small = jnp.stack(
        [small_is_left, ~small_is_left], axis=1).reshape(-1)  # (n_node,)

    msk = (pos >= 0) & table_lookup(is_small, jnp.clip(pos, 0, n_node - 1))
    cap = max(256, -(-(N // 2) // 256) * 256)
    dest = jnp.where(msk, jnp.cumsum(msk.astype(jnp.int32)) - 1, cap)

    def subtract_build():
        b_small = jnp.zeros((cap, F), binned.dtype).at[dest].set(
            binned, mode="drop")
        gh_small = jnp.zeros((cap, 2), gh_used.dtype).at[dest].set(
            gh_used, mode="drop")
        pos_small = jnp.full(cap, -1, jnp.int32).at[dest].set(
            pos, mode="drop")
        from xgboost_tpu.ops.histogram import build_level_histogram
        hist_small = dequantize_hist(red(build_level_histogram(
            b_small, gh_small, pos_small, n_node, B, cfg.hist_precision)))
        # the small child's histogram per parent is the pair-sum (the
        # non-built sibling's slots are zero)
        small_of_parent = hist_small.reshape(
            n_node // 2, 2, F, B, 2).sum(axis=1)
        # children of NON-split (leaf) parents have no rows: without the
        # mask, sibling = parent - 0 would hand the parent's full mass
        # to a phantom node, diverging from the plain build
        sibling = jnp.where(parent_split[:, None, None, None],
                            hist_parent - small_of_parent, 0.0)
        sib_child = jnp.repeat(sibling, 2, axis=0)
        return jnp.where(is_small[:, None, None, None],
                         hist_small, sib_child)

    def full_build():
        from xgboost_tpu.ops.histogram import build_level_histogram
        return dequantize_hist(red(build_level_histogram(
            binned, gh_used, pos, n_node, B, cfg.hist_precision)))

    # the N/2 bound holds for GLOBAL counts; a skewed shard can still
    # overflow its local buffer, so reduce the local overflow flag and
    # (rarely) flip every shard to the plain build together
    local_over = jnp.sum(msk.astype(jnp.int32)) > cap
    any_over = red(local_over.astype(jnp.float32)[None])[0] > 0
    return jax.lax.cond(any_over, full_build, subtract_build)


def root_level(n_roots: int) -> int:
    """Depth of the level holding the root slots (0 for a single root)."""
    return max(n_roots - 1, 0).bit_length()


def tree_capacity(max_depth: int, n_roots: int = 1) -> int:
    return 2 ** (root_level(n_roots) + max_depth + 1) - 1


@functools.partial(jax.jit, static_argnames=(
    "cfg", "hist_reduce", "split_finder", "router", "feat_sampler"))
def grow_tree(key: jax.Array, binned: jax.Array, gh: jax.Array,
              cut_values: jax.Array, n_cuts: jax.Array, cfg: GrowConfig,
              row_valid: Optional[jax.Array] = None,
              hist_reduce: Callable[[jax.Array], jax.Array] = None,
              split_finder=None, router=None, feat_sampler=None,
              root: Optional[jax.Array] = None,
              binned_t: Optional[jax.Array] = None):
    """Grow one tree level-by-level.

    Args:
      key: PRNG key for row/column subsampling.
      binned: (N, F) bin ids (0 = missing); F may be a feature SHARD.
      gh: (N, 2) gradient pairs.
      cut_values: (F, C) padded raw cut values, n_cuts: (F,).
      row_valid: optional (N,) bool — rows that belong to this shard/set
        (padding rows excluded from both stats and leaf assignment).
      root: optional (N,) int32 per-row root slot in [0, cfg.n_roots)
        (reference BoosterInfo root_index, data.h:39-58); None = root 0.
      hist_reduce: collective reduction applied to every histogram and
        node-stat tensor (identity when None; psum over 'data' in DP mode).
      split_finder/router/feat_sampler: the collective seams for
        column-split training (parallel/colsplit.py); the defaults are
        the single-shard implementations.

    Returns (tree: TreeArrays, row_leaf: (N,) int32 global leaf node per
    row, row_val: (N,) f32 the row's leaf VALUE).  row_val is recorded
    AT PARKING TIME from the level's would-be leaf weights — the same
    numbers apply_level writes into leaf_value, so it bit-matches
    ``leaf_value[row_leaf]`` while replacing that post-growth
     127-entry per-row lookup (measured 0.84 ms/round at 1M rows —
    round-5 trace) with per-level selects that fuse into the routing
    pass.
    """
    N, F = binned.shape
    D = cfg.max_depth
    d0 = root_level(cfg.n_roots)  # growth starts at the root-slot level
    red = hist_reduce if hist_reduce is not None else (lambda x: x)
    default_finder = split_finder is None
    if split_finder is None:
        split_finder = _default_split_finder
    if router is None:
        router = _default_router
    if feat_sampler is None:
        feat_sampler = _default_feat_sampler

    key_rows, key_ftree, key_flevel = jax.random.split(key, 3)

    # row subsampling (reference TrainParam::subsample applied at gradient
    # level, updater_colmaker-inl.hpp:115-146): dropped rows contribute no
    # statistics but still flow to a leaf for the prediction cache.
    gh_used = gh
    if cfg.subsample < 1.0:
        keep = jax.random.uniform(key_rows, (N,)) < cfg.subsample
        gh_used = gh * keep[:, None].astype(gh.dtype)
    if row_valid is not None:
        gh_used = gh_used * row_valid[:, None].astype(gh.dtype)

    # column sampling bytree (colmaker-inl.hpp:148-160): boolean mask, no
    # replacement semantics approximated by per-feature bernoulli with a
    # guaranteed non-empty fallback.
    feat_mask_tree = feat_sampler(key_ftree, cfg.colsample_bytree, binned)

    tree = empty_tree(D, cfg.n_roots)

    # level-local position at depth d0; -1 = parked in a leaf.  With one
    # root this is all zeros; multi-root rows start in their root slot
    # (the reference initializes position from root_index,
    # updater_colmaker-inl.hpp:115-146 / basemaker InitData).
    if root is not None and d0 > 0:
        pos = jnp.clip(root.astype(jnp.int32), 0, cfg.n_roots - 1)
    else:
        pos = jnp.zeros(N, jnp.int32)
    if row_valid is not None:
        pos = jnp.where(row_valid, pos, -1)
    row_leaf = jnp.zeros(N, jnp.int32)
    row_val = jnp.zeros(N, jnp.float32)
    hist_prev = None
    prev = None  # (best, nst, do_split) of the previous level

    # once-per-tree histogram precompute: the bins transpose and (int8
    # mode) gradient quantization hoisted out of the level loop —
    # re-materializing them per level cost ~9 ms/round at 1M x 28
    # (round-4 trace; ops/histogram.prepare_hist).  binned_t, when the
    # caller provides it (learner entries), is the RESIDENT
    # pre-transposed u8 operand: zero per-round transpose AND none of
    # the per-pallas-call layout copies an in-graph transpose incurs
    from xgboost_tpu.ops.histogram import prepare_hist
    hist_prep = prepare_hist(binned, gh_used, cfg.n_bin,
                             cfg.hist_precision, binned_t=binned_t)
    # kernel-NATIVE histogram layout (F, B, 2, n_node): the split
    # finder consumes the kernel's own output order, skipping the
    # per-level relayout transpose (~0.47 ms/round at 1M x 28 —
    # round-5 trace).  Default finder only (the colsplit/skmaker seams
    # speak the standard layout), single node tile, no subtraction.
    use_native = (default_finder and hist_prep is not None
                  and not cfg.hist_subtraction)

    for depth in range(d0, d0 + D + 1):
        n_node = 1 << depth
        base = n_node - 1  # global index of first node at this level

        if depth == d0 + D:
            # terminal level: everything still active becomes a leaf.
            # Node stats DERIVE from the parent's chosen split (left
            # child = winner's left sums, right = parent - left) when
            # the finder provides them — a full node_stats pass over
            # the rows costs ~4.4 ms at 1M rows (v5e, round 3)
            if prev is not None and prev[0].left_g is not None:
                p_best, p_nst, p_split = prev
                gl = jnp.where(p_split, p_best.left_g, 0.0)
                hl = jnp.where(p_split, p_best.left_h, 0.0)
                gr = jnp.where(p_split, p_nst[:, 0] - p_best.left_g, 0.0)
                hr = jnp.where(p_split, p_nst[:, 1] - p_best.left_h, 0.0)
                nst = jnp.stack(
                    [jnp.stack([gl, gr], 1).reshape(-1),
                     jnp.stack([hl, hr], 1).reshape(-1)], axis=1)
            else:
                nst = dequantize_hist(red(node_stats(
                    gh_used, pos, n_node,
                    cfg.hist_precision)))  # (n_node, 2)
            make_leaf = jnp.ones(n_node, jnp.bool_)
            best = None
        else:
            native = use_native and n_node <= 64
            if cfg.hist_subtraction and hist_prev is not None:
                hist = _subtracted_level_hist(binned, gh_used, pos,
                                              n_node, cfg, red, hist_prev,
                                              prev[2])
            else:
                hist = dequantize_hist(
                    red(build_level_histogram(binned, gh_used, pos,
                                              n_node, cfg.n_bin,
                                              cfg.hist_precision,
                                              prep=hist_prep,
                                              native=native)))
            hist_prev = hist if cfg.hist_subtraction else None
            # node totals fall out of the histogram (bin sums of any one
            # feature) — saves a per-level pass over all rows
            from xgboost_tpu.ops.histogram import stats_from_histogram_native
            nst = (stats_from_histogram_native(hist) if native
                   else stats_from_histogram(hist))
            fmask = feat_mask_tree
            if cfg.colsample_bylevel < 1.0:
                fmask = fmask & feat_sampler(
                    jax.random.fold_in(key_flevel, depth),
                    cfg.colsample_bylevel, binned)
            if native:
                from xgboost_tpu.ops.split import find_best_splits_native
                best = _wrap_best(
                    find_best_splits_native(hist, nst, n_cuts,
                                            cfg.split, fmask),
                    cut_values)
            else:
                best = split_finder(hist, nst, n_cuts, cut_values, fmask,
                                    cfg.split)
            # cannot_split (param.h:174): too little hessian mass to split
            can_try = nst[:, 1] >= 2.0 * cfg.split.min_child_weight
            do_split = best.valid & can_try
            make_leaf = ~do_split
            prev = (best, nst, do_split)

        tree = apply_level(tree, depth, nst, best, make_leaf, cfg.split)
        # the level's would-be leaf weights (same expression apply_level
        # writes — CSE'd, bitwise identical): parked rows record their
        # value here instead of a post-growth leaf_value[row_leaf] pass
        leaf_w = calc_weight(nst[:, 0], nst[:, 1], cfg.split) \
            * cfg.split.eta

        # park rows whose node became a leaf; route the rest to children
        active = pos >= 0
        node_of_row = jnp.clip(pos, 0, n_node - 1)
        if best is None:
            # terminal level: make_leaf is constant-true — no lookup
            row_is_leaf = active
            val_row = table_lookup(leaf_w, node_of_row)
        elif router is _default_router and n_node <= 1024:
            # ONE (N, n_node) one-hot compare serves all five per-node
            # channels (routing feature/cut/default + park flag + leaf
            # value): XLA multi-output-fuses the masked sums over the
            # shared compare, replacing 4 separate lookup fusions
            ids = jnp.arange(n_node, dtype=jnp.int32)
            sel = node_of_row[:, None] == ids             # (N, M)

            def pick(v):
                return jnp.where(sel, v[None, :], 0.0).sum(axis=1)
            f_row = pick(best.feature.astype(jnp.float32)
                         ).astype(jnp.int32)
            j1_row = pick(best.cut_index.astype(jnp.float32) + 1.0)
            dl_row = pick(best.default_left.astype(jnp.float32)) != 0.0
            leaf_row = pick(make_leaf.astype(jnp.float32)) != 0.0
            val_row = pick(leaf_w)
            row_is_leaf = active & leaf_row
            b = bin_of_feature(binned, f_row)
            go_left = jnp.where(b == 0, dl_row,
                                b.astype(jnp.float32) <= j1_row)
        else:
            row_is_leaf = active & table_lookup(make_leaf, node_of_row)
            val_row = table_lookup(leaf_w, node_of_row)
            go_left = router(best, node_of_row, binned)
        row_leaf = jnp.where(row_is_leaf, base + pos, row_leaf)
        row_val = jnp.where(row_is_leaf, val_row, row_val)
        if best is not None:
            new_pos = 2 * pos + (~go_left).astype(jnp.int32)
            pos = jnp.where(active & ~row_is_leaf, new_pos, -1)

    return tree, row_leaf, row_val


def apply_level(tree: TreeArrays, depth: int, nst: jax.Array,
                best: Optional[SplitDecision], make_leaf: jax.Array,
                split_cfg) -> TreeArrays:
    """Write one level's decisions into the tree arrays (shared by the
    in-memory, distributed and paged growers)."""
    n_node = 1 << depth
    base = n_node - 1
    # node occupancy: a level node is "live" iff some ancestor path made
    # it; detect via sum_hess>0 OR it is the root.  Empty nodes get
    # is_leaf=False and are unreachable, which is fine.
    live = (nst[:, 1] > 0.0) | (jnp.arange(n_node) == 0) if depth == 0 \
        else (nst[:, 1] > 0.0)

    # the would-be leaf weight is recorded for EVERY live node (not just
    # leaves): the prune updater turns split nodes back into leaves and
    # needs their weight (reference keeps base_weight in RTreeNodeStat)
    leaf_w = calc_weight(nst[:, 0], nst[:, 1], split_cfg) * split_cfg.eta
    idx = base + jnp.arange(n_node)
    tree = tree._replace(
        sum_hess=tree.sum_hess.at[idx].set(nst[:, 1]),
        is_leaf=tree.is_leaf.at[idx].set(make_leaf & live),
        leaf_value=tree.leaf_value.at[idx].set(leaf_w),
    )
    if best is not None:
        keep_split = ~make_leaf
        tree = tree._replace(
            feature=tree.feature.at[idx].set(
                jnp.where(keep_split, best.feature, -1)),
            cut_index=tree.cut_index.at[idx].set(best.cut_index),
            threshold=tree.threshold.at[idx].set(best.threshold),
            default_left=tree.default_left.at[idx].set(best.default_left),
            gain=tree.gain.at[idx].set(
                jnp.where(keep_split, best.gain, 0.0)),
        )
    return tree


def empty_tree(max_depth: int, n_roots: int = 1) -> TreeArrays:
    """All-unused tree arrays for a depth-``max_depth`` perfect layout."""
    n_total = tree_capacity(max_depth, n_roots)
    return TreeArrays(
        feature=jnp.full(n_total, -1, jnp.int32),
        cut_index=jnp.zeros(n_total, jnp.int32),
        threshold=jnp.zeros(n_total, jnp.float32),
        default_left=jnp.zeros(n_total, jnp.bool_),
        is_leaf=jnp.zeros(n_total, jnp.bool_),
        leaf_value=jnp.zeros(n_total, jnp.float32),
        gain=jnp.zeros(n_total, jnp.float32),
        sum_hess=jnp.zeros(n_total, jnp.float32),
    )


def _sample_features(key: jax.Array, F: int, rate: float) -> jax.Array:
    if rate >= 1.0:
        return jnp.ones(F, jnp.bool_)
    mask = jax.random.uniform(key, (F,)) < rate
    # never allow an empty feature set (reference resamples until non-empty)
    fallback = jnp.zeros(F, jnp.bool_).at[
        jax.random.randint(key, (), 0, F)].set(True)
    return jnp.where(mask.any(), mask, fallback)


# ---------------------------------------------------------------- traversal

def _traverse_one(tree: TreeArrays, binned: jax.Array, max_depth: int,
                  root: Optional[jax.Array] = None, n_roots: int = 1):
    """Leaf index per row for one tree on binned data.

    Matches reference RegTree::GetLeafIndex / GetNext (model.h:534-566)
    including missing-value default direction; with ``root`` (the
    per-row root_index, data.h:39-58) traversal starts at that root
    slot instead of node 0.

    Level-LOCAL like the grower: at depth d a row can only sit in one
    of 2^d nodes, so the per-node lookups compare against a STATIC
    SLICE of the tree arrays (2^d wide) instead of the full perfect
    layout — sliced lookups total ~5 * n_nodes compare-selects per
    tree where full-table lookups cost ~5 * n_nodes * depth (measured
    6.3 s -> see PROFILE.md for 1M rows x 100 depth-6 trees).  All
    five channels share one (N, 2^d) compare, as in growth.
    """
    N = binned.shape[0]
    d0 = root_level(n_roots)
    # level-local position within depth level d0 + d; parked rows keep
    # their GLOBAL leaf index in `leaf_node` and pos = -1
    if n_roots > 1 and root is not None:
        pos = jnp.clip(root.astype(jnp.int32), 0, n_roots - 1)
    else:
        pos = jnp.zeros_like(binned[:, 0], dtype=jnp.int32)
    leaf_node = jnp.zeros(N, jnp.int32)
    for d in range(d0, d0 + max_depth + 1):
        n_node = 1 << d
        base = n_node - 1
        sl = slice(base, base + n_node)
        active = pos >= 0
        node = jnp.clip(pos, 0, n_node - 1)
        if n_node <= 1024:
            ids = jnp.arange(n_node, dtype=jnp.int32)
            sel = node[:, None] == ids

            def pick(v):
                return jnp.where(sel, v[None, :], 0.0).sum(axis=1)
            f_row = pick(tree.feature[sl].astype(jnp.float32)
                         ).astype(jnp.int32)
            is_leaf_row = pick(tree.is_leaf[sl].astype(jnp.float32)) \
                != 0.0
        else:
            # very deep levels: compare-select stops paying (see
            # table_lookup) — per-level gathers on the slices
            def pick(v):
                return table_lookup(v, node)
            f_row = pick(tree.feature[sl])
            is_leaf_row = pick(tree.is_leaf[sl])
        stop = active & (is_leaf_row | (f_row < 0) | (d == d0 + max_depth))
        leaf_node = jnp.where(stop, base + pos, leaf_node)
        if d == d0 + max_depth:
            break
        if n_node <= 1024:
            j1_row = pick(tree.cut_index[sl].astype(jnp.float32) + 1.0)
            dl_row = pick(tree.default_left[sl].astype(jnp.float32)) \
                != 0.0
        else:
            j1_row = pick(tree.cut_index[sl]).astype(jnp.float32) + 1.0
            dl_row = pick(tree.default_left[sl])
        b = bin_of_feature(binned, jnp.maximum(f_row, 0))
        go_left = jnp.where(b == 0, dl_row,
                            b.astype(jnp.float32) <= j1_row)
        new_pos = 2 * pos + (~go_left).astype(jnp.int32)
        pos = jnp.where(active & ~stop, new_pos, -1)
    return leaf_node


def padded_tree_count(T: int, tree_chunk: int) -> int:
    """Padded ensemble size of the chunked traversal for ``T`` trees.

    The ladder bounds compilation count for GROWING ensembles while
    keeping padded-tree waste near zero for the small stacks the
    incremental per-round margin update traverses:

      - ``T <= tree_chunk``: next power of two >= T, capped at
        ``tree_chunk`` (a 1-tree round update pads to 1, not to a full
        chunk; the cap keeps a non-power-of-two chunk's promised vmap
        width — T=12 at chunk 12 pads to 12, not 16);
      - ``T > tree_chunk``: next multiple of ``tree_chunk``.

    Distinct padded sizes for T in [1, k*chunk] total at most
    ``log2(chunk) + k`` — the fixed compile budget the bounded-compile
    test pins (tests/test_predict_chunk.py)."""
    if tree_chunk <= 1:
        return T
    if T <= tree_chunk:
        return min(1 << max(T - 1, 0).bit_length(), tree_chunk)
    return -(-T // tree_chunk) * tree_chunk


def predict_chunk_layout(T: int, tree_chunk: int):
    """(T_padded, chunk_size, n_chunks) of the chunked traversal —
    shared by the traversal itself and by serving/observability code
    attributing per-chunk cost.  Below the chunk the whole (power-of-
    two-padded, chunk-capped) ensemble is one chunk."""
    if tree_chunk <= 1:
        return T, 1, T
    T_pad = padded_tree_count(T, tree_chunk)
    C = T_pad if T <= tree_chunk else tree_chunk
    return T_pad, C, T_pad // C


def pad_predict_stack(stack: TreeArrays, tree_group: jax.Array,
                      tree_chunk: int):
    """Pad a (T, ...) ensemble stack to the :func:`padded_tree_count`
    ladder with zero-leaf-value trees (feature -1 = immediate leaf at
    the root, contributing exactly 0 — and the traversal core skips
    them via ``n_valid`` anyway).

    Returns ``(stack_padded, group_padded, n_valid)``.  This is EAGER
    glue deliberately kept OUTSIDE the jitted traversal core: padding
    inside the jit would key the compiled program on the raw T and
    recompile the whole traversal per ensemble size; out here, growing
    T costs only byte-copy concat ops while the heavy program compiles
    once per ladder rung (tests/test_predict_chunk.py pins the
    budget)."""
    T = int(stack.feature.shape[0])
    T_pad = padded_tree_count(T, tree_chunk)
    if T_pad == T:
        return stack, tree_group, T

    def pad(x, fill=0):
        return jnp.concatenate(
            [x, jnp.full((T_pad - T,) + x.shape[1:], fill, x.dtype)])
    stack = stack._replace(
        **{f: pad(getattr(stack, f), -1 if f == "feature" else 0)
           for f in TreeArrays._fields})
    return stack, pad(tree_group), T


def _chunk_leaves(chunk: TreeArrays, binned, max_depth, root, n_roots):
    """(C, N) leaf indices of one tree chunk: ``_traverse_one`` vmapped
    over the tree axis.  The per-level one-hot compares batch into
    (C, N, 2^d) fused compare-select-sums — the same lowering that made
    vmapped ensemble GROWTH beat sequential launches (PROFILE.md round
    3: table_lookup's custom_vmap rule; 6-tree growth 305 -> 70 ms)."""
    return jax.vmap(
        lambda tr: _traverse_one(tr, binned, max_depth, root, n_roots)
    )(chunk)


@functools.partial(jax.jit, static_argnames=("max_depth", "n_group",
                                             "n_roots"))
def _predict_margin_scan(stack: TreeArrays, tree_group: jax.Array,
                         binned: jax.Array, base: jax.Array,
                         max_depth: int, n_group: int,
                         root: Optional[jax.Array] = None,
                         n_roots: int = 1) -> jax.Array:
    """Sequential ``lax.scan`` over trees — the pre-chunking traversal,
    kept as the A/B baseline and the ``tree_chunk<=1`` path."""
    N = binned.shape[0]

    def body(margin, tg):
        tree, group = tg
        leaf = _traverse_one(tree, binned, max_depth, root, n_roots)
        contrib = table_lookup(tree.leaf_value, leaf)
        margin = margin + contrib[:, None] * jax.nn.one_hot(
            group, n_group, dtype=margin.dtype)
        return margin, None

    margin0 = jnp.broadcast_to(base, (N, n_group)).astype(jnp.float32)
    margin, _ = jax.lax.scan(body, margin0, (stack, tree_group))
    return margin


@functools.partial(jax.jit, static_argnames=("max_depth", "n_group",
                                             "n_roots", "tree_chunk"))
def _predict_margin_chunked(stack: TreeArrays, tree_group: jax.Array,
                            n_valid: jax.Array, binned: jax.Array,
                            base: jax.Array, max_depth: int, n_group: int,
                            root: Optional[jax.Array], n_roots: int,
                            tree_chunk: int) -> jax.Array:
    """Chunked tree-parallel traversal core.  ``stack`` is ALREADY
    padded to a ``tree_chunk`` multiple (:func:`pad_predict_stack`), so
    the compiled program is keyed on the ladder rung, not the raw
    ensemble size; ``n_valid`` (the real tree count) is a TRACED
    scalar, so growing within a rung never retraces.

    Bit-identity with the scan: contributions accumulate IN TREE ORDER
    through the same ``margin + contrib * one_hot`` expression (the
    per-tree one-hot compare-selects are exact — a single nonzero term
    summed over zeros), and padded trees leave the margin untouched via
    ``where(valid, updated, margin)`` rather than adding 0.0 (which
    would flip a -0.0 margin cell to +0.0)."""
    N = binned.shape[0]
    T_pad = stack.feature.shape[0]
    C = tree_chunk                 # layout-derived; always divides T_pad
    n_chunks = T_pad // C
    margin = jnp.broadcast_to(base, (N, n_group)).astype(jnp.float32)

    chunks = jax.tree.map(
        lambda x: x.reshape((n_chunks, C) + x.shape[1:]), stack)
    groups = tree_group.reshape(n_chunks, C)
    valid = (jnp.arange(T_pad, dtype=jnp.int32)
             < n_valid).reshape(n_chunks, C)

    def body(m, cgv):
        chunk, gs, vs = cgv
        leaves = _chunk_leaves(chunk, binned, max_depth, root, n_roots)
        contribs = jax.vmap(table_lookup)(chunk.leaf_value, leaves)

        def acc(mm, tgv):
            contrib, group, ok = tgv
            upd = mm + contrib[:, None] * jax.nn.one_hot(
                group, n_group, dtype=mm.dtype)
            return jnp.where(ok, upd, mm), None
        m, _ = jax.lax.scan(acc, m, (contribs, gs, vs))
        return m, None

    margin, _ = jax.lax.scan(body, margin, (chunks, groups, valid))
    return margin


def predict_margin_binned(stack: TreeArrays, tree_group: jax.Array,
                          binned: jax.Array, base: jax.Array,
                          max_depth: int, n_group: int,
                          root: Optional[jax.Array] = None,
                          n_roots: int = 1,
                          tree_chunk: int = 0) -> jax.Array:
    """Sum of leaf values over a (T, n_nodes) stacked ensemble.

    ``tree_chunk > 1`` selects the chunked TREE-PARALLEL traversal:
    the ensemble pads to the :func:`padded_tree_count` ladder with
    zero-leaf-value trees, ``tree_chunk`` trees traverse at once under
    ``vmap`` (each level one batched compare-select instead of a
    per-tree chain of dependent launches — the PROFILE.md round-3
    vmapped-growth result applied to inference), and per-tree leaf
    contributions reduce into the (N, n_group) margin in tree order —
    bit-identical to the sequential scan (tests/test_predict_chunk.py).
    One compilation serves every ensemble size on the same ladder rung
    (``recompile_guard``-enforced).

    ``tree_chunk <= 1`` keeps the original scan over trees
    (``XGBTPU_PREDICT_TREE_CHUNK=0`` forces it end to end).  Returns
    (N, n_group) margins.
    """
    if tree_chunk <= 1:
        return _predict_margin_scan(stack, tree_group, binned, base,
                                    max_depth, n_group, root, n_roots)
    _, C, _ = predict_chunk_layout(int(stack.feature.shape[0]),
                                   tree_chunk)
    stack, tree_group, n_valid = pad_predict_stack(stack, tree_group,
                                                   tree_chunk)
    return _predict_margin_chunked(stack, tree_group, jnp.int32(n_valid),
                                   binned, base, max_depth, n_group,
                                   root, n_roots, C)


# ---------------------------------------------------- fused quantize+traverse

def _quantize_in_graph(X: jax.Array, cut_values: jax.Array) -> jax.Array:
    """Device quantization as a traceable sub-graph: the EXACT expression
    of :func:`binning.bin_dense_device` (one function, imported — not a
    copy), so the fused program's bin ids are bit-identical to the
    two-step path's by construction.  Raw f32 rows in (NaN = missing),
    small-int bin ids out; the binned matrix exists only as an XLA
    intermediate — it never materializes host-side."""
    from xgboost_tpu.binning import bin_dense_device
    return bin_dense_device(X, cut_values)


@functools.partial(jax.jit, static_argnames=("max_depth", "n_group",
                                             "n_roots"))
def _predict_margin_fused_scan(stack: TreeArrays, tree_group: jax.Array,
                               X: jax.Array, cut_values: jax.Array,
                               base: jax.Array, max_depth: int,
                               n_group: int,
                               root: Optional[jax.Array] = None,
                               n_roots: int = 1) -> jax.Array:
    binned = _quantize_in_graph(X, cut_values)
    return _predict_margin_scan.__wrapped__(stack, tree_group, binned,
                                            base, max_depth, n_group,
                                            root, n_roots)


@functools.partial(jax.jit, static_argnames=("max_depth", "n_group",
                                             "n_roots", "tree_chunk"))
def _predict_margin_fused_chunked(stack: TreeArrays, tree_group: jax.Array,
                                  n_valid: jax.Array, X: jax.Array,
                                  cut_values: jax.Array, base: jax.Array,
                                  max_depth: int, n_group: int,
                                  root: Optional[jax.Array], n_roots: int,
                                  tree_chunk: int) -> jax.Array:
    binned = _quantize_in_graph(X, cut_values)
    return _predict_margin_chunked.__wrapped__(
        stack, tree_group, n_valid, binned, base, max_depth, n_group,
        root, n_roots, tree_chunk)


def predict_margin_fused(stack: TreeArrays, tree_group: jax.Array,
                         X: jax.Array, cut_values: jax.Array,
                         base: jax.Array, max_depth: int, n_group: int,
                         root: Optional[jax.Array] = None,
                         n_roots: int = 1,
                         tree_chunk: int = 0) -> jax.Array:
    """FUSED quantize+traverse: raw f32 feature rows (NaN = missing) go
    cut-compare → bin ids → margins inside ONE jitted program.

    The transfer-wall companion of :func:`predict_margin_binned` (round
    7): a one-off prediction uploads raw f32 blocks and never
    materializes the binned matrix outside the program — no second
    device buffer, no extra launch boundary, and on hosts where the
    upload dominates (PROFILE.md) the quantize+traverse cost hides
    under the NEXT block's upload (learner's prefetch pipeline).

    Bit-parity contract: the quantize sub-graph IS
    ``binning.bin_dense_device`` (imported, not re-derived) and the
    traversal cores are the two-step path's own (``__wrapped__`` of the
    same jitted functions), so margins are bit-identical to
    quantize-then-:func:`predict_margin_binned` on the same rows
    (tests/test_predict_fused.py).  Same ladder/padding discipline:
    compiled programs are keyed on the ladder rung, not the raw T."""
    if tree_chunk <= 1:
        return _predict_margin_fused_scan(stack, tree_group, X, cut_values,
                                          base, max_depth, n_group, root,
                                          n_roots)
    _, C, _ = predict_chunk_layout(int(stack.feature.shape[0]),
                                   tree_chunk)
    stack, tree_group, n_valid = pad_predict_stack(stack, tree_group,
                                                   tree_chunk)
    return _predict_margin_fused_chunked(stack, tree_group,
                                         jnp.int32(n_valid), X, cut_values,
                                         base, max_depth, n_group, root,
                                         n_roots, C)


@functools.partial(jax.jit, static_argnames=("max_depth", "n_roots"))
def _predict_leaf_scan(stack: TreeArrays, binned: jax.Array,
                       max_depth: int, root: Optional[jax.Array] = None,
                       n_roots: int = 1) -> jax.Array:
    def body(_, tree):
        return None, _traverse_one(tree, binned, max_depth, root, n_roots)
    _, leaves = jax.lax.scan(body, None, stack)
    return leaves.T


@functools.partial(jax.jit, static_argnames=("max_depth", "n_roots",
                                             "tree_chunk"))
def _predict_leaf_chunked(stack: TreeArrays, binned: jax.Array,
                          max_depth: int, root: Optional[jax.Array],
                          n_roots: int, tree_chunk: int) -> jax.Array:
    """(T_pad, N) leaves of a padded stack, chunked like the margin
    core (padded columns are sliced off by the caller)."""
    T_pad = stack.feature.shape[0]
    C = tree_chunk                 # layout-derived; always divides T_pad
    n_chunks = T_pad // C
    chunks = jax.tree.map(
        lambda x: x.reshape((n_chunks, C) + x.shape[1:]), stack)

    def body(_, chunk):
        return None, _chunk_leaves(chunk, binned, max_depth, root,
                                   n_roots)
    _, leaves = jax.lax.scan(body, None, chunks)     # (n_chunks, C, N)
    return leaves.reshape(T_pad, -1)


def predict_leaf_binned(stack: TreeArrays, binned: jax.Array,
                        max_depth: int, root: Optional[jax.Array] = None,
                        n_roots: int = 1,
                        tree_chunk: int = 0) -> jax.Array:
    """(N, T) leaf node index per tree (reference PredictLeaf,
    gbtree-inl.hpp:355-385).  ``tree_chunk > 1`` traverses chunks of
    trees in parallel (same ladder/padding as
    :func:`predict_margin_binned`); leaf indices are integers, so
    parity with the scan is trivial."""
    if tree_chunk <= 1:
        return _predict_leaf_scan(stack, binned, max_depth, root, n_roots)
    T = int(stack.feature.shape[0])
    _, C, _ = predict_chunk_layout(T, tree_chunk)
    group = jnp.zeros(T, jnp.int32)          # layout only; groups unused
    stack, _, _ = pad_predict_stack(stack, group, tree_chunk)
    leaves = _predict_leaf_chunked(stack, binned, max_depth, root,
                                   n_roots, C)
    return leaves[:T].T

"""Elastic supervisor: hold fleet utilization inside a target band.

The second placer loop (SERVING.md "Autonomous placement"): where the
:class:`~xgboost_tpu.placer.controller.PlacementController` decides
WHERE models live, this decides HOW MANY replicas exist.  The signal
is fleet utilization — router in-flight over nominal capacity
(``placer_replica_slots`` per replica), EWMA-smoothed — and the policy
is a band state machine:

- ``steady``     — utilization inside ``[util_low, util_high]``.
- ``scale_up``   — above the band and below ``max_replicas``: spawn
  one replica through the launcher; it registers through the normal
  lease path and starts taking traffic when healthy.
- ``scale_down`` — below the band and above ``min_replicas``: drain
  one replica.  The drain deregisters AT DRAIN START (the replica's
  SIGTERM drain path, PR 7) so the router stops dispatching before the
  first 503 — no request is lost.
- ``hold``       — a rollout/canary soak is in flight
  (``rollout_in_progress`` on the router's ``/healthz``): the fleet
  size is pinned, because a drain mid-soak could remove the canary's
  pinned path-groups and invalidate the gate.  The withheld resize is
  counted (``xgbtpu_placer_resize_holds_total``).

One resize per ``cooldown_sec`` — a burst walks the fleet up one
replica at a time instead of thrashing.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Callable, Optional

from xgboost_tpu.obs import event
from xgboost_tpu.obs.metrics import placer_metrics, swallowed_error


class ElasticSupervisor:
    """Band controller over a replica launcher.

    The launcher contract is three callables, so tests drive a fake
    and ``tools/launch_fleet.py --supervise`` passes its
    ``FleetLauncher`` methods: ``spawn_fn()`` starts one replica,
    ``drain_fn()`` drains one (deregister-at-drain-start) and returns
    an identifier or None, ``count_fn()`` is the current replica
    count.  ``probe_fn`` (tests) overrides the router ``/healthz``
    probe."""

    def __init__(self, router_url: str,
                 spawn_fn: Callable[[], object],
                 drain_fn: Callable[[], Optional[object]],
                 count_fn: Callable[[], int],
                 min_replicas: int = 1, max_replicas: int = 8,
                 util_low: float = 0.2, util_high: float = 0.75,
                 util_alpha: float = 0.3, replica_slots: int = 8,
                 cooldown_sec: float = 10.0, http_timeout: float = 5.0,
                 probe_fn: Optional[Callable[[], dict]] = None):
        self.router_url = router_url.rstrip("/")
        self.spawn_fn = spawn_fn
        self.drain_fn = drain_fn
        self.count_fn = count_fn
        self.min_replicas = max(int(min_replicas), 1)
        self.max_replicas = max(int(max_replicas), self.min_replicas)
        self.util_low = float(util_low)
        self.util_high = float(util_high)
        self.util_alpha = float(util_alpha)
        self.replica_slots = max(int(replica_slots), 1)
        self.cooldown_sec = float(cooldown_sec)
        self.http_timeout = float(http_timeout)
        self.probe_fn = probe_fn or self._probe_router
        self.util = 0.0                 # EWMA utilization
        self.state = "steady"
        self._rollout_active = False
        self._last_resize = 0.0         # monotonic; 0 = never
        self.metrics = placer_metrics()

    # ------------------------------------------------------------- signal
    def _probe_router(self) -> dict:
        with urllib.request.urlopen(self.router_url + "/healthz",
                                    timeout=self.http_timeout) as r:
            return json.loads(r.read())

    def observe(self) -> float:
        """Fold one router probe into the utilization EWMA."""
        st = self.probe_fn()
        members = max(int(st.get("members") or 0), 1)
        inflight = float(st.get("inflight") or 0.0)
        raw = inflight / float(self.replica_slots * members)
        self.util += self.util_alpha * (raw - self.util)
        self.metrics.fleet_util.set(round(self.util, 4))
        self._rollout_active = bool(st.get("rollout_in_progress"))
        return self.util

    # --------------------------------------------------------------- tick
    def tick(self) -> dict:
        """One band evaluation; returns ``{"state": ..., "util": ...,
        "replicas": ...}``."""
        try:
            self.observe()
        except (OSError, ValueError) as e:
            # router unreachable: freeze the fleet size — resizing
            # blind could drain the last healthy replica
            swallowed_error("placer.elastic.probe", e)
            self.state = "steady"
            return {"state": self.state, "util": round(self.util, 4),
                    "replicas": self.count_fn(), "error": str(e)}
        n = int(self.count_fn())
        now = time.monotonic()
        cooled = (self._last_resize == 0.0
                  or now - self._last_resize >= self.cooldown_sec)
        want_up = self.util > self.util_high and n < self.max_replicas
        want_down = self.util < self.util_low and n > self.min_replicas
        if (want_up or want_down) and self._rollout_active:
            # resize-during-rollout rule: the soak's path-groups are
            # pinned — defer until the gate settles
            self.state = "hold"
            self.metrics.resize_holds.inc()
            event("placer.resize_hold", util=round(self.util, 4),
                  replicas=n)
        elif want_up and cooled:
            self.state = "scale_up"
            self.spawn_fn()
            self._last_resize = now
            n += 1
            self.metrics.resizes.inc("up")
            event("placer.scale_up", util=round(self.util, 4),
                  replicas=n)
        elif want_down and cooled:
            self.state = "scale_down"
            victim = self.drain_fn()
            if victim is not None:
                self._last_resize = now
                n -= 1
                self.metrics.resizes.inc("down")
                event("placer.scale_down", util=round(self.util, 4),
                      replicas=n, victim=str(victim))
        else:
            self.state = "steady"
        self.metrics.replicas_target.set(n)
        return {"state": self.state, "util": round(self.util, 4),
                "replicas": n}

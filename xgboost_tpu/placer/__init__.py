"""xgboost_tpu.placer — autonomous catalog placement + elastic fleet.

The serving-side control plane (SERVING.md "Autonomous placement";
ROADMAP "Autonomous placement + elastic fleet"): where the fleet
(xgboost_tpu.fleet) serves whatever manifests operators hand-wrote,
this package DECIDES — two cooperating loops that close the gap
between "a catalog of N models" and hands-off operation:

- :class:`PlacementController` (:mod:`~xgboost_tpu.placer.controller`):
  consumes the router's observed per-tenant load (``xgbtpu_tenant_*``
  counters), the per-replica device budgets advertised in heartbeats,
  and the membership table; computes a target assignment of
  models->replicas (greedy bin-pack, replication floor raised for hot
  tenants, :class:`~xgboost_tpu.fleet.membership.HashRing` anchoring so
  a rebalance moves only the tenants that must move); converges the
  fleet by pushing manifest deltas (``POST /-/catalog`` +
  ``/-/reload``) to replica admin surfaces.  The target plan is
  CRC-snapshotted so a SIGKILL'd placer resumes its last plan, and a
  router-side single-holder lease keeps standby placers from fighting.
- :class:`ElasticSupervisor` (:mod:`~xgboost_tpu.placer.elastic`):
  holds fleet utilization (in-flight / slots EWMA) inside a target
  band by spawning/draining replica processes through a launcher
  (``tools/launch_fleet.py --supervise``); drains deregister at drain
  start so no request is lost, and an in-flight rollout pins the
  fleet size so a resize mid-soak cannot invalidate the canary gate.

Quickstart::

    python -m xgboost_tpu task=placer \
        placer_router_url=http://127.0.0.1:8000 \
        placer_catalog='a=ma.bin,b=mb.bin'

or, elastic fleet + placement in one command::

    python tools/launch_fleet.py --model m.bin --replicas 2 --supervise
"""

from xgboost_tpu.placer.controller import PlacementController, run_placer
from xgboost_tpu.placer.elastic import ElasticSupervisor

__all__ = [
    "PlacementController",
    "ElasticSupervisor",
    "run_placer",
]
